"""Remote FIB agent client + spawner.

The reference runs route programming in a standalone native binary
(`platform_linux`, openr/platform/LinuxPlatformMain.cpp) that the Fib
module reaches over thrift (openr/fib/Fib.cpp:697 createFibClient). Here
the native agent is native/platform/onl_fib_agent.cpp (built into
openr_tpu/_native/onl_fib_agent) speaking newline-delimited JSON, and
RemoteFibService is the FibService-shaped client the Fib module plugs in.

Wire route shapes:
  unicast: {"dest": "10.0.0.0/24", "nexthops": [nh...]}
  mpls:    {"label": 100100, "nexthops": [nh...]}
  nh:      {"via": addr|"", "iface": name|"", "weight": int,
            "mpls_action": 0-3 (onl_mpls_action), "labels": [int...]}
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
from typing import Dict, List, Optional, Tuple

from openr_tpu.types import (
    IpPrefix,
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    UnicastRoute,
)
from openr_tpu.platform.fib_service import FibService, PlatformError

_ACTION_TO_WIRE = {
    MplsActionCode.PUSH: 1,
    MplsActionCode.SWAP: 2,
    MplsActionCode.PHP: 3,
    MplsActionCode.POP_AND_LOOKUP: 3,
}
_WIRE_TO_ACTION = {
    1: MplsActionCode.PUSH,
    2: MplsActionCode.SWAP,
    3: MplsActionCode.PHP,
}

AGENT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "_native",
    "onl_fib_agent",
)


def spawn_agent(
    port: int = 0, dryrun: bool = False, agent_path: Optional[str] = None
) -> Tuple[subprocess.Popen, int]:
    """Start the native agent; returns (process, bound port)."""
    path = agent_path or AGENT_PATH
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not built — run `make -C native` first"
        )
    args = [path, "--port", str(port)]
    if dryrun:
        args.append("--dryrun")
    proc = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True
    )
    line = proc.stdout.readline().strip()
    if not line.startswith("LISTENING "):
        proc.kill()
        raise PlatformError(f"agent failed to start: {line!r}")
    return proc, int(line.split()[1])


def _nh_to_wire(nh: NextHop) -> Dict:
    action, labels = 0, []
    if nh.mpls_action is not None:
        action = _ACTION_TO_WIRE[nh.mpls_action.action]
        if nh.mpls_action.action == MplsActionCode.SWAP:
            labels = [nh.mpls_action.swap_label]
        elif nh.mpls_action.action == MplsActionCode.PUSH:
            labels = list(nh.mpls_action.push_labels)
    via = nh.address
    if via in ("0.0.0.0", "::"):
        via = ""
    return {
        "via": via,
        "iface": nh.iface or "",
        "weight": max(1, nh.weight),
        "mpls_action": action,
        "labels": labels,
    }


def _nh_from_wire(d: Dict) -> NextHop:
    action = _WIRE_TO_ACTION.get(d.get("mpls_action", 0))
    mpls = None
    if action is not None:
        labels = d.get("labels") or []
        if action == MplsActionCode.SWAP:
            mpls = MplsAction(action, swap_label=labels[0] if labels else None)
        elif action == MplsActionCode.PUSH:
            mpls = MplsAction(action, push_labels=tuple(labels))
        else:
            mpls = MplsAction(action)
    return NextHop(
        address=d.get("via", ""),
        iface=d.get("iface") or None,
        weight=d.get("weight", 0),
        mpls_action=mpls,
    )


class RemoteFibService(FibService):
    """FibService client speaking the native agent's JSON protocol."""

    def __init__(self, host: str = "127.0.0.1", port: int = 60100) -> None:
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._next_id = 0
        self._lock = asyncio.Lock()

    async def _connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:
                pass
            self._reader = self._writer = None

    async def _call(self, method: str, **params):
        async with self._lock:
            # (re)connect lazily so agent restarts surface as one failed
            # call and then recover — Fib's keepAliveCheck handles the rest
            if self._writer is None:
                try:
                    await self._connect()
                except OSError as exc:
                    raise PlatformError(f"agent unreachable: {exc}") from exc
            self._next_id += 1
            req = {"id": self._next_id, "method": method, "params": params}
            try:
                self._writer.write(json.dumps(req).encode() + b"\n")
                await self._writer.drain()
                line = await self._reader.readline()
            except OSError as exc:
                await self.close()
                raise PlatformError(f"agent io error: {exc}") from exc
            if not line:
                await self.close()
                raise PlatformError("agent closed connection")
            resp = json.loads(line)
            if resp.get("error") is not None:
                raise PlatformError(resp["error"])
            return resp.get("result")

    # -- FibService ------------------------------------------------------

    async def alive_since(self) -> int:
        return await self._call("aliveSince")

    async def add_unicast_routes(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        await self._call(
            "addUnicastRoutes",
            client=client_id,
            routes=[
                {
                    "dest": str(r.dest),
                    "nexthops": [_nh_to_wire(nh) for nh in r.nexthops],
                }
                for r in routes
            ],
        )

    async def delete_unicast_routes(
        self, client_id: int, prefixes: List[IpPrefix]
    ) -> None:
        await self._call(
            "deleteUnicastRoutes",
            client=client_id,
            prefixes=[str(p) for p in prefixes],
        )

    async def sync_fib(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        await self._call(
            "syncFib",
            client=client_id,
            routes=[
                {
                    "dest": str(r.dest),
                    "nexthops": [_nh_to_wire(nh) for nh in r.nexthops],
                }
                for r in routes
            ],
        )

    async def add_mpls_routes(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        await self._call(
            "addMplsRoutes",
            client=client_id,
            routes=[
                {
                    "label": r.top_label,
                    "nexthops": [_nh_to_wire(nh) for nh in r.nexthops],
                }
                for r in routes
            ],
        )

    async def delete_mpls_routes(
        self, client_id: int, labels: List[int]
    ) -> None:
        await self._call(
            "deleteMplsRoutes", client=client_id, labels=list(labels)
        )

    async def sync_mpls_fib(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        await self._call(
            "syncMplsFib",
            client=client_id,
            routes=[
                {
                    "label": r.top_label,
                    "nexthops": [_nh_to_wire(nh) for nh in r.nexthops],
                }
                for r in routes
            ],
        )

    async def get_route_table_by_client(
        self, client_id: int
    ) -> List[UnicastRoute]:
        rows = await self._call("getRouteTableByClient", client=client_id)
        return [
            UnicastRoute(
                dest=IpPrefix(r["dest"]),
                nexthops=tuple(_nh_from_wire(nh) for nh in r["nexthops"]),
            )
            for r in rows
        ]

    async def get_mpls_route_table_by_client(
        self, client_id: int
    ) -> List[MplsRoute]:
        rows = await self._call("getMplsRouteTableByClient", client=client_id)
        return [
            MplsRoute(
                top_label=r["label"],
                nexthops=tuple(_nh_from_wire(nh) for nh in r["nexthops"]),
            )
            for r in rows
        ]

    async def get_neighbors(self, family: int = 0):
        """Kernel neighbor table via the agent (empty in dryrun mode)."""
        from openr_tpu.nl import Neighbor

        rows = await self._call("getNeighbors", family=family)
        return [
            Neighbor(
                ifindex=r["ifindex"],
                dest=r["dest"],
                lladdr=r["lladdr"],
                family=r["family"],
                state=r["state"],
                is_reachable=bool(r["is_reachable"]),
            )
            for r in rows
        ]
