"""Admission control for expensive ctrl RPCs.

The ctrl server runs on the same asyncio loop as the convergence path
(Decision rebuilds, Fib programming). Expensive calls — `runTeOptimize`
(a full gradient-descent optimization), `getRouteDbComputed` (an SPF
solve when asked for another node's perspective), `getConvergenceReport`
(full span/rollup aggregation) — cost milliseconds to seconds each, and
heavy client traffic used to queue them back to back ahead of route
programming with no bound at all.

`AdmissionController` puts a weighted admission queue in front of them:

  - **Concurrency cap**: each method carries a cost weight; at most
    `capacity` units run at once. Excess callers queue.
  - **Bounded wait + typed rejection**: a caller waits at most
    `max_wait_s` for a slot; a full queue or an expired wait raises
    `ServerBusyError`, which the ctrl server maps to a typed
    `error_kind: "server_busy"` response with a `retry_after_ms` hint —
    clients back off instead of piling on.
  - **Fairness**: waiters queue per client id and slots are granted
    round-robin across clients, with a per-client pending cap — one
    client hammering `runTeOptimize` cannot occupy every queue slot, and
    the bounded total means expensive work admitted ahead of the
    convergence path is always O(capacity + queue), never O(clients).

The controller never moves work off the loop — admitted handlers run
where they always ran (loop-serialized with the module owners, which is
what the thread-ownership analyzer's `# analysis: shared` handovers
assume). What it guarantees is that the *total* expensive work in front
of route programming is bounded and fairly shared; async handlers (used
by tests to model slow optimizations) are awaited under the slot without
blocking the loop at all.

Fault point: `ctrl.admission.dispatch` fires before each admitted call
(docs/Robustness.md) — injected failures exercise the typed-error path.
"""

from __future__ import annotations

import asyncio
import collections
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, Optional

from openr_tpu.testing.faults import fault_point
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin

# default cost weights of the guarded ctrl methods: runTeOptimize is a
# whole optimization loop, the other two are one solve / one aggregation
DEFAULT_COSTS: Dict[str, int] = {
    "runTeOptimize": 2,
    "getRouteDbComputed": 1,
    "getConvergenceReport": 1,
    # an on-demand profiling window perturbs every dispatch it covers:
    # admission-bounded like the other expensive calls
    "startProfile": 1,
    # journal replays re-fold the LSDB and re-run the CPU oracle
    # (docs/Journal.md): expensive like a computed-route-db request
    "explainRoute": 1,
    "getRibDiff": 1,
    "verifyJournalReplay": 1,
}


class ServerBusyError(RuntimeError):
    """Typed server-busy rejection (wire shape: error_kind=server_busy)."""

    error_kind = "server_busy"

    def __init__(
        self, method: str, reason: str, retry_after_ms: int
    ) -> None:
        super().__init__(
            f"server busy: {method} {reason} "
            f"(retry after {retry_after_ms}ms)"
        )
        self.method = method
        self.reason = reason
        self.retry_after_ms = retry_after_ms


@dataclass
class AdmissionConfig:
    """Admission knobs (config `stream_config` section)."""

    capacity: int = 2  # concurrent cost units
    max_wait_s: float = 2.0  # bounded queue wait per caller
    max_queue: int = 16  # total queued waiters
    max_queue_per_client: int = 4  # fairness: per-client pending cap
    costs: Dict[str, int] = field(
        default_factory=lambda: dict(DEFAULT_COSTS)
    )


class _Waiter:
    __slots__ = ("client", "cost", "future")

    def __init__(self, client: str, cost: int, future: asyncio.Future):
        self.client = client
        self.cost = cost
        self.future = future


class AdmissionController(CountersMixin, HistogramsMixin):
    """Weighted fair admission queue (one per daemon, `ctrl_admission`
    monitor module — `ctrl.admission.*` counters/histograms)."""

    def __init__(self, config: Optional[AdmissionConfig] = None) -> None:
        self.config = config or AdmissionConfig()
        self._inflight = 0
        # per-client FIFO queues, granted round-robin via _rotation
        self._waiters: Dict[str, Deque[_Waiter]] = {}
        self._rotation: Deque[str] = collections.deque()
        self._ensure_counters()
        self._ensure_histograms()

    # -- public ---------------------------------------------------------

    def guards(self, method: str) -> bool:
        return method in self.config.costs

    async def run(
        self, method: str, client: str, fn: Callable[[], Any]
    ) -> Any:
        """Admit, run, release. `fn` may return a value or a coroutine
        (awaited under the slot). Raises ServerBusyError on rejection."""
        cost = self.config.costs.get(method, 1)
        t0 = time.perf_counter()
        await self._acquire(method, client, cost)
        self._observe(
            "ctrl.admission.wait_ms", (time.perf_counter() - t0) * 1e3
        )
        self._bump("ctrl.admission.admitted")
        t_run = time.perf_counter()
        try:
            # named fault seam: injected dispatch failures exercise the
            # per-request error isolation without touching the modules
            fault_point("ctrl.admission.dispatch", method)
            result = fn()
            if asyncio.iscoroutine(result):
                result = await result
            return result
        finally:
            self._observe(
                "ctrl.admission.run_ms", (time.perf_counter() - t_run) * 1e3
            )
            self._release(cost)

    def stats(self) -> Dict[str, Any]:
        return {
            "capacity": self.config.capacity,
            "in_flight": self._inflight,
            "queued": sum(len(q) for q in self._waiters.values()),
            "max_wait_s": self.config.max_wait_s,
            "costs": dict(self.config.costs),
            "counters": dict(self._ensure_counters()),
        }

    # -- internals ------------------------------------------------------

    def _retry_hint_ms(self) -> int:
        return int(self.config.max_wait_s * 1e3)

    async def _acquire(self, method: str, client: str, cost: int) -> None:
        queued_total = sum(len(q) for q in self._waiters.values())
        if queued_total == 0 and (
            self._inflight + cost <= self.config.capacity
        ):
            # fast path: capacity free and nobody queued ahead
            self._inflight += cost
            self._gauge()
            return
        mine = self._waiters.get(client)
        if (
            mine is not None
            and len(mine) >= self.config.max_queue_per_client
        ):
            # checked before the global bound: "YOU are over your cap"
            # beats "the queue is full" for a client deciding how to
            # back off (fairness attribution)
            self._bump("ctrl.admission.rejected_client_cap")
            raise ServerBusyError(
                method,
                f"client has {len(mine)} requests queued",
                self._retry_hint_ms(),
            )
        if queued_total >= self.config.max_queue:
            self._bump("ctrl.admission.rejected_queue_full")
            raise ServerBusyError(
                method, "admission queue full", self._retry_hint_ms()
            )
        mine = self._waiters.setdefault(client, collections.deque())
        if client not in self._rotation:
            self._rotation.append(client)
        waiter = _Waiter(
            client, cost, asyncio.get_running_loop().create_future()
        )
        mine.append(waiter)
        self._bump("ctrl.admission.queued")
        self._gauge()
        try:
            await asyncio.wait_for(waiter.future, self.config.max_wait_s)
        except asyncio.TimeoutError:
            self._discard(waiter)
            self._bump("ctrl.admission.timeouts")
            # the timed-out waiter may have blocked grantable capacity
            self._grant()
            raise ServerBusyError(
                method,
                f"no slot within {self.config.max_wait_s}s",
                self._retry_hint_ms(),
            )
        except BaseException:
            granted = waiter.future.done() and not waiter.future.cancelled()
            self._discard(waiter)
            if granted:
                # the grant raced our cancellation: give the slot back
                self._release(cost)
            raise
        # granted: _grant() already charged our cost to _inflight

    def _discard(self, waiter: _Waiter) -> None:
        queue = self._waiters.get(waiter.client)
        if queue is not None:
            try:
                queue.remove(waiter)
            except ValueError:
                pass
            if not queue:
                self._waiters.pop(waiter.client, None)
        self._gauge()

    def _release(self, cost: int) -> None:
        self._inflight = max(0, self._inflight - cost)
        self._grant()
        self._gauge()

    def _grant(self) -> None:
        """Round-robin across client queues while capacity lasts — the
        fairness rule: after a client is granted, the rotation pointer
        moves past it (and PERSISTS across grant rounds), so a heavy
        client's queued burst yields to every other client between its
        own grants and cannot starve anyone."""
        attempts = len(self._rotation)
        while self._rotation and attempts > 0:
            client = self._rotation[0]
            queue = self._waiters.get(client)
            if not queue:
                self._rotation.popleft()
                attempts = len(self._rotation)
                continue
            head = queue[0]
            if self._inflight + head.cost > self.config.capacity:
                # head doesn't fit: give the other clients a look, but
                # a full fruitless scan ends the round (position intact:
                # rotating len(rotation) times is the identity)
                self._rotation.rotate(-1)
                attempts -= 1
                continue
            queue.popleft()
            if not queue:
                self._waiters.pop(client, None)
                self._rotation.popleft()
            else:
                self._rotation.rotate(-1)
            self._inflight += head.cost
            if not head.future.done():
                head.future.set_result(None)
            else:  # cancelled while granting: return the slot
                self._inflight -= head.cost
            attempts = len(self._rotation)
        self._gauge()

    def _gauge(self) -> None:
        counters = self._ensure_counters()
        counters["ctrl.admission.in_flight_last"] = self._inflight
        counters["ctrl.admission.queued_last"] = sum(
            len(q) for q in self._waiters.values()
        )
