"""STREAM_SMOKE tier-1 smoke (the streaming sibling of FLEET/FAULT/
TRACE/SOAK/RESTART_SMOKE): the shared-encode fan-out invariant proven
end-to-end over real ctrl sockets — N subscribers in ONE
filter-equivalence class must cost exactly ONE class encode per
dispatched frame, with every other member reusing the shared bytes.

Sequence:

  1. a small VirtualNetwork line converges; N `subscribeKvStore`
     subscribers (same area, no prefix/originator filters — one filter
     class by construction) attach to one node and drain their
     snapshots (snapshots are per-subscriber private encodes and never
     touch the class meters);
  2. one mid-link flap runs fail→restore→reconverge; every delta frame
     the flap floods through the subscribed node's fan-out is filtered
     once, encoded once (`encode_classes`), and reused N-1 times
     (`encode_class_hits`);
  3. the contract: class encodes == frames each subscriber saw, class
     hits == (N-1) x class encodes, zero coalesces/resyncs (the queues
     are sized for the burst), and the node reports exactly one live
     kv filter class while the cohort is attached.

Sizes scale via STREAM_SMOKE_NODES / STREAM_SMOKE_SUBS; returns a
summary dict.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict


def run_stream_smoke() -> Dict[str, Any]:
    from openr_tpu.ctrl.client import CtrlClient
    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    n = max(3, int(os.environ.get("STREAM_SMOKE_NODES", "3")))
    subs = max(2, int(os.environ.get("STREAM_SMOKE_SUBS", "8")))
    mid = n // 2
    host = "n0"

    async def body() -> Dict[str, Any]:
        net = VirtualNetwork()
        for i in range(n):
            net.add_node(
                f"n{i}",
                loopback_prefix=f"10.{i}.0.0/24",
                # roomy bounds: the invariant under test is the encode
                # count, so no subscriber may overflow into coalesce or
                # resync (those re-enter the private-encode path)
                config_overrides={
                    "stream_config": {
                        "subscriber_max_pending": 256,
                        "coalesce_budget": 256,
                    }
                },
            )
        await net.start_all()
        for i in range(n - 1):
            net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

        def converged() -> bool:
            for i in range(n):
                got = set(net.wrappers[f"n{i}"].programmed_prefixes())
                want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
                if not want.issubset(got):
                    return False
            return True

        def partitioned() -> bool:
            left = net.wrappers[host].programmed_prefixes()
            return f"10.{n - 1}.0.0/24" not in left

        counts = [
            {"snapshot": 0, "delta": 0, "resync": 0} for _ in range(subs)
        ]
        clients: list = []
        tasks: list = []

        async def watch(client, idx: int) -> None:
            try:
                async for frame in client.subscribe(
                    "subscribeKvStore", area="0", client=f"smoke-{idx}"
                ):
                    kind = frame.get("type")
                    if kind in counts[idx]:
                        counts[idx][kind] += 1
            except Exception:
                pass

        sm = net.wrappers[host].daemon.stream_manager
        try:
            await wait_until(converged, timeout=60.0)
            port = net.wrappers[host].ctrl_port
            for i in range(subs):
                client = await CtrlClient("127.0.0.1", port).connect()
                clients.append(client)
                tasks.append(
                    asyncio.get_running_loop().create_task(
                        watch(client, i)
                    )
                )
            # every subscriber drained its snapshot (private encodes)
            await wait_until(
                lambda: all(c["snapshot"] == 1 for c in counts),
                timeout=30.0,
            )
            live = sm.stats()
            counters0 = dict(sm._ensure_counters())

            net.fail_link(
                f"n{mid}", f"if{mid}r", f"n{mid + 1}", f"if{mid + 1}l"
            )
            await wait_until(partitioned, timeout=60.0)
            net.restore_link(
                f"n{mid}", f"if{mid}r", f"n{mid + 1}", f"if{mid + 1}l"
            )
            await wait_until(converged, timeout=60.0)

            # drain to quiescence: the meters and every subscriber's
            # delta count must be read in ONE sync block (no await in
            # between) after a stable window, or in-flight deliveries
            # would skew the exact-count assertions below
            async def settle():
                while True:
                    pre = dict(sm._ensure_counters())
                    await asyncio.sleep(0.4)
                    post = dict(sm._ensure_counters())
                    snap = [c["delta"] for c in counts]
                    if (
                        snap[0] > 0
                        and all(s == snap[0] for s in snap)
                        and pre.get("ctrl.stream.published")
                        == post.get("ctrl.stream.published")
                        and pre.get("ctrl.stream.delivered")
                        == post.get("ctrl.stream.delivered")
                    ):
                        return post, snap[0]

            counters1, frames_per_sub = await asyncio.wait_for(
                settle(), timeout=30.0
            )
        finally:
            for task in tasks:
                task.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
            for client in clients:
                await client.close()
            await net.stop_all()

        def delta(name: str) -> int:
            return counters1.get(name, 0) - counters0.get(name, 0)

        class_encodes = delta("ctrl.stream.encode_classes")
        class_hits = delta("ctrl.stream.encode_class_hits")
        summary = {
            "nodes": n,
            "subscribers": subs,
            "filter_classes_live": live["kv_filter_classes"],
            "frames_per_subscriber": frames_per_sub,
            "class_encodes": class_encodes,
            "class_hits": class_hits,
            "coalesced": delta("ctrl.stream.coalesced"),
            "resyncs": delta("ctrl.stream.resyncs"),
            "counts": counts,
        }
        # -- the smoke's contract ----------------------------------------
        # one filter class while the whole cohort is attached
        assert live["kv_filter_classes"] == 1, summary
        assert live["kv_subscribers"] == subs, summary
        assert live["shared_encode"] is True, summary
        # nothing overflowed: the invariant below would not hold otherwise
        assert summary["coalesced"] == 0, summary
        assert summary["resyncs"] == 0, summary
        assert all(c["resync"] == 0 for c in counts), summary
        # the tentpole invariant: exactly ONE class encode per frame,
        # shared with every other member of the class
        assert frames_per_sub > 0, summary
        assert class_encodes == frames_per_sub, summary
        assert class_hits == (subs - 1) * class_encodes, summary
        return summary

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()
