"""Stream frame codecs: newline-JSON (default/debug) and length-prefixed
binary, with the body/envelope split the shared-encode fan-out rides on.

Every stream frame the ctrl server sends is an envelope (request id,
frame type, sequence number) around a **body** — the serialized KvStore
publication or route-update lists. The body is the expensive part and is
identical for every subscriber in a filter-equivalence class, so it is
encoded here as standalone bytes that a `SharedFrame` can memoize once
per class and every class member can splice into its own envelope with
plain buffer writes (writev-style — no per-subscriber re-serialization,
no body copy; docs/Streaming.md "Shared-encode fan-out").

Two codecs produce interchangeable frames:

  - ``json`` — the wire stays exactly what it always was: one
    ``{"id": N, "stream": {...}}`` line per frame. The envelope splice is
    byte-identical to ``json.dumps`` of the whole frame (same default
    separators, same key order), so a shared-path frame and a privately
    encoded frame cannot be told apart on the wire.
  - ``binary`` — length-prefixed frames negotiated per connection at
    subscribe time (docs/Streaming.md "Codec negotiation"): a JSON ack
    line ``{"id": N, "codec": "binary"}``, then ``u32 length`` +
    ``u8 frame-type`` + ``u32 seq`` + body. Bodies carry raw value bytes
    (no base64) and struct-packed fields; decode reproduces the exact
    JSON payload dict, so consumers stay codec-agnostic.

Snapshot/resync/coalesced frames are per-subscriber state: they use the
same body encoders privately and re-enter the shared path only when
their class re-converges on live deltas.
"""

from __future__ import annotations

import base64
import json
import struct
from typing import Any, Dict, List, Optional, Tuple

from openr_tpu.types import Publication

CODEC_JSON = "json"
CODEC_BINARY = "binary"
CODECS = (CODEC_JSON, CODEC_BINARY)

# binary frame types (u8 in the frame header)
FT_SNAPSHOT = 1
FT_DELTA = 2
FT_RESYNC = 3
_FT_BY_KIND = {"snapshot": FT_SNAPSHOT, "delta": FT_DELTA, "resync": FT_RESYNC}
_KIND_BY_FT = {v: k for k, v in _FT_BY_KIND.items()}

# binary frame header: payload length (excl. itself), frame type, seq
_HDR = struct.Struct("!IBI")
# per-value metadata: flags, version, ttl, ttl_version, hash, value length
_VAL = struct.Struct("!Bqqqqi")
_F_HAS_VALUE = 1
_F_HAS_HASH = 2

# hard cap on one binary frame payload, mirroring the JSON _LINE_LIMIT
MAX_FRAME = 256 * 1024 * 1024


def normalize_codec(name: Optional[str]) -> str:
    """Clamp a client-requested codec to a supported one. Unknown names
    fall back to JSON (graceful degradation, never an error)."""
    return CODEC_BINARY if name == CODEC_BINARY else CODEC_JSON


# ---------------------------------------------------------------------------
# body encoders — the per-class (shared) serialization work
# ---------------------------------------------------------------------------


def _pub_to_json(pub: Publication) -> Dict[str, Any]:
    """Subscriber-facing publication dict (node_ids/tobe_updated_keys are
    peer-sync internals, intentionally omitted — ctrl/server.py keeps the
    same shape)."""
    from openr_tpu.kvstore import wire

    return {
        "area": pub.area,
        "key_vals": wire.key_vals_to_json(pub.key_vals),
        "expired_keys": list(pub.expired_keys),
    }


def _pack_str(out: List[bytes], text: str) -> None:
    raw = text.encode()
    out.append(struct.pack("!H", len(raw)))
    out.append(raw)


class _Cursor:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        chunk = self.data[self.pos : self.pos + n]
        if len(chunk) != n:
            raise ValueError("truncated binary frame body")
        self.pos += n
        return chunk

    def unpack(self, st: struct.Struct) -> tuple:
        return st.unpack(self.take(st.size))

    def read_str(self) -> str:
        (n,) = self.unpack(struct.Struct("!H"))
        return self.take(n).decode()


def encode_kv_body(pub: Publication, codec: str) -> bytes:
    """Serialize one publication as a standalone frame body."""
    if codec == CODEC_JSON:
        return json.dumps(_pub_to_json(pub)).encode()
    out: List[bytes] = []
    _pack_str(out, pub.area)
    out.append(struct.pack("!I", len(pub.key_vals)))
    for key, v in pub.key_vals.items():
        _pack_str(out, key)
        flags = (_F_HAS_VALUE if v.value is not None else 0) | (
            _F_HAS_HASH if v.hash is not None else 0
        )
        raw = v.value or b""
        out.append(
            _VAL.pack(
                flags,
                v.version,
                v.ttl,
                v.ttl_version,
                v.hash if v.hash is not None else 0,
                len(raw),
            )
        )
        _pack_str(out, v.originator_id)
        out.append(raw)
    out.append(struct.pack("!I", len(pub.expired_keys)))
    for key in pub.expired_keys:
        _pack_str(out, key)
    return b"".join(out)


def decode_kv_body(body: bytes) -> Dict[str, Any]:
    """Binary kv body -> the exact `_pub_to_json` dict shape (value bytes
    back to base64, None-ness restored) — codec-agnostic consumers."""
    cur = _Cursor(body)
    area = cur.read_str()
    (nkeys,) = cur.unpack(struct.Struct("!I"))
    key_vals: Dict[str, Any] = {}
    for _ in range(nkeys):
        key = cur.read_str()
        flags, version, ttl, ttl_version, vhash, vlen = cur.unpack(_VAL)
        originator = cur.read_str()
        raw = cur.take(vlen)
        key_vals[key] = {
            "version": version,
            "originator_id": originator,
            "value": (
                base64.b64encode(raw).decode()
                if flags & _F_HAS_VALUE
                else None
            ),
            "ttl": ttl,
            "ttl_version": ttl_version,
            "hash": vhash if flags & _F_HAS_HASH else None,
        }
    (nexpired,) = cur.unpack(struct.Struct("!I"))
    expired = [cur.read_str() for _ in range(nexpired)]
    return {"area": area, "key_vals": key_vals, "expired_keys": expired}


def route_fields_from_update(update) -> Dict[str, Any]:
    """DecisionRouteUpdate -> the four route-list fields of a delta frame
    (b64 serializer blobs, the shape docs/Streaming.md documents)."""
    from openr_tpu.utils import serializer

    def blob(obj) -> str:
        return base64.b64encode(serializer.dumps(obj)).decode()

    return {
        "unicast_to_update": [
            blob(e.to_unicast_route()) for e in update.unicast_routes_to_update
        ],
        "unicast_to_delete": [
            str(p) for p in update.unicast_routes_to_delete
        ],
        "mpls_to_update": [
            blob(e.to_mpls_route()) for e in update.mpls_routes_to_update
        ],
        "mpls_to_delete": [
            int(label) for label in update.mpls_routes_to_delete
        ],
    }


def encode_route_body(fields: Dict[str, Any], codec: str) -> bytes:
    """Serialize the four route-list fields as a standalone body. JSON
    bodies keep the object braces — the envelope splice strips them."""
    if codec == CODEC_JSON:
        return json.dumps(fields).encode()
    out: List[bytes] = []
    for field in ("unicast_to_update", "mpls_to_update"):
        blobs = fields[field]
        out.append(struct.pack("!I", len(blobs)))
        for b64_text in blobs:
            raw = base64.b64decode(b64_text)
            out.append(struct.pack("!I", len(raw)))
            out.append(raw)
    out.append(struct.pack("!I", len(fields["unicast_to_delete"])))
    for prefix in fields["unicast_to_delete"]:
        _pack_str(out, prefix)
    out.append(struct.pack("!I", len(fields["mpls_to_delete"])))
    for label in fields["mpls_to_delete"]:
        out.append(struct.pack("!i", int(label)))
    return b"".join(out)


def decode_route_body(body: bytes) -> Dict[str, Any]:
    cur = _Cursor(body)
    u32 = struct.Struct("!I")
    updates: Dict[str, List[str]] = {}
    for field in ("unicast_to_update", "mpls_to_update"):
        (n,) = cur.unpack(u32)
        blobs = []
        for _ in range(n):
            (blen,) = cur.unpack(u32)
            blobs.append(base64.b64encode(cur.take(blen)).decode())
        updates[field] = blobs
    (n,) = cur.unpack(u32)
    unicast_delete = [cur.read_str() for _ in range(n)]
    (n,) = cur.unpack(u32)
    mpls_delete = [
        cur.unpack(struct.Struct("!i"))[0] for _ in range(n)
    ]
    return {
        "unicast_to_update": updates["unicast_to_update"],
        "unicast_to_delete": unicast_delete,
        "mpls_to_update": updates["mpls_to_update"],
        "mpls_to_delete": mpls_delete,
    }


# ---------------------------------------------------------------------------
# envelopes — the cheap per-subscriber splice around a shared body
# ---------------------------------------------------------------------------


def kv_frame_segments(
    codec: str,
    req_id: int,
    kind: str,
    seq: int,
    area: str,
    body: bytes,
    legacy: bool = False,
) -> List[bytes]:
    """Write-ready segments for one kv frame: a per-subscriber envelope
    prefix, the (possibly shared) body, a suffix. The JSON splice is
    byte-identical to json.dumps of the whole frame."""
    if codec == CODEC_BINARY:
        return [_HDR.pack(len(body) + 5, _FT_BY_KIND[kind], seq), body]
    if legacy:
        prefix = '{"id": %d, "stream": ' % req_id
        return [prefix.encode(), body, b"}\n"]
    prefix = '{"id": %d, "stream": {"type": "%s", "seq": %d, "area": %s, "pub": ' % (
        req_id,
        kind,
        seq,
        json.dumps(area),
    )
    return [prefix.encode(), body, b"}}\n"]


def route_frame_segments(
    codec: str, req_id: int, kind: str, seq: int, body: bytes
) -> List[bytes]:
    """Write-ready segments for one route frame. The JSON body keeps its
    braces; the splice strips them with a zero-copy memoryview."""
    if codec == CODEC_BINARY:
        return [_HDR.pack(len(body) + 5, _FT_BY_KIND[kind], seq), body]
    prefix = '{"id": %d, "stream": {"type": "%s", "seq": %d, ' % (
        req_id,
        kind,
        seq,
    )
    return [prefix.encode(), memoryview(body)[1:-1], b"}}\n"]


def decode_binary_frame(payload: bytes, stream: str) -> Dict[str, Any]:
    """One received binary frame payload (everything after the length
    word) -> the JSON-equivalent stream payload dict."""
    ftype, seq = struct.unpack("!BI", payload[:5])
    kind = _KIND_BY_FT[ftype]
    body = payload[5:]
    if stream == "kv":
        pub = decode_kv_body(body)
        return {"type": kind, "seq": seq, "area": pub["area"], "pub": pub}
    fields = decode_route_body(body)
    return {"type": kind, "seq": seq, **fields}


def frame_header_info(header: bytes) -> Tuple[int, int]:
    """(payload length, total header size) for one binary frame."""
    (length,) = struct.unpack("!I", header)
    if length > MAX_FRAME:
        raise ValueError(f"binary frame too large ({length} bytes)")
    return length, 4


def frame_kind_seq(payload: bytes) -> Tuple[str, int]:
    """(kind, seq) straight off a binary frame payload, body left
    unparsed — the fast-consumer path
    (`CtrlClient.subscribe(decode=False)`, docs/Streaming.md)."""
    ftype, seq = struct.unpack("!BI", payload[:5])
    return _KIND_BY_FT[ftype], seq
