"""Streaming control plane: delta subscriptions with bounded fan-out.

The reference Open/R serves its control plane as *streams* —
`subscribeKvStoreFilter` (OpenrCtrlHandler.h:207-211) pushes LSDB deltas
to subscribers instead of re-snapshotting per request. This module is the
fan-out layer between the daemon's module queues and the ctrl server's
per-connection stream handlers:

  - `StreamManager` owns ONE reader per source `ReplicateQueue` (KvStore
    publications, Decision route updates) and fans each item out to every
    registered subscriber with a **non-blocking** `offer()` — publication
    never waits on any client.
  - Fan-out encode cost is O(filter-equivalence-classes), not
    O(subscribers): subscribers with equal filters (KvStore: area +
    key-prefixes + originators; routes: unfiltered, one class) are
    grouped, each source item is filtered once per class, and the
    resulting `SharedFrame` memoizes its serialized body once per codec —
    per-subscriber work is a queue append plus an envelope splice and
    buffer write in the connection task (docs/Streaming.md
    "Shared-encode fan-out"; `shared_encode: false` restores the
    historical per-subscriber re-encode path for measurement).
  - Each subscriber holds a **bounded** frame queue. When a slow client
    falls `max_pending` frames behind, the queue is coalesced: KvStore
    deltas merge per key (newest value wins, expiry/update cancel each
    other), route deltas merge per prefix/label. If the *merged* delta
    still exceeds `coalesce_budget` entries, the queue is dropped and the
    subscriber is flagged for a **marked snapshot-resync** — the stream
    handler sends a fresh full dump tagged `"type": "resync"`, so the
    client knows to replace (not merge) its state. Overflow is therefore
    never silent loss: a subscriber always ends at a state equal to a
    fresh dump.
  - Slow-client isolation falls out of the design: the only blocking
    waits (`writer.drain()`) live in the per-connection handler task; a
    stalled reader stalls its own bounded queue, nothing else.

Everything runs on the daemon's single asyncio loop. The publisher-side
enqueue (`offer`, called from the dispatch task) and the subscriber-side
dequeue (`next_frame`, called from the connection task) interleave only
at awaits — the subscriber-queue handover pattern the thread-ownership
analyzer sanctions via the `# analysis: queue` attribute marker
(docs/Analysis.md).

Observability: `ctrl.stream.*` counters/histograms (docs/Monitoring.md),
`ctrl.stream.publish` fault point at the fan-out seam and
`ctrl.stream.deliver` at the per-frame delivery seam (docs/Robustness.md).
"""

from __future__ import annotations

import asyncio
import collections
import time
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

from openr_tpu.messaging import QueueClosedError
from openr_tpu.solver import DecisionRouteUpdate
from openr_tpu.testing.faults import fault_point
from openr_tpu.types import Publication
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin
from openr_tpu.utils.ownership import owned_by


@dataclass
class StreamConfig:
    """Fan-out bounds (config `stream_config` section, docs/Streaming.md)."""

    # frames buffered per subscriber before the queue is coalesced
    subscriber_max_pending: int = 64
    # merged-delta entry budget after coalescing; beyond it the queue is
    # dropped and the subscriber resyncs from a marked snapshot
    coalesce_budget: int = 4096
    # hard cap on concurrent subscriptions (typed server-busy beyond)
    max_subscribers: int = 1024
    # encode each delta once per filter-equivalence class and share the
    # bytes across the class (docs/Streaming.md "Shared-encode fan-out");
    # off = the historical per-subscriber re-encode path, kept for
    # before/after measurement on identical flap batches
    shared_encode: bool = True


class SharedFrame:
    """One source item filtered for one filter-equivalence class.

    Every subscriber in the class holds a reference to the same
    SharedFrame in its bounded queue; the frame's body bytes are encoded
    lazily, once per codec, by the first connection task that delivers
    it (`body()`), and every later delivery reuses the memoized bytes.
    `body()` is synchronous and all consumers share one asyncio loop, so
    the memoization is race-free without locks.

    The per-subscriber oldest-enqueue stamp `publish_to_deliver_ms`
    depends on NEVER rides this object — it stays on the queue entry
    (`_frames` stores `(frame, t_enq)` tuples), so shared bytes cannot
    overwrite another subscriber's latency accounting.
    """

    __slots__ = ("item", "kind", "_manager", "_bodies")

    def __init__(self, item: Any, kind: str, manager: "StreamManager") -> None:
        self.item = item
        self.kind = kind  # "kvstore" | "routes"
        self._manager = manager
        self._bodies: Dict[str, bytes] = {}

    def body(self, codec_name: str) -> bytes:
        """Frame body bytes for `codec_name`; encodes on first use (the
        class encode), reuses thereafter (the class hit)."""
        cached = self._bodies.get(codec_name)
        if cached is not None:
            self._manager.note_class_hit()
            return cached
        from openr_tpu.streaming import codec as _codec

        t0 = time.perf_counter()
        if self.kind == "kvstore":
            body = _codec.encode_kv_body(self.item, codec_name)
        else:
            body = _codec.encode_route_body(
                _codec.route_fields_from_update(self.item), codec_name
            )
        self._bodies[codec_name] = body
        self._manager.note_class_encode(
            (time.perf_counter() - t0) * 1e3, len(body)
        )
        return body


def _unwrap(frame: Any) -> Any:
    """Queue entries may be SharedFrames (shared path) or raw items
    (direct `offer`, coalesced merges) — coalescing works on the item."""
    return frame.item if type(frame) is SharedFrame else frame


class SubscriberLimitError(RuntimeError):
    """Raised when `max_subscribers` is reached (typed server-busy)."""

    error_kind = "server_busy"
    retry_after_ms = 1000


class _BaseSubscription:
    """One subscriber's bounded frame queue (publisher side: `offer`,
    sync; subscriber side: `next_frame`, async — same loop)."""

    kind = "?"

    def __init__(self, manager: "StreamManager", label: str = "") -> None:
        self._manager = manager
        self.label = label
        cfg = manager.config
        self.max_pending = cfg.subscriber_max_pending
        self.coalesce_budget = cfg.coalesce_budget
        self._frames: Deque[Tuple[Any, float]] = collections.deque()
        self._resync_at: Optional[float] = None
        self._waiter: Optional[asyncio.Future] = None
        self.closed = False
        # per-frame delivery delay (seconds), consumed one-shot by the
        # stream handler before each write: the `ctrl.stream.deliver`
        # fault point's action hook sets it to emulate a slow client
        # deterministically (docs/Robustness.md)
        self.throttle_s = 0.0
        self.coalesces = 0
        self.resyncs = 0
        self.delivered = 0

    # -- publisher side (dispatch task) --------------------------------

    def offer(self, item: Any, t_enq: float) -> None:
        """Non-blocking enqueue; never raises, never waits. Called by the
        StreamManager dispatch task for every source-queue item (the
        per-subscriber-filter path; the shared path pre-filters once per
        class and calls `offer_shared`)."""
        if self.closed:
            return
        filtered = self._filter(item)
        if filtered is None:
            return
        self._enqueue(filtered, t_enq)

    def offer_shared(self, frame: SharedFrame, t_enq: float) -> None:
        """Shared-path enqueue: the dispatch task already filtered the
        item once for this subscriber's whole filter-equivalence class,
        so per-subscriber work is exactly one queue append."""
        if self.closed:
            return
        self._enqueue(frame, t_enq)

    def _enqueue(self, filtered: Any, t_enq: float) -> None:
        if self._resync_at is not None:
            # a pending resync supersedes deltas: the snapshot the
            # handler is about to take will already contain this change
            self._manager._bump("ctrl.stream.dropped_for_resync")
            self._wake()
            return
        self._frames.append((filtered, t_enq))
        depth = len(self._frames)
        counters = self._manager._ensure_counters()
        if depth > counters.get("ctrl.stream.queue_depth_last", 0):
            counters["ctrl.stream.queue_depth_last"] = depth
        if depth > self.max_pending:
            merged, t0, size = self._coalesce(self._frames)
            self.coalesces += 1
            self._manager._bump("ctrl.stream.coalesced")
            self._frames.clear()
            if size > self.coalesce_budget:
                # over budget even merged: drop everything, force a
                # marked snapshot-resync — never silent loss
                self._resync_at = t0
                self.resyncs += 1
                self._manager._bump("ctrl.stream.resyncs")
            else:
                self._frames.append((merged, t0))
        self._wake()

    def force_resync(self) -> None:
        """Drop pending frames and flag a marked snapshot-resync (the
        fan-out fault recovery: a failed publish must not become loss)."""
        if self.closed:
            return
        t0 = self._frames[0][1] if self._frames else time.monotonic()
        self._frames.clear()
        if self._resync_at is None:
            self._resync_at = t0
            self.resyncs += 1
            self._manager._bump("ctrl.stream.resyncs")
        self._wake()

    def close(self) -> None:
        self.closed = True
        self._wake()

    def _wake(self) -> None:
        if self._waiter is not None and not self._waiter.done():
            self._waiter.set_result(None)

    # -- subscriber side (connection task) -----------------------------

    async def next_frame(self) -> Tuple[str, Any, float]:
        """('delta', item, t_enqueued) | ('resync', None, t) |
        ('closed', None, t). Awaits until one is available."""
        while True:
            if self._resync_at is not None:
                t0 = self._resync_at
                self._resync_at = None
                return ("resync", None, t0)
            if self._frames:
                item, t0 = self._frames.popleft()
                return ("delta", item, t0)
            if self.closed:
                return ("closed", None, time.monotonic())
            self._waiter = asyncio.get_running_loop().create_future()
            try:
                await self._waiter
            finally:
                self._waiter = None

    # -- kind-specific hooks --------------------------------------------

    @property
    def filter_key(self) -> Tuple:
        """Filter-equivalence class key: subscriptions with equal keys
        see byte-identical filtered frames, so one class encode serves
        them all (docs/Streaming.md "Shared-encode fan-out")."""
        raise NotImplementedError

    def _filter(self, item: Any) -> Optional[Any]:
        raise NotImplementedError

    def _coalesce(
        self, frames: Deque[Tuple[Any, float]]
    ) -> Tuple[Any, float, int]:
        """Merge all pending frames into one; returns (merged, oldest
        enqueue stamp, merged entry count)."""
        raise NotImplementedError


class KvSubscription(_BaseSubscription):
    """KvStore publication stream with key-prefix/originator filters."""

    kind = "kvstore"

    def __init__(
        self,
        manager: "StreamManager",
        *,
        area: str = "0",
        prefixes: Optional[List[str]] = None,
        originators: Optional[Set[str]] = None,
        label: str = "",
    ) -> None:
        super().__init__(manager, label)
        self.area = area
        self.prefixes = list(prefixes or [])
        self.originators = set(originators or ())

    @property
    def filter_key(self) -> Tuple:
        return (
            "kvstore",
            self.area,
            tuple(sorted(self.prefixes)),
            tuple(sorted(self.originators)),
        )

    def _filter(self, pub: Publication) -> Optional[Publication]:
        if pub.area != self.area:
            return None
        key_vals = pub.key_vals
        expired = list(pub.expired_keys)
        if self.prefixes:
            key_vals = {
                k: v
                for k, v in key_vals.items()
                if any(k.startswith(p) for p in self.prefixes)
            }
            expired = [
                k
                for k in expired
                if any(k.startswith(p) for p in self.prefixes)
            ]
        if self.originators:
            key_vals = {
                k: v
                for k, v in key_vals.items()
                if v.originator_id in self.originators
            }
        if not key_vals and not expired:
            return None
        if len(key_vals) == len(pub.key_vals) and len(expired) == len(
            pub.expired_keys
        ):
            return pub  # unfiltered: share the publication object
        return Publication(
            key_vals=key_vals, expired_keys=expired, area=self.area
        )

    def _coalesce(self, frames):
        t0 = frames[0][1]
        key_vals: Dict[str, Any] = {}
        expired: Dict[str, None] = {}
        for frame, _ in frames:
            pub = _unwrap(frame)
            for key in pub.expired_keys:
                key_vals.pop(key, None)
                expired[key] = None
            for key, value in pub.key_vals.items():
                expired.pop(key, None)
                key_vals[key] = value  # newest version wins
        merged = Publication(
            key_vals=key_vals, expired_keys=list(expired), area=self.area
        )
        return merged, t0, len(key_vals) + len(expired)


# delete markers inside the coalesced route maps
_DELETE = object()


class RouteSubscription(_BaseSubscription):
    """Decision route-update stream (the DeltaPath consumer path)."""

    kind = "routes"

    @property
    def filter_key(self) -> Tuple:
        # route subscriptions carry no filters: one class for all
        return ("routes",)

    def _filter(
        self, update: DecisionRouteUpdate
    ) -> Optional[DecisionRouteUpdate]:
        return None if update.empty() else update

    def _coalesce(self, frames):
        t0 = frames[0][1]
        unicast: Dict[Any, Any] = {}
        mpls: Dict[int, Any] = {}
        for frame, _ in frames:
            update = _unwrap(frame)
            for prefix in update.unicast_routes_to_delete:
                unicast[prefix] = _DELETE
            for entry in update.unicast_routes_to_update:
                unicast[entry.prefix] = entry
            for label in update.mpls_routes_to_delete:
                mpls[label] = _DELETE
            for entry in update.mpls_routes_to_update:
                mpls[entry.label] = entry
        merged = DecisionRouteUpdate(
            unicast_routes_to_update=[
                e for e in unicast.values() if e is not _DELETE
            ],
            unicast_routes_to_delete=[
                p for p, e in unicast.items() if e is _DELETE
            ],
            mpls_routes_to_update=[
                e for e in mpls.values() if e is not _DELETE
            ],
            mpls_routes_to_delete=[
                label for label, e in mpls.items() if e is _DELETE
            ],
        )
        return merged, t0, len(unicast) + len(mpls)


@owned_by("ctrl-loop")
class StreamManager(CountersMixin, HistogramsMixin):
    """Subscription registry + fan-out dispatch for the ctrl server.

    One instance per daemon, registered with the Monitor as the
    `ctrl_stream` module so `ctrl.stream.*` land in every scrape."""

    def __init__(
        self,
        *,
        kvstore_updates=None,
        route_updates=None,
        config: Optional[StreamConfig] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self._kvstore_updates = kvstore_updates
        self._route_updates = route_updates
        self.config = config or StreamConfig()
        self._loop = loop
        # subscriber registries: appended by ctrl connection tasks,
        # iterated by the dispatch tasks — all on one loop (the
        # publisher-side enqueue is the sanctioned handover seam)
        self._kv_subs: List[KvSubscription] = []  # analysis: queue
        self._route_subs: List[RouteSubscription] = []  # analysis: queue
        # filter-equivalence classes, maintained incrementally on add/
        # remove so dispatch never re-groups 100k subscribers per frame:
        # filter_key -> members (same handover seam as the registries)
        self._kv_classes: Dict[Tuple, List[KvSubscription]] = {}  # analysis: queue
        self._route_classes: Dict[Tuple, List[RouteSubscription]] = {}  # analysis: queue
        self._tasks: List[asyncio.Task] = []
        self._started = False
        self._ensure_counters()
        self._ensure_histograms()

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Start one dispatch task per wired source queue. The readers
        drain continuously (zero subscribers = cheap drop), so the source
        ReplicateQueues never grow behind an idle manager."""
        if self._started:
            return
        self._started = True
        if self._kvstore_updates is not None:
            self._tasks.append(
                self.loop().create_task(
                    self._dispatch(
                        self._kvstore_updates.get_reader(),
                        self._kv_subs,
                        self._kv_classes,
                        "kvstore",
                    )
                )
            )
        if self._route_updates is not None:
            self._tasks.append(
                self.loop().create_task(
                    self._dispatch(
                        self._route_updates.get_reader(),
                        self._route_subs,
                        self._route_classes,
                        "routes",
                    )
                )
            )

    def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        self._tasks.clear()
        self._started = False
        for sub in list(self._kv_subs) + list(self._route_subs):
            sub.close()
        self._kv_subs.clear()
        self._route_subs.clear()
        self._kv_classes.clear()
        self._route_classes.clear()

    # -- subscription registry (ctrl connection tasks) ------------------

    def add_kvstore_subscriber(self, **kw) -> KvSubscription:
        self._check_capacity()
        sub = KvSubscription(self, **kw)
        self._kv_subs.append(sub)
        self._kv_classes.setdefault(sub.filter_key, []).append(sub)
        self._bump("ctrl.stream.subscribed_total")
        self._gauge_subscribers()
        return sub

    def add_route_subscriber(self, **kw) -> RouteSubscription:
        self._check_capacity()
        sub = RouteSubscription(self, **kw)
        self._route_subs.append(sub)
        self._route_classes.setdefault(sub.filter_key, []).append(sub)
        self._bump("ctrl.stream.subscribed_total")
        self._gauge_subscribers()
        return sub

    def remove_subscriber(self, sub: _BaseSubscription) -> None:
        sub.close()
        for registry in (self._kv_subs, self._route_subs):
            if sub in registry:
                registry.remove(sub)
        classes = (
            self._kv_classes if sub.kind == "kvstore" else self._route_classes
        )
        members = classes.get(sub.filter_key)
        if members is not None and sub in members:
            members.remove(sub)
            if not members:
                del classes[sub.filter_key]
        self._gauge_subscribers()

    def ensure_capacity(self) -> None:
        """Typed server-busy when `max_subscribers` is reached. The ctrl
        server calls this in the request handler (before the stream
        starts) so the rejection rides the normal error response; the
        add_* registrations re-check, race-free on one loop."""
        total = len(self._kv_subs) + len(self._route_subs)
        if total >= self.config.max_subscribers:
            self._bump("ctrl.stream.subscriber_rejects")
            raise SubscriberLimitError(
                f"subscriber limit reached ({self.config.max_subscribers})"
            )

    _check_capacity = ensure_capacity

    def _gauge_subscribers(self) -> None:
        counters = self._ensure_counters()
        counters["ctrl.stream.kv_subscribers_active"] = len(self._kv_subs)
        counters["ctrl.stream.route_subscribers_active"] = len(
            self._route_subs
        )

    def note_encode(self, ms: float, nbytes: int) -> None:
        """One REAL body serialization (docs/Monitoring.md): on the
        shared path this fires once per filter-class per frame (via
        `note_class_encode`); snapshot/resync/coalesced frames are
        per-subscriber state and meter their private encodes here too.
        `encode_ms` x `encode_bytes` is therefore the actual
        serialization bill — compare against `deliver_*` for the
        per-subscriber splice-and-write cost the sharing reduced it to."""
        self._observe("ctrl.stream.encode_ms", ms)
        self._bump("ctrl.stream.encode_bytes", nbytes)

    def note_class_encode(self, ms: float, nbytes: int) -> None:
        """A shared-path class encode: the one serialization a whole
        filter-equivalence class amortizes (`SharedFrame.body` miss)."""
        self._bump("ctrl.stream.encode_classes")
        self.note_encode(ms, nbytes)

    def note_class_hit(self) -> None:
        """A shared-bytes reuse (`SharedFrame.body` hit): hit rate =
        encode_class_hits / (encode_class_hits + encode_classes)."""
        self._bump("ctrl.stream.encode_class_hits")

    def note_deliver(self, ms: float, nbytes: int) -> None:
        """Per-subscriber delivery work (envelope splice + buffer
        write), recorded by the ctrl server per frame actually sent —
        the O(subscribers) half of the fan-out bill."""
        self._observe("ctrl.stream.deliver_ms", ms)
        self._bump("ctrl.stream.deliver_bytes", nbytes)

    def mark_delivered(self, sub: _BaseSubscription, t_enq: float) -> None:
        """Delivery accounting, called by the stream handler after the
        frame hit the socket: publish-to-deliver latency includes every
        millisecond a slow client spent stalled."""
        sub.delivered += 1
        self._bump("ctrl.stream.delivered")
        self._observe(
            "ctrl.stream.publish_to_deliver_ms",
            (time.monotonic() - t_enq) * 1e3,
        )

    def stats(self) -> Dict[str, Any]:
        """Live fan-out stats (ctrl getStreamStats / docs/Streaming.md)."""
        return {
            "kv_subscribers": len(self._kv_subs),
            "route_subscribers": len(self._route_subs),
            "kv_filter_classes": len(self._kv_classes),
            "route_filter_classes": len(self._route_classes),
            "shared_encode": self.config.shared_encode,
            "max_subscribers": self.config.max_subscribers,
            "subscriber_max_pending": self.config.subscriber_max_pending,
            "coalesce_budget": self.config.coalesce_budget,
            "counters": dict(self._ensure_counters()),
        }

    # -- fan-out dispatch -----------------------------------------------

    async def _dispatch(
        self,
        reader,
        subs: List[_BaseSubscription],
        classes: Dict[Tuple, List[_BaseSubscription]],
        kind: str,
    ) -> None:
        shared = self.config.shared_encode
        try:
            while True:
                item = await reader.get()
                t_enq = time.monotonic()
                t0 = time.perf_counter()
                try:
                    # named fault seam: an injected fan-out failure must
                    # degrade to marked resyncs, never silent loss
                    fault_point("ctrl.stream.publish", item)
                    if shared:
                        # filter ONCE per filter-equivalence class, wrap
                        # the result in a SharedFrame whose body bytes
                        # every class member reuses; per-subscriber work
                        # is one queue append
                        for members in list(classes.values()):
                            if not members:
                                continue
                            filtered = members[0]._filter(item)
                            if filtered is None:
                                continue
                            frame = SharedFrame(filtered, kind, self)
                            for sub in list(members):
                                sub.offer_shared(frame, t_enq)
                    else:
                        for sub in list(subs):
                            sub.offer(item, t_enq)
                except Exception:
                    self._bump("ctrl.stream.publish_errors")
                    for sub in list(subs):
                        sub.force_resync()
                self._bump("ctrl.stream.published")
                if subs:
                    self._observe(
                        "ctrl.stream.fanout_ms",
                        (time.perf_counter() - t0) * 1e3,
                    )
        except (QueueClosedError, asyncio.CancelledError):
            return
        finally:
            reader.close()
