"""Streaming control plane: delta subscriptions, bounded fan-out, and
admission control for expensive ctrl RPCs (docs/Streaming.md)."""

from openr_tpu.streaming.admission import (
    DEFAULT_COSTS,
    AdmissionConfig,
    AdmissionController,
    ServerBusyError,
)
from openr_tpu.streaming.subscription import (
    KvSubscription,
    RouteSubscription,
    SharedFrame,
    StreamConfig,
    StreamManager,
    SubscriberLimitError,
)

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "DEFAULT_COSTS",
    "KvSubscription",
    "RouteSubscription",
    "ServerBusyError",
    "SharedFrame",
    "StreamConfig",
    "StreamManager",
    "SubscriberLimitError",
]
