"""Shared journaled-file framing for crash-safe append logs.

Extracted from PersistentStore so every durable log in the tree — the
config store, the state journal — shares one framing and one recovery
discipline instead of re-deriving it:

  - records are ``<BII>``-framed (type byte, key length, value length)
    behind a per-log magic prefix;
  - full rewrites are atomic (tmp + fsync + rename): a kill mid-rewrite
    leaves the previous file intact plus a stray ``.tmp`` that load
    ignores;
  - appends are fsynced, and ``scan()`` recovers to the **longest
    well-formed record prefix**: a torn/truncated tail (crash
    mid-append, torn sector) truncates back to the last durable record
    instead of discarding the whole file.

Policy stays with the caller: what the records mean, when to compact,
how to count failures. This module only owns bytes on disk.
"""

from __future__ import annotations

import os
import struct
from typing import FrozenSet, Iterable, List, Tuple

HEADER = struct.Struct("<BII")

# one scanned record: (rec_type, key bytes, value bytes)
Record = Tuple[int, bytes, bytes]


class BadMagicError(ValueError):
    """The file exists but does not start with this log's magic."""


def pack(rec_type: int, key: bytes, value: bytes) -> bytes:
    return HEADER.pack(rec_type, len(key), len(value)) + key + value


class RecordLog:
    """One journaled file: magic prefix + framed records.

    Stateless over the file contents — ``scan()`` re-reads from disk, and
    the caller tracks geometry (snapshot vs journal bytes) from the
    records it writes/reads.
    """

    def __init__(
        self, path: str, magic: bytes, valid_types: Iterable[int]
    ) -> None:
        self.path = path
        self.magic = magic
        self.valid_types: FrozenSet[int] = frozenset(valid_types)

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def append(self, blob: bytes) -> None:
        """Fsynced append of already-packed records."""
        with open(self.path, "ab") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())

    def rewrite(self, blob: bytes) -> None:
        """Atomic full rewrite: magic + packed records, tmp + rename."""
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(self.magic + blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def scan(self) -> Tuple[List[Record], bool]:
        """Read the file back as (records, truncated).

        Recovers to the longest well-formed record prefix; ``truncated``
        is True when a torn tail was dropped. Raises ``BadMagicError``
        when the file does not start with this log's magic; OSError from
        the read propagates (the caller decides how to count it).
        """
        with open(self.path, "rb") as f:
            raw = f.read()
        if not raw.startswith(self.magic):
            raise BadMagicError(self.path)
        records: List[Record] = []
        off = len(self.magic)
        truncated = False
        while off < len(raw):
            if off + HEADER.size > len(raw):
                truncated = True
                break
            rec_type, klen, vlen = HEADER.unpack_from(raw, off)
            body_end = off + HEADER.size + klen + vlen
            if rec_type not in self.valid_types or body_end > len(raw):
                truncated = True
                break
            key_off = off + HEADER.size
            records.append(
                (rec_type, raw[key_off : key_off + klen], raw[key_off + klen : body_end])
            )
            off = body_end
        return records, truncated
