"""Durable on-disk key-value store (config-store).

Equivalent of openr/config-store/PersistentStore.{h,cpp}. The shared
journaled-file framing lives in `record_log` (also used by the state
journal, openr_tpu/journal/).
"""

from openr_tpu.configstore.persistent_store import PersistentStore
from openr_tpu.configstore.record_log import RecordLog

__all__ = ["PersistentStore", "RecordLog"]
