"""Durable on-disk key-value store (config-store).

Equivalent of openr/config-store/PersistentStore.{h,cpp}.
"""

from openr_tpu.configstore.persistent_store import PersistentStore

__all__ = ["PersistentStore"]
