"""Write-behind durable key-value store.

Behavioral port of openr/config-store/PersistentStore.{h,cpp}: an on-disk
kv database used to persist drain state, link-metric overrides and
allocated prefix indices across restarts. The reference appends
thrift-serialized ADD/DEL records to a TLV log and periodically rewrites
the full snapshot, with an 100ms..5s exponential write backoff
(Constants.h:81-83). This build keeps the same durability semantics with a
journaled format in one file: a snapshot record followed by ADD/DEL journal
entries, compacted on save when the journal grows past the snapshot size.
Writes are debounced (write-behind) and crash-safe (tmp + rename).
"""

from __future__ import annotations

import asyncio
import os
import struct
from typing import Any, Dict, Optional

from openr_tpu.utils import ExponentialBackoff
from openr_tpu.utils import serializer

_MAGIC = b"ONRPS1\n"
_REC_SNAPSHOT, _REC_ADD, _REC_DEL = 0, 1, 2

INITIAL_BACKOFF = 0.1  # Constants.h:81-83
MAX_BACKOFF = 5.0


class PersistentStore:
    """Durable kv store with write-behind persistence.

    API mirrors the reference (`store`/`load`/`erase` +
    `store_obj`/`load_obj` standing in for storeThriftObj/loadThriftObj).
    Synchronous calls mutate memory immediately; disk flush is debounced
    onto the event loop, or immediate when no loop is running (tools).
    """

    def __init__(
        self,
        path: str,
        dryrun: bool = False,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.path = path
        self.dryrun = dryrun
        self._loop = loop
        self.data: Dict[str, bytes] = {}
        self._journal: list = []  # pending (rec_type, key, value) records
        self._backoff = ExponentialBackoff(INITIAL_BACKOFF, MAX_BACKOFF)
        self._flush_timer: Optional[asyncio.TimerHandle] = None
        self.num_writes_to_disk = 0
        self._load_from_disk()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def store(self, key: str, value: bytes) -> None:
        self.data[key] = value
        self._journal.append((_REC_ADD, key, value))
        self._schedule_flush()

    def load(self, key: str) -> Optional[bytes]:
        return self.data.get(key)

    def erase(self, key: str) -> bool:
        existed = self.data.pop(key, None) is not None
        if existed:
            self._journal.append((_REC_DEL, key, b""))
            self._schedule_flush()
        return existed

    def store_obj(self, key: str, obj: Any) -> None:
        """storeThriftObj equivalent: serialize any wire-type dataclass."""
        self.store(key, serializer.dumps(obj))

    def load_obj(self, key: str) -> Optional[Any]:
        blob = self.load(key)
        return None if blob is None else serializer.loads(blob)

    def flush(self) -> None:
        """Force pending writes to disk now (also called on stop)."""
        if self._flush_timer is not None:
            self._flush_timer.cancel()
            self._flush_timer = None
        self._write_snapshot()

    def stop(self) -> None:
        self.flush()

    # ------------------------------------------------------------------
    # disk format
    # ------------------------------------------------------------------

    @staticmethod
    def _pack_record(rec_type: int, key: str, value: bytes) -> bytes:
        kb = key.encode()
        return (
            struct.pack("<BII", rec_type, len(kb), len(value)) + kb + value
        )

    def _write_snapshot(self) -> None:
        """Atomic full-state rewrite (tmp + rename)."""
        self._journal.clear()
        if self.dryrun:
            return
        blob = bytearray(_MAGIC)
        payload = serializer.dumps(dict(self.data))
        blob += self._pack_record(_REC_SNAPSHOT, "", payload)
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(bytes(blob))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self.num_writes_to_disk += 1

    def _load_from_disk(self) -> None:
        if self.dryrun or not os.path.exists(self.path):
            return
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
            if not raw.startswith(_MAGIC):
                return
            off = len(_MAGIC)
            while off + 9 <= len(raw):
                rec_type, klen, vlen = struct.unpack_from("<BII", raw, off)
                off += 9
                key = raw[off : off + klen].decode()
                off += klen
                value = raw[off : off + vlen]
                off += vlen
                if rec_type == _REC_SNAPSHOT:
                    self.data = dict(serializer.loads(value))
                elif rec_type == _REC_ADD:
                    self.data[key] = value
                elif rec_type == _REC_DEL:
                    self.data.pop(key, None)
        except Exception:
            # a corrupt store must not prevent startup; state rebuilds
            # from the network (reference tolerates the same)
            self.data = {}

    # ------------------------------------------------------------------
    # write-behind scheduling
    # ------------------------------------------------------------------

    def _schedule_flush(self) -> None:
        try:
            loop = self._loop or asyncio.get_running_loop()
        except RuntimeError:
            self._write_snapshot()  # no loop (CLI/tool usage): write now
            return
        if self._flush_timer is not None:
            return
        self._backoff.report_error()  # consecutive writes back off
        delay = self._backoff.get_time_remaining_until_retry()
        self._flush_timer = loop.call_later(delay, self._flush_cb)

    def _flush_cb(self) -> None:
        self._flush_timer = None
        self._write_snapshot()
        self._backoff.report_success()
