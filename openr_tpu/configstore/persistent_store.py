"""Write-behind durable key-value store.

Behavioral port of openr/config-store/PersistentStore.{h,cpp}: an on-disk
kv database used to persist drain state, link-metric overrides, allocated
prefix indices and self-originated KvStore key versions across restarts.
The reference appends thrift-serialized ADD/DEL records to a TLV log and
periodically rewrites the full snapshot, with an 100ms..5s exponential
write backoff (Constants.h:81-83). This build keeps the same durability
semantics with a journaled format in one file: a snapshot record followed
by ADD/DEL journal entries appended in place, compacted (tmp + rename)
when the on-disk journal grows past the snapshot size. Writes are
debounced (write-behind) and crash-safe:

  - the snapshot rewrite is atomic (tmp + fsync + rename) — a kill during
    compaction leaves the previous file intact plus a stray `.tmp` that
    load ignores;
  - journal appends are fsynced, and load recovers to the **longest
    well-formed record prefix**: a torn/truncated tail (crash mid-append,
    torn sector) silently truncates back to the last durable record
    instead of discarding the whole store;
  - after a truncated load the next flush force-compacts so fresh appends
    never land after garbage bytes.

Named fault points `configstore.save` / `configstore.load`
(testing/faults.py) let tests drive the failure paths deterministically:
a save fault keeps the journal pending and retries on the write backoff,
a load fault degrades to an empty store (state rebuilds from the
network, like the reference's corrupt-database tolerance).
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict, Optional

from openr_tpu.configstore import record_log
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils import ExponentialBackoff
from openr_tpu.utils import serializer

_MAGIC = b"ONRPS1\n"
_REC_SNAPSHOT, _REC_ADD, _REC_DEL = 0, 1, 2
_REC_HEADER = record_log.HEADER

INITIAL_BACKOFF = 0.1  # Constants.h:81-83
MAX_BACKOFF = 5.0


class PersistentStore:
    """Durable kv store with write-behind persistence.

    API mirrors the reference (`store`/`load`/`erase` +
    `store_obj`/`load_obj` standing in for storeThriftObj/loadThriftObj).
    Synchronous calls mutate memory immediately; disk flush is debounced
    onto the event loop, or immediate when no loop is running (tools).
    """

    def __init__(
        self,
        path: str,
        dryrun: bool = False,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.path = path
        self.dryrun = dryrun
        self._loop = loop
        self._log = record_log.RecordLog(
            path, _MAGIC, (_REC_SNAPSHOT, _REC_ADD, _REC_DEL)
        )
        self.data: Dict[str, bytes] = {}
        self._journal: list = []  # pending (rec_type, key, value) records
        self._backoff = ExponentialBackoff(INITIAL_BACKOFF, MAX_BACKOFF)
        self._flush_timer: Optional[asyncio.TimerHandle] = None
        self.num_writes_to_disk = 0
        self.num_journal_appends = 0
        self.num_compactions = 0
        self.num_write_failures = 0
        self.num_load_truncations = 0
        self.num_load_errors = 0
        # on-disk geometry driving the append-vs-compact decision
        self._snapshot_bytes = 0
        self._journal_bytes = 0
        # set when the on-disk tail is not trustworthy (truncated load,
        # failed append): the next flush must compact, never append
        self._needs_compact = True
        self._load_from_disk()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def store(self, key: str, value: bytes) -> None:
        self.data[key] = value
        self._journal.append((_REC_ADD, key, value))
        self._schedule_flush()

    def load(self, key: str) -> Optional[bytes]:
        return self.data.get(key)

    def erase(self, key: str) -> bool:
        existed = self.data.pop(key, None) is not None
        if existed:
            self._journal.append((_REC_DEL, key, b""))
            self._schedule_flush()
        return existed

    def store_obj(self, key: str, obj: Any) -> None:
        """storeThriftObj equivalent: serialize any wire-type dataclass."""
        self.store(key, serializer.dumps(obj))

    def load_obj(self, key: str) -> Optional[Any]:
        blob = self.load(key)
        return None if blob is None else serializer.loads(blob)

    def flush(self) -> None:
        """Force pending writes to disk now (also called on stop)."""
        if self._flush_timer is not None:
            self._flush_timer.cancel()
            self._flush_timer = None
        self._flush_to_disk()

    def stop(self) -> None:
        self.flush()

    # ------------------------------------------------------------------
    # disk format
    # ------------------------------------------------------------------

    @staticmethod
    def _pack_record(rec_type: int, key: str, value: bytes) -> bytes:
        return record_log.pack(rec_type, key.encode(), value)

    def _flush_to_disk(self) -> None:
        """One durable write: append the pending journal records, or
        compact to a fresh snapshot when the journal outgrew the snapshot
        (or the on-disk tail is suspect). A failed write keeps the
        journal pending and retries on the write backoff — persistence
        failures must never crash the daemon."""
        if self.dryrun:
            self._journal.clear()
            return
        if not self._journal and not self._needs_compact:
            return
        try:
            # named fault seam: injected write failures ride the exact
            # keep-journal + backoff-retry path an EIO would
            fault_point("configstore.save", self)
            if (
                self._needs_compact
                or not os.path.exists(self.path)
                or self._journal_bytes >= max(self._snapshot_bytes, 1)
            ):
                self._write_snapshot()
            else:
                self._append_journal()
        except Exception:
            self.num_write_failures += 1
            import logging

            logging.getLogger(__name__).exception(
                "config-store write failed; retrying"
            )
            self._schedule_flush(retry=True)

    def _write_snapshot(self) -> None:
        """Atomic full-state rewrite (tmp + rename)."""
        payload = serializer.dumps(dict(self.data))
        self._log.rewrite(self._pack_record(_REC_SNAPSHOT, "", payload))
        self._journal.clear()
        self._snapshot_bytes = len(payload)
        self._journal_bytes = 0
        self._needs_compact = False
        self.num_writes_to_disk += 1
        self.num_compactions += 1

    def _append_journal(self) -> None:
        """Fsynced append of the pending ADD/DEL records after the
        snapshot — the write-amplification win over rewriting the full
        snapshot on every debounced flush."""
        blob = b"".join(
            self._pack_record(rec_type, key, value)
            for rec_type, key, value in self._journal
        )
        self._log.append(blob)
        self._journal.clear()
        self._journal_bytes += len(blob)
        self.num_writes_to_disk += 1
        self.num_journal_appends += 1

    def _load_from_disk(self) -> None:
        if self.dryrun or not os.path.exists(self.path):
            self._needs_compact = True
            return
        try:
            # named fault seam: an injected load failure degrades to an
            # empty store (state rebuilds from the network)
            fault_point("configstore.load", self)
            records, truncated = self._log.scan()
        except record_log.BadMagicError:
            self.data = {}
            self._needs_compact = True
            return
        except Exception:
            self.num_load_errors += 1
            self.data = {}
            self._needs_compact = True
            return
        # fold the recovered record prefix back into the kv map
        data: Dict[str, bytes] = {}
        journal_bytes = 0
        snapshot_bytes = 0
        for rec_type, key_b, value in records:
            if rec_type == _REC_SNAPSHOT:
                try:
                    data = dict(serializer.loads(value))
                except Exception:
                    truncated = True  # torn snapshot body
                    break
                snapshot_bytes = len(value)
                journal_bytes = 0
            else:
                key = key_b.decode(errors="replace")
                if rec_type == _REC_ADD:
                    data[key] = value
                else:
                    data.pop(key, None)
                journal_bytes += _REC_HEADER.size + len(key_b) + len(value)
        self.data = data
        self._snapshot_bytes = snapshot_bytes
        self._journal_bytes = journal_bytes
        if truncated:
            self.num_load_truncations += 1
            self._needs_compact = True  # never append after garbage
        else:
            self._needs_compact = False

    # ------------------------------------------------------------------
    # write-behind scheduling
    # ------------------------------------------------------------------

    def _schedule_flush(self, retry: bool = False) -> None:
        try:
            loop = self._loop or asyncio.get_running_loop()
        except RuntimeError:
            if not retry:
                self._flush_to_disk()  # no loop (CLI/tool usage): write now
            return
        if self._flush_timer is not None:
            return
        self._backoff.report_error()  # consecutive writes back off
        delay = self._backoff.get_time_remaining_until_retry()
        self._flush_timer = loop.call_later(delay, self._flush_cb)

    def _flush_cb(self) -> None:
        self._flush_timer = None
        failures = self.num_write_failures
        self._flush_to_disk()
        if self.num_write_failures == failures:
            self._backoff.report_success()
