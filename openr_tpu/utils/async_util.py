"""Asyncio-based rate limiters.

Equivalents of openr/common/AsyncDebounce.h and AsyncThrottle.h. The reference
builds these on folly::AsyncTimeout scheduled on a module's EventBase; here the
module runtime is an asyncio event loop, so they schedule loop timers instead.

AsyncDebounce: every invocation doubles the wait (min..max backoff) and
(re)schedules the callback; the callback fires once the invocations quiesce or
the max backoff elapses. Used by Decision to batch SPF runs (Decision.cpp:1406).

AsyncThrottle: invocations within the window collapse into one callback at the
window boundary. Used by LinkMonitor/PrefixManager advertisement paths.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from openr_tpu.utils.backoff import ExponentialBackoff


class AsyncDebounce:
    def __init__(
        self,
        min_backoff: float,
        max_backoff: float,
        callback: Callable[[], None],
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self._backoff = ExponentialBackoff(min_backoff, max_backoff)
        self._callback = callback
        self._loop = loop
        self._handle: Optional[asyncio.TimerHandle] = None

    def __call__(self) -> None:
        loop = self._loop or asyncio.get_running_loop()
        if not self._backoff.at_max_backoff():
            self._backoff.report_error()
            if self._handle is not None:
                self._handle.cancel()
            self._handle = loop.call_later(
                self._backoff.get_current_backoff(), self._fire
            )
        assert self._handle is not None

    def _fire(self) -> None:
        self._handle = None
        self._backoff.report_success()
        self._callback()

    def is_scheduled(self) -> bool:
        return self._handle is not None

    def cancel(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
            self._backoff.report_success()


class AsyncThrottle:
    def __init__(
        self,
        timeout: float,
        callback: Callable[[], None],
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self._timeout = timeout
        self._callback = callback
        self._loop = loop
        self._handle: Optional[asyncio.TimerHandle] = None

    def __call__(self) -> None:
        if self._handle is not None:
            return  # already scheduled; coalesce
        loop = self._loop or asyncio.get_running_loop()
        if self._timeout <= 0:
            # immediate execution, mirrors AsyncThrottle.cpp zero-timeout path
            self._callback()
            return
        self._handle = loop.call_later(self._timeout, self._fire)

    def _fire(self) -> None:
        self._handle = None
        self._callback()

    def is_active(self) -> bool:
        return self._handle is not None

    def cancel(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
