"""Utility substrate: backoff, debounce, throttle, step detection.

Equivalents of openr/common/{ExponentialBackoff,AsyncDebounce,AsyncThrottle,
StepDetector}.h, rebuilt on asyncio instead of folly EventBase.
"""

from openr_tpu.utils.backoff import ExponentialBackoff
from openr_tpu.utils.async_util import AsyncDebounce, AsyncThrottle
from openr_tpu.utils.step_detector import StepDetector

__all__ = [
    "ExponentialBackoff",
    "AsyncDebounce",
    "AsyncThrottle",
    "StepDetector",
]
