"""Utility substrate: backoff, debounce, throttle, step detection.

Equivalents of openr/common/{ExponentialBackoff,AsyncDebounce,AsyncThrottle,
StepDetector}.h, rebuilt on asyncio instead of folly EventBase. Also home
to the @shape_contract kernel annotation the ShapeFlow static analysis
seeds from (utils/shape_contract.py, docs/Analysis.md).
"""

from openr_tpu.utils.backoff import ExponentialBackoff
from openr_tpu.utils.async_util import AsyncDebounce, AsyncThrottle
from openr_tpu.utils.shape_contract import ContractError, shape_contract
from openr_tpu.utils.step_detector import StepDetector

__all__ = [
    "ExponentialBackoff",
    "AsyncDebounce",
    "AsyncThrottle",
    "ContractError",
    "StepDetector",
    "shape_contract",
]
