"""Deterministic wire serialization for LSDB objects.

The reference serializes thrift structs into KvStore value bytes; here
dataclasses are encoded as canonical JSON (sorted keys, no whitespace).
Determinism matters: the KvStore CRDT merge breaks same-version ties by
comparing value BYTES (KvStore.cpp:316-334), so two encodings of the same
logical object must be byte-identical.
"""

from __future__ import annotations

import dataclasses
import functools
import json
from typing import Any, Dict, Type

from openr_tpu import types as T

_TYPE_REGISTRY: Dict[str, Type] = {
    cls.__name__: cls
    for cls in (
        T.Adjacency,
        T.AdjacencyDatabase,
        T.PrefixEntry,
        T.PrefixDatabase,
        T.PerfEvent,
        T.PerfEvents,
        T.MetricEntity,
        T.MetricVector,
        T.NextHop,
        T.MplsAction,
        T.UnicastRoute,
        T.MplsRoute,
    )
}

_ENUMS: Dict[str, Type] = {
    cls.__name__: cls
    for cls in (
        T.PrefixType,
        T.PrefixForwardingType,
        T.PrefixForwardingAlgorithm,
        T.CompareType,
        T.MplsActionCode,
    )
}


def _encode(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            "__t": type(obj).__name__,
            **{
                f.name: _encode(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            },
        }
    if type(obj).__name__ in _ENUMS:
        return {"__t": type(obj).__name__, "v": obj.name}
    if isinstance(obj, bytes):
        return {"__t": "bytes", "v": obj.hex()}
    if isinstance(obj, (list, tuple)):
        return [_encode(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    return obj


# per-class decode plan: which field names are declared as tuples (list
# values must be converted back). Computed once per class — calling
# dataclasses.fields() per decoded object dominated cold-start ingest
# profiles at emulation scale.
_TUPLE_FIELDS: Dict[Type, frozenset] = {}


def _tuple_fields(cls: Type) -> frozenset:
    cached = _TUPLE_FIELDS.get(cls)
    if cached is None:
        cached = frozenset(
            f.name
            for f in dataclasses.fields(cls)
            if "Tuple" in str(f.type) or "tuple" in str(f.type)
        )
        _TUPLE_FIELDS[cls] = cached
    return cached


@functools.lru_cache(maxsize=65536)
def _ip_prefix(prefix: str) -> "T.IpPrefix":
    """IpPrefix is frozen; share parsed instances (ipaddress parsing is the
    second-hottest decode cost after field reconstruction)."""
    return T.IpPrefix(prefix)


def _decode(obj: Any) -> Any:
    if isinstance(obj, list):
        return [_decode(x) for x in obj]
    if isinstance(obj, dict):
        tname = obj.get("__t")
        if tname is None:
            return {k: _decode(v) for k, v in obj.items()}
        if tname == "IpPrefix":
            return _ip_prefix(obj["prefix"])
        if tname == "bytes":
            return bytes.fromhex(obj["v"])
        if tname in _ENUMS:
            return _ENUMS[tname][obj["v"]]
        cls = _TYPE_REGISTRY[tname]
        fields = {
            k: _decode(v) for k, v in obj.items() if k != "__t"
        }
        for name in _tuple_fields(cls):
            val = fields.get(name)
            if isinstance(val, list):
                fields[name] = tuple(val)
        return cls(**fields)
    return obj


def register_type(cls: Type) -> Type:
    """Make a wire-type dataclass decodable (journal payloads register
    KvStore Value this way). Idempotent; returns the class so it can be
    used as a decorator."""
    _TYPE_REGISTRY.setdefault(cls.__name__, cls)
    return cls


def to_jsonable(obj: Any) -> Any:
    """Encode to the tagged plain-JSON form without stringifying — for
    callers that embed wire objects inside larger JSON documents (the
    state journal's record payloads)."""
    return _encode(obj)


def from_jsonable(obj: Any) -> Any:
    """Inverse of to_jsonable."""
    return _decode(obj)


def dumps(obj: Any) -> bytes:
    return json.dumps(
        _encode(obj), sort_keys=True, separators=(",", ":")
    ).encode()


def loads(data: bytes) -> Any:
    return _decode(json.loads(data.decode()))
