"""Build metadata, the common/BuildInfo equivalent.

The reference exposes build user/time/package through fb303's getBuildInfo
(openr/common/BuildInfo.h via exportBuildInfo); here the same shape is
assembled from the package itself so `breeze openr version` and the ctrl
API report something meaningful in a from-source deployment.
"""

from __future__ import annotations

import platform
import sys
from typing import Dict

VERSION = "1.0.0"  # single source of truth; breeze derives its banner from it
PACKAGE = "openr-tpu"

# SOAK_r*/BENCH_r* artifact field contract: bump when the shape of the
# judged report / bench line changes, so offline renderers (`breeze perf
# soak-report`, `breeze fleet report`) can warn instead of misreading
ARTIFACT_SCHEMA_VERSION = 1


def build_fingerprint() -> str:
    """`git describe --always --dirty` of the source tree, degrading to
    the package VERSION outside a checkout — stamped next to
    ARTIFACT_SCHEMA_VERSION in every soak/bench artifact so a report
    line is always traceable to the exact code that produced it."""
    import os
    import subprocess

    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        probe = subprocess.run(
            ["git", "describe", "--always", "--dirty"],
            capture_output=True,
            cwd=root,
            timeout=10,
        )
        desc = probe.stdout.decode(errors="replace").strip()
        if probe.returncode == 0 and desc:
            return desc
    except Exception:
        pass
    return VERSION


def get_build_info() -> Dict[str, str]:
    info = {
        "build_package_name": PACKAGE,
        "build_package_version": VERSION,
        "build_mode": "opt",
        "build_platform": platform.platform(),
        "build_python": sys.version.split()[0],
        "build_rule": "openr_tpu",
    }
    info.update(get_analysis_build_info())
    return info


def get_analysis_build_info() -> Dict[str, str]:
    """Which static-analysis invariants this binary was linted against
    (the getAnalysisVersion surface: rides ctrl getBuildInfo and `breeze
    openr version`, so deployed daemons self-report their lint contract —
    docs/Analysis.md). When an analysis ran in this process (the tier-1
    self-run, a `--changed` pre-commit pass, an operator-triggered run),
    its cost is surfaced too: total wall time plus per-rule
    `<rule>=<findings>:<ms>` pairs — analysis cost is observable like
    every other cost in this codebase."""
    from openr_tpu.analysis import get_analysis_info

    meta = get_analysis_info()
    info = {
        "build_analysis_version": meta["analysis_version"],
        "build_analysis_rules": ",".join(meta["analysis_rules"]),
    }
    if "analysis_wall_ms" in meta:
        info["build_analysis_wall_ms"] = f"{meta['analysis_wall_ms']:.1f}"
        info["build_analysis_files"] = str(meta["analysis_files"])
        info["build_analysis_rule_stats"] = ",".join(
            f"{name}={stats['findings']}:{stats['ms']:.1f}ms"
            for name, stats in sorted(
                meta["analysis_rule_stats"].items()
            )
        )
    if "analysis_contracts" in meta:
        # ShapeFlow pass shape: how many @shape_contract annotations were
        # verified, how many functions were interpreted/inferred, and the
        # pass wall time — `contracts=12,functions=41,inferred=29:83.0ms`
        sf = meta["analysis_contracts"]
        info["build_analysis_contracts"] = (
            f"contracts={sf['contracts']},functions={sf['functions']},"
            f"inferred={sf['inferred']}:{sf['wall_ms']:.1f}ms"
        )
    return info
