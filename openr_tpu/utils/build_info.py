"""Build metadata, the common/BuildInfo equivalent.

The reference exposes build user/time/package through fb303's getBuildInfo
(openr/common/BuildInfo.h via exportBuildInfo); here the same shape is
assembled from the package itself so `breeze openr version` and the ctrl
API report something meaningful in a from-source deployment.
"""

from __future__ import annotations

import platform
import sys
from typing import Dict

VERSION = "1.0.0"  # single source of truth; breeze derives its banner from it
PACKAGE = "openr-tpu"


def get_build_info() -> Dict[str, str]:
    return {
        "build_package_name": PACKAGE,
        "build_package_version": VERSION,
        "build_mode": "opt",
        "build_platform": platform.platform(),
        "build_python": sys.version.split()[0],
        "build_rule": "openr_tpu",
    }
