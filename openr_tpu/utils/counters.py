"""Shared counters mixin (the fb303 fbData equivalent).

Every module exposes a `counters` dict of monotonically increasing values
(naming convention `<module>.<counter>`, docs/Monitoring.md:19-31); the
monitor module aggregates them across modules for the ctrl API.
"""

from __future__ import annotations

from typing import Dict


class CountersMixin:
    counters: Dict[str, int]

    def _ensure_counters(self) -> Dict[str, int]:
        if not hasattr(self, "counters"):
            self.counters = {}
        return self.counters

    def _bump(self, counter: str, n: int = 1) -> None:
        counters = self._ensure_counters()
        counters[counter] = counters.get(counter, 0) + n
