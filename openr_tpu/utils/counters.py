"""Shared counters + histogram primitives (the fb303 fbData equivalent).

Every module exposes a `counters` dict of monotonically increasing values
(naming convention `<module>.<counter>`, docs/Monitoring.md:19-31) and a
`histograms` dict of fixed log-bucket `Histogram`s for latency-style
distributions; the monitor module aggregates both across modules for the
ctrl API (`getCounters` / `getHistograms`).
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Tuple


class CountersMixin:
    counters: Dict[str, int]

    def _ensure_counters(self) -> Dict[str, int]:
        if not hasattr(self, "counters"):
            self.counters = {}
        return self.counters

    def _bump(self, counter: str, n: int = 1) -> None:
        counters = self._ensure_counters()
        counters[counter] = counters.get(counter, 0) + n


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------

# Log-bucket geometry: bucket 0 is [0, _LO); bucket i >= 1 is
# [_LO * 2**((i-1)/_SUB), _LO * 2**(i/_SUB)); the last bucket absorbs
# everything larger. _LO is in the recorded unit (milliseconds by
# convention), so one fixed geometry spans 1µs solver dispatches to
# multi-hour tails with <= 2**(1/_SUB)-1 ≈ 19% relative bucket error —
# no per-histogram bucket configuration, unlike the reference's linear
# fb303 ExportedHistogram (docs/Monitoring.md histogram section).
_LO = 1e-3
_SUB = 4
_NBUCKETS = 1 + _SUB * 40


class Histogram:
    """Fixed log-bucket histogram: O(1) record, mergeable, percentile export.

    Records are floats in a single unit (ms for every `*_ms` histogram).
    Percentiles interpolate linearly inside the target bucket and clamp to
    the exact observed min/max, so single-sample and edge percentiles are
    exact while the memory stays one small int list per histogram.
    """

    __slots__ = ("buckets", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self.buckets: List[int] = [0] * _NBUCKETS
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    @staticmethod
    def bucket_index(value: float) -> int:
        if value < _LO:
            return 0
        # the 1e-9 guard pins exact bucket edges to their own bucket: log2
        # of a representable edge can land a hair under its integer value
        # and would otherwise misfile the edge one bucket down
        idx = 1 + math.floor(math.log2(value / _LO) * _SUB + 1e-9)
        if idx < 1:
            return 1
        return idx if idx < _NBUCKETS else _NBUCKETS - 1

    @staticmethod
    def bucket_bounds(index: int) -> Tuple[float, float]:
        """[lower, upper) value range of a bucket."""
        if index <= 0:
            return (0.0, _LO)
        return (_LO * 2 ** ((index - 1) / _SUB), _LO * 2 ** (index / _SUB))

    def record(self, value: float) -> None:
        v = float(value)
        if v < 0.0 or v != v:  # negative clock skew / NaN: clamp out
            v = 0.0
        self.buckets[self.bucket_index(v)] += 1
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold `other` into self (cross-module aggregation); returns self."""
        for i, c in enumerate(other.buckets):
            if c:
                self.buckets[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.min is not None and (self.min is None or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None or other.max > self.max):
            self.max = other.max
        return self

    def copy(self) -> "Histogram":
        out = Histogram()
        out.buckets = list(self.buckets)
        out.count = self.count
        out.sum = self.sum
        out.min = self.min
        out.max = self.max
        return out

    def reset(self) -> None:
        """Clear all recorded samples (the reset-on-read snapshot mode:
        dashboards export-then-reset to turn lifetime-cumulative
        histograms into per-window rates)."""
        for i in range(len(self.buckets)):
            self.buckets[i] = 0
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def percentile(self, p: float) -> float:
        """p-th percentile (0..100); 0.0 when empty."""
        if self.count == 0:
            return 0.0
        rank = (p / 100.0) * self.count
        cum = 0
        for i, c in enumerate(self.buckets):
            if not c:
                continue
            if cum + c >= rank:
                lo, hi = self.bucket_bounds(i)
                val = lo + (hi - lo) * ((rank - cum) / c)
                return min(max(val, self.min), self.max)
            cum += c
        return self.max  # float-fuzz fallthrough: rank beyond last bucket

    @property
    def avg(self) -> float:
        return self.sum / self.count if self.count else 0.0

    @property
    def p50(self) -> float:
        return self.percentile(50)

    @property
    def p95(self) -> float:
        return self.percentile(95)

    @property
    def p99(self) -> float:
        return self.percentile(99)

    def to_dict(self) -> Dict[str, float]:
        """Export shape served by ctrl getHistograms / breeze rendering."""
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "avg": round(self.avg, 6),
            "min": round(self.min, 6) if self.min is not None else 0.0,
            "max": round(self.max, 6) if self.max is not None else 0.0,
            "p50": round(self.p50, 6),
            "p95": round(self.p95, 6),
            "p99": round(self.p99, 6),
        }

    def to_sparse(self) -> Dict[str, object]:
        """Lossless JSON-serializable form: only nonzero buckets ride. Unlike
        to_dict (stats only), a sparse export can be rehydrated with
        from_sparse and merged — the shape convergence-report rollups use to
        fold per-node windowed histograms network-wide."""
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "buckets": {
                str(i): c for i, c in enumerate(self.buckets) if c
            },
        }

    @classmethod
    def from_sparse(cls, data: Dict[str, object]) -> "Histogram":
        out = cls()
        for key, c in dict(data.get("buckets") or {}).items():
            out.buckets[int(key)] = int(c)
        out.count = int(data.get("count", 0))
        out.sum = float(data.get("sum", 0.0))
        out.min = None if data.get("min") is None else float(data["min"])
        out.max = None if data.get("max") is None else float(data["max"])
        return out


class Timer:
    """Context manager recording elapsed milliseconds into a histogram.

    Runs on time.perf_counter (monotonic), so wall-clock steps never skew
    latency stats — the same rule the convergence span path follows."""

    __slots__ = ("_observe", "_name", "_t0")

    def __init__(self, observe, name: str) -> None:
        self._observe = observe
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._observe(self._name, (time.perf_counter() - self._t0) * 1e3)


class HistogramsMixin:
    """Per-module histogram dict, the distribution sibling of CountersMixin
    (same `<module>.<name>` naming convention; `*_ms` suffix for latency)."""

    histograms: Dict[str, Histogram]

    def _ensure_histograms(self) -> Dict[str, Histogram]:
        if not hasattr(self, "histograms"):
            self.histograms = {}
        return self.histograms

    def _observe(self, name: str, value: float) -> None:
        histograms = self._ensure_histograms()
        hist = histograms.get(name)
        if hist is None:
            hist = histograms[name] = Histogram()
        hist.record(value)

    def _timer(self, name: str) -> Timer:
        return Timer(self._observe, name)
