"""Thread/task-ownership annotations for module state.

The daemon runs every module on one asyncio loop, but state is still
*owned*: each module's mutable attributes belong to that module's task set,
while the ctrl server's per-connection tasks (and the monitor's drain task)
reach into modules from outside. `owned_by` declares that ownership so the
static thread-ownership analyzer (openr_tpu/analysis/thread_ownership.py)
can flag externally-reachable methods that mutate owned state without a
declared handover.

Usage:

    @owned_by("decision-loop")          # class: who owns the state
    class Decision(...):
        ...
        # analysis: shared              # method: deliberately shared —
        def set_rib_policy(self, p):    # sync, so loop-serialized with the
            ...                         # owner's callbacks

The decorator is a runtime no-op (it only stamps ``__analysis_owner__``);
the convention is enforced at analysis time, not at run time. A method may
alternatively be decorated `@owned_by("ctrl")` instead of carrying the
`# analysis: shared` comment — both declare the same thing, and the
analyzer additionally requires such methods to be synchronous (an async
shared method could interleave with the owner at its awaits).
"""

from __future__ import annotations


def owned_by(owner: str):
    """Declare the owning loop/task of a class's state (class decorator) or
    declare a method safe to invoke from outside the owner (method
    decorator). Metadata only; see openr_tpu/analysis/thread_ownership.py."""

    def mark(obj):
        obj.__analysis_owner__ = owner
        return obj

    return mark
