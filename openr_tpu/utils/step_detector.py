"""Two-window step detector for noisy time series (RTT smoothing).

Semantics follow openr/common/StepDetector.h: a fast and a slow sliding-window
mean; when |fast-slow|/slow (percent) rises above hi_threshold we are on a
step's rising edge; when it falls back below lo_threshold we signal the step
with the fast mean. A separate absolute threshold catches slow "staircase"
drift. Spark uses this to re-advertise adjacency RTT metrics only on real
changes (Spark.cpp:667).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Tuple


class _SlidingWindow:
    """Count-bounded and time-bounded sliding window average."""

    def __init__(self, max_samples: int, max_age: float) -> None:
        self._max_samples = max_samples
        self._max_age = max_age
        self._samples: Deque[Tuple[float, float]] = deque()

    def add(self, now: float, value: float) -> None:
        self._samples.append((now, value))
        while len(self._samples) > self._max_samples:
            self._samples.popleft()
        while self._samples and now - self._samples[0][0] > self._max_age:
            self._samples.popleft()

    def avg(self) -> float:
        if not self._samples:
            return 0.0
        return sum(v for _, v in self._samples) / len(self._samples)

    def count(self) -> int:
        return len(self._samples)


class StepDetector:
    def __init__(
        self,
        step_cb: Callable[[float], None],
        fast_window_size: int = 10,
        slow_window_size: int = 60,
        lower_threshold: float = 2.0,  # percent
        upper_threshold: float = 5.0,  # percent
        abs_threshold: float = 500.0,
        sample_period: float = 1.0,
    ) -> None:
        assert lower_threshold < upper_threshold
        assert fast_window_size < slow_window_size
        self._fast = _SlidingWindow(
            fast_window_size, sample_period * fast_window_size
        )
        self._slow = _SlidingWindow(
            slow_window_size, sample_period * slow_window_size
        )
        self._slow_window_size = slow_window_size
        self._lo = lower_threshold
        self._hi = upper_threshold
        self._abs = abs_threshold
        self._step_cb = step_cb
        self._last_avg = 0.0
        self._last_avg_init = False
        self._in_transit = False

    def add_value(self, now: float, value: float) -> None:
        self._fast.add(now, value)
        self._slow.add(now, value)
        fast_avg = self._fast.avg()
        slow_avg = self._slow.avg()

        if not self._last_avg_init and (
            self._slow.count() >= self._slow_window_size / 2
        ):
            self._last_avg = slow_avg
            self._last_avg_init = True

        if slow_avg == 0:
            raise ZeroDivisionError("slow window average is zero")

        diff = abs((fast_avg - slow_avg) / slow_avg) * 100

        if self._in_transit:
            if diff <= self._lo:
                # falling edge: step complete, report the fast mean
                self._in_transit = False
                self._step_cb(fast_avg)
                self._last_avg = fast_avg
                self._last_avg_init = True
                return
        elif diff >= self._hi:
            self._in_transit = True

        # gradual drift missed by the edge state machine
        if (
            diff <= self._lo
            and self._last_avg_init
            and abs(slow_avg - self._last_avg) >= self._abs
        ):
            self._step_cb(slow_avg)
            self._last_avg = slow_avg
