"""TLS for the control-plane and KvStore-peering transports.

Equivalent of the reference's thrift-server TLS setup (openr/Main.cpp:
517-543 — x509 cert/key/CA paths, TLSTicketKeySeeds, acceptable-peer
common names): mutual TLS with a shared CA, both sides presenting
certificates, with an optional allow-list of peer common names checked
after the handshake (`tls_acceptable_peers` flag semantics).

`make_test_ca` generates an ephemeral CA + node certificates (via the
`cryptography` package) for tests and lab setups; production deployments
point the daemon at files from their own PKI.
"""

from __future__ import annotations

import ssl
from typing import List, Optional, Sequence, Tuple


def server_ssl_context(
    cert_path: str, key_path: str, ca_path: Optional[str] = None
) -> ssl.SSLContext:
    """Server side of mutual TLS: present cert, require + verify clients
    against the CA when given."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    if ca_path:
        ctx.load_verify_locations(ca_path)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(
    ca_path: str,
    cert_path: Optional[str] = None,
    key_path: Optional[str] = None,
) -> ssl.SSLContext:
    """Client side: verify the server against the CA (no hostname check —
    routers peer by address) and present our certificate for mutual auth.

    Note the asymmetry, mirroring the reference's server-side
    `tls_acceptable_peers` flag: only SERVERS check the peer CN against
    the acceptable-peers list; a client accepts any server certificate
    issued by the CA. Callers needing client-side peer pinning can check
    `peer_common_name()` after the handshake."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_verify_locations(ca_path)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    if cert_path and key_path:
        ctx.load_cert_chain(cert_path, key_path)
    return ctx


def peer_common_name(ssl_object) -> Optional[str]:
    """CN of the peer certificate of an established TLS connection."""
    cert = ssl_object.getpeercert()
    if not cert:
        return None
    for rdn in cert.get("subject", ()):
        for key, value in rdn:
            if key == "commonName":
                return value
    return None


def check_acceptable_peer(
    ssl_object, acceptable_peers: Optional[Sequence[str]]
) -> bool:
    """tls_acceptable_peers semantics: empty/None accepts any CA-verified
    peer; otherwise the peer certificate CN must be in the list."""
    if not acceptable_peers:
        return True
    return peer_common_name(ssl_object) in set(acceptable_peers)


def enforce_acceptable_peer(writer, acceptable_peers, log, what: str) -> bool:
    """Post-handshake allow-list check shared by the ctrl and KvStore
    servers: closes the connection and returns False on rejection."""
    if not acceptable_peers:
        return True
    if check_acceptable_peer(
        writer.get_extra_info("ssl_object"), acceptable_peers
    ):
        return True
    log.warning("%s: rejecting peer outside acceptable list", what)
    writer.close()
    return False


def make_test_ca(
    directory: str, names: List[str]
) -> Tuple[str, List[Tuple[str, str]]]:
    """Ephemeral CA + one (cert, key) pair per name, written under
    `directory`. Returns (ca_path, [(cert_path, key_path), ...])."""
    import datetime
    import os

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    now = datetime.datetime.now(datetime.timezone.utc)

    def _name(cn: str):
        return x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
        )

    def _write_key(path: str, key) -> None:
        with open(path, "wb") as f:
            f.write(
                key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.PKCS8,
                    serialization.NoEncryption(),
                )
            )

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(_name("openr-tpu-test-ca"))
        .issuer_name(_name("openr-tpu-test-ca"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), True)
        .sign(ca_key, hashes.SHA256())
    )
    ca_path = os.path.join(directory, "ca.pem")
    with open(ca_path, "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))

    pairs: List[Tuple[str, str]] = []
    for cn in names:
        key = ec.generate_private_key(ec.SECP256R1())
        cert = (
            x509.CertificateBuilder()
            .subject_name(_name(cn))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .sign(ca_key, hashes.SHA256())
        )
        cert_path = os.path.join(directory, f"{cn}.pem")
        key_path = os.path.join(directory, f"{cn}.key")
        with open(cert_path, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))
        _write_key(key_path, key)
        pairs.append((cert_path, key_path))
    return ca_path, pairs
