"""Exponential backoff tracker.

Semantics match openr/common/ExponentialBackoff.h: reportError doubles the
current backoff (starting at initial, capped at max), reportSuccess clears it,
canTryNow/time_remaining are measured from the last error time. Durations are
float seconds.

Opt-in decorrelated jitter (`jitter=True`): each error draws the next
backoff uniformly from [initial, 3 * previous] (capped at max) instead of
deterministic doubling — the AWS "decorrelated jitter" scheme. Fleets of
agents that fail together (power event, agent push) then spread their
retries instead of re-converging on the same instants and producing
synchronized resync storms. The RNG is injectable for deterministic tests;
the default (`jitter=False`) keeps the reference's exact doubling so
existing callers are bit-compatible.
"""

from __future__ import annotations

import random
import time
from typing import Optional


class ExponentialBackoff:
    def __init__(
        self,
        initial_backoff: float,
        max_backoff: float,
        clock=time.monotonic,
        jitter: bool = False,
        rng: Optional[random.Random] = None,
    ) -> None:
        assert initial_backoff > 0 and max_backoff >= initial_backoff
        self._initial = initial_backoff
        self._max = max_backoff
        self._current = 0.0
        self._last_error_time = 0.0
        self._clock = clock
        self._jitter = jitter
        self._rng = rng if rng is not None else random.Random()

    def can_try_now(self) -> bool:
        return self.get_time_remaining_until_retry() <= 0

    def report_success(self) -> None:
        self._current = 0.0
        self._last_error_time = 0.0

    def report_error(self) -> None:
        self._last_error_time = self._clock()
        if not self._jitter:
            if self._current == 0.0:
                self._current = self._initial
            else:
                self._current = min(self._max, self._current * 2)
            return
        # decorrelated jitter: uniform in [initial, 3 * previous], where
        # the first error uses previous = initial; always within
        # [initial, max] so retry latency stays bounded both ways
        prev = self._current if self._current > 0.0 else self._initial
        self._current = min(
            self._max, self._rng.uniform(self._initial, prev * 3)
        )

    def report_status(self, ok: bool) -> None:
        if ok:
            self.report_success()
        else:
            self.report_error()

    def at_max_backoff(self) -> bool:
        return self._current >= self._max

    def get_time_remaining_until_retry(self) -> float:
        if self._current == 0.0:
            return 0.0
        remaining = self._last_error_time + self._current - self._clock()
        return max(0.0, remaining)

    def get_current_backoff(self) -> float:
        return self._current

    def get_last_error_time(self) -> float:
        return self._last_error_time

    def get_initial_backoff(self) -> float:
        return self._initial

    def get_max_backoff(self) -> float:
        return self._max
