"""Shape contracts: declared (shape, dtype, sentinel) intent for kernels.

The ShapeFlow abstract interpreter (openr_tpu/analysis/shapeflow.py) walks
every jit-reachable function propagating symbolic shapes, dtypes, and the
INF-sentinel lattice. Kernel authors can *declare* what a function expects
instead of leaving the interpreter to infer it:

    @shape_contract("a:[B,B]:int32:inf", "b:[B,B]:int32:inf",
                    returns="[B,B]:int32:inf")
    def _mp(a, b):
        return jnp.min(jnp.minimum(a[:, :, None] + b[None, :, :], INF),
                       axis=1)

Contract grammar (one string per parameter, in any order):

    <param>:[<dim>,<dim>,...]:<dtype>[:inf]

  - <param>   must name a parameter of the decorated function (checked at
    import time, so a typo fails the test run, not a trace);
  - <dim>     a symbolic dimension name (`n_pad`, `S`, `B` — unified by
    name across the contract and against module constants like
    `_FW_BLOCK = 128`), or an integer literal;
  - <dtype>   int32 / float32 / bool / ... (jnp dtype spelling);
  - :inf      marks the value as living in the INF-sentinel domain
    (maybe-INF: every element is <= INF). The sentinel-overflow rule
    seeds from this marker.

`returns=` takes the same spec with the leading name optional.

The decorator is a pure annotation: it parses + validates the strings and
stores them on `fn.__shape_contract__`, then returns the *original*
function object — zero wrapper, zero tracing overhead, safe under
jax.jit/shard_map. The analysis side re-parses the same grammar from the
AST (it never imports kernel modules), so this module is the single
source of truth for the syntax.
"""

from __future__ import annotations

import inspect
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

Dim = Union[int, str]

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_DTYPES = {
    "bool",
    "int8",
    "int16",
    "int32",
    "int64",
    "uint8",
    "uint16",
    "uint32",
    "uint64",
    "bfloat16",
    "float16",
    "float32",
    "float64",
}


class ContractError(ValueError):
    """A malformed contract string (raised at import time)."""


@dataclass(frozen=True)
class ArraySpec:
    """One operand's declared (shape, dtype, sentinel) triple."""

    name: str  # parameter name; '' for an anonymous returns spec
    dims: Tuple[Dim, ...]  # symbolic names and/or integer literals
    dtype: str
    inf: bool = False  # True: values live in the INF-sentinel domain

    @property
    def rank(self) -> int:
        return len(self.dims)

    def render(self) -> str:
        dims = ",".join(str(d) for d in self.dims)
        tail = ":inf" if self.inf else ""
        head = f"{self.name}:" if self.name else ""
        return f"{head}[{dims}]:{self.dtype}{tail}"


@dataclass
class Contract:
    params: Dict[str, ArraySpec] = field(default_factory=dict)
    returns: Optional[ArraySpec] = None

    def specs(self) -> List[ArraySpec]:
        out = list(self.params.values())
        if self.returns is not None:
            out.append(self.returns)
        return out


def parse_spec(text: str, anonymous_ok: bool = False) -> ArraySpec:
    """Parse one `name:[dims]:dtype[:inf]` spec string."""
    raw = text.strip()
    lb = raw.find("[")
    rb = raw.find("]")
    if lb < 0 or rb < lb:
        raise ContractError(f"contract spec needs a [dims] block: {text!r}")
    name = raw[:lb].rstrip(":").strip()
    if name and not _NAME_RE.match(name):
        raise ContractError(f"bad operand name in contract spec: {text!r}")
    if not name and not anonymous_ok:
        raise ContractError(
            f"parameter contract spec needs a leading name: {text!r}"
        )
    dims_text = raw[lb + 1 : rb].strip()
    dims: List[Dim] = []
    if dims_text:
        for tok in dims_text.split(","):
            tok = tok.strip()
            if not tok:
                raise ContractError(f"empty dim in contract spec: {text!r}")
            if tok.lstrip("-").isdigit():
                val = int(tok)
                if val <= 0:
                    raise ContractError(
                        f"dims must be positive: {text!r}"
                    )
                dims.append(val)
            elif _NAME_RE.match(tok):
                dims.append(tok)
            else:
                raise ContractError(f"bad dim token {tok!r} in {text!r}")
    tail = raw[rb + 1 :].lstrip(":")
    parts = [p for p in tail.split(":") if p]
    if not parts:
        raise ContractError(f"contract spec needs a dtype: {text!r}")
    dtype = parts[0]
    if dtype not in _DTYPES:
        raise ContractError(f"unknown dtype {dtype!r} in {text!r}")
    inf = False
    for extra in parts[1:]:
        if extra == "inf":
            inf = True
        else:
            raise ContractError(f"unknown contract marker {extra!r} in {text!r}")
    return ArraySpec(name=name, dims=tuple(dims), dtype=dtype, inf=inf)


def parse_contract(
    specs: Tuple[str, ...], returns: Optional[str] = None
) -> Contract:
    contract = Contract()
    for text in specs:
        spec = parse_spec(text)
        if spec.name in contract.params:
            raise ContractError(f"duplicate contract for {spec.name!r}")
        contract.params[spec.name] = spec
    if returns is not None:
        contract.returns = parse_spec(returns, anonymous_ok=True)
    return contract


def shape_contract(*specs: str, returns: Optional[str] = None):
    """Attach a parsed shape contract to a kernel function.

    Validates the grammar and the parameter names eagerly (import time),
    then returns the original function untouched — the contract is an
    annotation the static analyzer reads, never a runtime wrapper.
    """
    contract = parse_contract(specs, returns=returns)

    def attach(fn):
        try:
            sig_params = set(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            sig_params = None
        if sig_params is not None:
            unknown = set(contract.params) - sig_params
            if unknown:
                raise ContractError(
                    f"@shape_contract on {fn.__name__}: "
                    f"{sorted(unknown)} are not parameters "
                    f"(has {sorted(sig_params)})"
                )
        fn.__shape_contract__ = contract
        return fn

    return attach
