"""openr-tpu: a TPU-native link-state routing platform.

A ground-up rebuild of the capabilities of Facebook Open/R (reference:
/root/reference) designed TPU-first: the distributed-protocol shell (discovery,
replicated LSDB, link monitoring, FIB programming, control API) is host-side
Python/C++ systems code, while the Decision module's shortest-path computation
runs as a batched min-plus solver on TPU via JAX/XLA/Pallas, sharded over a
device mesh with pjit.

Layout (mirrors SURVEY.md §2 component inventory):
  types.py        wire types (thrift-IDL equivalents, openr/if/*.thrift)
  utils/          backoff, debounce, throttle, step detector, constants
  messaging/      in-process pub/sub queues (openr/messaging/)
  lsdb/           LinkState graph + PrefixState (openr/decision/LinkState.*)
  solver/         CPU oracle + TPU batched SPF solvers (openr/decision/Decision.cpp)
  ops/            JAX/Pallas min-plus kernels and nexthop extraction
  parallel/       device mesh + sharding for the batched solver
  kvstore/        replicated CRDT store + flooding (openr/kvstore/)
  decision/       Decision module shell (openr/decision/Decision.cpp)
  spark/          neighbor discovery FSM (openr/spark/)
  linkmonitor/    link state + peering (openr/link-monitor/)
  fib/            route programming proxy (openr/fib/)
  prefix_manager/ prefix origination (openr/prefix-manager/)
  allocators/     distributed value election (openr/allocators/)
  platform/       FIB service + netlink seam (openr/platform/, openr/nl/)
  config/         typed config (openr/config/)
  ctrl/           control API surface (openr/ctrl-server/)
  cli/            breeze-style CLI (openr/py/)
  monitor/        counters + structured events (openr/monitor/)
  watchdog/       liveness watchdog (openr/watchdog/)
"""

__version__ = "0.1.0"
