"""Typed configuration (openr/config/ equivalent)."""

from openr_tpu.config.config import (
    AreaConfig,
    Config,
    JournalConfigSection,
    KvstoreConfig,
    LinkMonitorConfig,
    MonitorConfig,
    OpenrConfig,
    PrefixAllocationConfig,
    SparkConfig,
    StepDetectorConfig,
    StreamConfigSection,
    WatchdogConfig,
)

__all__ = [
    "AreaConfig",
    "Config",
    "JournalConfigSection",
    "KvstoreConfig",
    "LinkMonitorConfig",
    "MonitorConfig",
    "OpenrConfig",
    "PrefixAllocationConfig",
    "SparkConfig",
    "StepDetectorConfig",
    "StreamConfigSection",
    "WatchdogConfig",
]
