"""Typed configuration (openr/config/ equivalent)."""

from openr_tpu.config.config import (
    AreaConfig,
    Config,
    KvstoreConfig,
    LinkMonitorConfig,
    MonitorConfig,
    OpenrConfig,
    PrefixAllocationConfig,
    SparkConfig,
    StepDetectorConfig,
    StreamConfigSection,
    WatchdogConfig,
)

__all__ = [
    "AreaConfig",
    "Config",
    "KvstoreConfig",
    "LinkMonitorConfig",
    "MonitorConfig",
    "OpenrConfig",
    "PrefixAllocationConfig",
    "SparkConfig",
    "StepDetectorConfig",
    "StreamConfigSection",
    "WatchdogConfig",
]
