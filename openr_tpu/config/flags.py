"""Legacy command-line flag bridge.

Equivalent of the reference's gflags layer (openr/common/Flags.cpp, 111
gflags) and its translator GflagConfig::createConfigFromGflag
(openr/config/GflagConfig.h): a daemon invoked with legacy-style flags gets
a full OpenrConfig built from them, while `--config <file>` short-circuits
to the thrift-JSON config file exactly like Main.cpp:199-207 (file wins;
flags are the fallback path).

Only the flags with behavior in this rebuild are bridged; each maps onto
the OpenrConfig field that GflagConfig targets. Unknown flags fail fast
(argparse) rather than being silently dropped.
"""

from __future__ import annotations

import argparse
from typing import List, Optional, Sequence

from openr_tpu.config.config import (
    AreaConfig,
    Config,
    OpenrConfig,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="openr_tpu",
        description="Open/R-compatible routing daemon (TPU-native rebuild)",
    )
    p.add_argument("--config", default=None, help="thrift-JSON config file; overrides all other flags (Main.cpp:199)")
    # identity / areas (Flags.cpp: node_name, domain, areas)
    p.add_argument("--node_name", default="")
    p.add_argument("--domain", default="openr")
    p.add_argument("--areas", default="", help="comma-separated area ids")
    # ports (Flags.cpp: openr_ctrl_port, fib_handler_port, spark_mcast_port)
    p.add_argument("--openr_ctrl_port", type=int, default=2018)
    p.add_argument("--fib_handler_port", type=int, default=60100)
    p.add_argument("--spark_mcast_port", type=int, default=6666)
    # interface selection (Flags.cpp: iface_regex_include/exclude,
    # redistribute_ifaces)
    p.add_argument("--iface_regex_include", default="")
    p.add_argument("--iface_regex_exclude", default="")
    p.add_argument("--redistribute_ifaces", default="")
    # spark timers (Flags.cpp/OpenrConfig.thrift:52-63)
    p.add_argument("--spark_hold_time_s", type=float, default=10.0)
    p.add_argument("--spark_keepalive_time_s", type=float, default=2.0)
    p.add_argument("--spark_hello_time_s", type=float, default=20.0)
    p.add_argument("--spark_fastinit_hello_time_ms", type=float, default=500.0)
    p.add_argument("--spark_gr_hold_time_s", type=float, default=30.0)
    # kvstore (Flags.cpp: kvstore_key_ttl_ms, kvstore_sync_interval_s,
    # enable_flood_optimization, is_flood_root)
    p.add_argument("--kvstore_key_ttl_ms", type=int, default=300_000)
    p.add_argument("--kvstore_sync_interval_s", type=int, default=60)
    p.add_argument("--enable_flood_optimization", action="store_true")
    p.add_argument("--noenable_native_kvstore", dest="enable_native_kvstore", action="store_false", default=True, help="disable the C++ KvStore engine even when built")
    p.add_argument("--is_flood_root", action="store_true")
    # decision (Runbook.md:425-435 debounce; rebuild's backend selector)
    p.add_argument("--decision_debounce_min_ms", type=float, default=10.0)
    p.add_argument("--decision_debounce_max_ms", type=float, default=250.0)
    p.add_argument("--enable_lfa", action="store_true")
    p.add_argument("--decision_solver_backend", choices=("cpu", "tpu"), default="cpu")
    # link monitor dampening (OpenrConfig.thrift:36-37)
    p.add_argument("--link_flap_initial_backoff_ms", type=int, default=60_000)
    p.add_argument("--link_flap_max_backoff_ms", type=int, default=300_000)
    p.add_argument("--enable_rtt_metric", dest="enable_rtt_metric", action="store_true", default=True)
    p.add_argument("--noenable_rtt_metric", dest="enable_rtt_metric", action="store_false")
    # feature toggles (Flags.cpp enable_*)
    p.add_argument("--dryrun", action="store_true")
    p.add_argument("--enable_v4", dest="enable_v4", action="store_true", default=True)
    p.add_argument("--noenable_v4", dest="enable_v4", action="store_false")
    p.add_argument("--enable_netlink_fib_handler", action="store_true")
    p.add_argument("--enable_fib_agent", action="store_true", help="program routes through the standalone native agent (platform_linux equivalent)")
    p.add_argument("--enable_segment_routing", action="store_true")
    p.add_argument("--enable_rib_policy", action="store_true")
    p.add_argument("--enable_ordered_fib_programming", action="store_true")
    p.add_argument("--enable_bgp_peering", action="store_true")
    # TLS (Flags.cpp: enable_secure_thrift_server, x509_*_path,
    # tls_acceptable_peers)
    p.add_argument("--enable_secure_thrift_server", action="store_true")
    p.add_argument("--x509_cert_path", default=None)
    p.add_argument("--x509_key_path", default=None)
    p.add_argument("--x509_ca_path", default=None)
    p.add_argument("--tls_acceptable_peers", default="", help="comma-separated peer common names; empty accepts any CA-verified peer")
    # prefix allocation (Flags.cpp: enable_prefix_alloc, seed_prefix,
    # alloc_prefix_len, set/override_loopback_addr, loopback_iface)
    p.add_argument("--enable_prefix_alloc", action="store_true")
    p.add_argument("--seed_prefix", default=None)
    p.add_argument("--alloc_prefix_len", type=int, default=None)
    p.add_argument("--set_loopback_address", action="store_true")
    p.add_argument("--override_loopback_addr", action="store_true")
    p.add_argument("--loopback_iface", default="lo")
    # watchdog (OpenrConfig.thrift:65-69)
    p.add_argument("--enable_watchdog", dest="enable_watchdog", action="store_true", default=True)
    p.add_argument("--noenable_watchdog", dest="enable_watchdog", action="store_false")
    p.add_argument("--watchdog_interval_s", type=int, default=20)
    p.add_argument("--watchdog_threshold_s", type=int, default=300)
    p.add_argument("--memory_limit_mb", type=int, default=800)
    # eor / cold start (Main.cpp:233-235)
    p.add_argument("--eor_time_s", type=int, default=None)
    # persistent store (Flags.cpp: config_store_filepath)
    p.add_argument("--config_store_filepath", default="/tmp/openr_persistent_config_store.bin")
    return p


def _csv(value: str) -> List[str]:
    return [v for v in (s.strip() for s in value.split(",")) if v]


def config_from_flags(args: argparse.Namespace) -> Config:
    """GflagConfig::createConfigFromGflag equivalent: flags -> OpenrConfig."""
    if args.config:
        return Config.load_file(args.config)
    cfg = OpenrConfig(node_name=args.node_name, domain=args.domain)
    # flag-configured areas match everything, as the reference's
    # GflagConfig does (openr/config/GflagConfig.h:57-63); per-area regex
    # scoping needs the config-file path
    cfg.areas = [
        AreaConfig(a, interface_regexes=[".*"], neighbor_regexes=[".*"])
        for a in _csv(args.areas)
    ]
    cfg.openr_ctrl_port = args.openr_ctrl_port
    cfg.fib_port = args.fib_handler_port
    cfg.dryrun = args.dryrun
    cfg.enable_v4 = args.enable_v4
    cfg.enable_netlink_fib_handler = args.enable_netlink_fib_handler
    cfg.enable_fib_agent = args.enable_fib_agent
    cfg.enable_segment_routing = args.enable_segment_routing
    cfg.enable_rib_policy = args.enable_rib_policy
    cfg.enable_ordered_fib_programming = args.enable_ordered_fib_programming
    cfg.enable_bgp_peering = args.enable_bgp_peering
    cfg.enable_secure_thrift_server = args.enable_secure_thrift_server
    cfg.x509_cert_path = args.x509_cert_path
    cfg.x509_key_path = args.x509_key_path
    cfg.x509_ca_path = args.x509_ca_path
    cfg.tls_acceptable_peers = _csv(args.tls_acceptable_peers)
    cfg.eor_time_s = args.eor_time_s

    sp = cfg.spark_config
    sp.neighbor_discovery_port = args.spark_mcast_port
    sp.hello_time_s = args.spark_hello_time_s
    sp.fastinit_hello_time_ms = args.spark_fastinit_hello_time_ms
    sp.keepalive_time_s = args.spark_keepalive_time_s
    sp.hold_time_s = args.spark_hold_time_s
    sp.graceful_restart_time_s = args.spark_gr_hold_time_s

    kv = cfg.kvstore_config
    kv.key_ttl_ms = args.kvstore_key_ttl_ms
    kv.sync_interval_s = args.kvstore_sync_interval_s
    kv.enable_flood_optimization = args.enable_flood_optimization
    kv.enable_native_store = args.enable_native_kvstore
    kv.is_flood_root = args.is_flood_root

    dc = cfg.decision_config
    dc.debounce_min_ms = args.decision_debounce_min_ms
    dc.debounce_max_ms = args.decision_debounce_max_ms
    dc.compute_lfa_paths = args.enable_lfa
    dc.solver_backend = args.decision_solver_backend

    lm = cfg.link_monitor_config
    lm.linkflap_initial_backoff_ms = args.link_flap_initial_backoff_ms
    lm.linkflap_max_backoff_ms = args.link_flap_max_backoff_ms
    lm.use_rtt_metric = args.enable_rtt_metric
    lm.include_interface_regexes = _csv(args.iface_regex_include)
    lm.exclude_interface_regexes = _csv(args.iface_regex_exclude)
    lm.redistribute_interface_regexes = _csv(args.redistribute_ifaces)

    cfg.enable_prefix_allocation = args.enable_prefix_alloc
    pa = cfg.prefix_allocation_config
    pa.seed_prefix = args.seed_prefix
    pa.allocate_prefix_len = args.alloc_prefix_len
    pa.set_loopback_addr = args.set_loopback_address
    pa.override_loopback_addr = args.override_loopback_addr
    pa.loopback_interface = args.loopback_iface

    cfg.enable_watchdog = args.enable_watchdog
    wd = cfg.watchdog_config
    wd.interval_s = args.watchdog_interval_s
    wd.thread_timeout_s = args.watchdog_threshold_s
    wd.max_memory_mb = args.memory_limit_mb

    return Config(cfg)


def parse_flags(argv: Optional[Sequence[str]] = None):
    """(Config, parsed args) from argv — the daemon entry's front door."""
    args = build_parser().parse_args(argv)
    return config_from_flags(args), args
