"""Typed daemon configuration.

Behavioral port of openr/if/OpenrConfig.thrift:180-244 (the OpenrConfig
struct with per-module sub-structs and defaults) and openr/config/Config.h
(the accessor class deriving per-area regex sets and feature predicates).
Loaded from a JSON file exactly like the reference loads thrift-JSON
(Main.cpp:199-207); unknown fields are rejected so typos fail loudly
(Config::Config runs a parse-validate pass).
"""

from __future__ import annotations

import dataclasses
import json
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from openr_tpu.types import PrefixForwardingAlgorithm, PrefixForwardingType


@dataclass
class KvstoreFloodRate:
    flood_msg_per_sec: int = 0
    flood_msg_burst_size: int = 0


@dataclass
class KvstoreConfig:
    """OpenrConfig.thrift KvstoreConfig:19."""

    key_ttl_ms: int = 300_000
    sync_interval_s: int = 60
    ttl_decrement_ms: int = 1
    flood_rate: Optional[KvstoreFloodRate] = None
    set_leaf_node: bool = False
    key_prefix_filters: List[str] = field(default_factory=list)
    key_originator_id_filters: List[str] = field(default_factory=list)
    enable_flood_optimization: bool = False
    is_flood_root: bool = False
    # keep the key->Value table + CRDT merge in the native C++ engine
    # (native/kvstore) when the library is available
    enable_native_store: bool = True
    # flood-storm damping: per-(key, originator) exponential penalty with a
    # hold-down (docs/Robustness.md "Hostile-network hardening")
    damping_enabled: bool = True
    damping_half_life_s: float = 8.0
    damping_max_hold_s: float = 30.0
    damping_suppress_limit: float = 8000.0
    damping_reuse_limit: float = 2000.0
    # peer-health quarantine ladder (healthy → suspect → quarantined →
    # probing) with probe-driven recovery hysteresis
    quarantine_enabled: bool = True
    peer_suspect_failures: int = 3
    peer_quarantine_failures: int = 6
    peer_probe_min_backoff_s: float = 0.1
    peer_probe_max_backoff_s: float = 2.0
    peer_probe_successes: int = 2
    # adaptive anti-entropy: `sync_interval_s` rounds arm only when flood
    # health (duplicate ratio / failures / wire rejects) is off budget
    anti_entropy_enabled: bool = True
    flood_duplicate_budget: float = 0.5


@dataclass
class LinkMonitorConfig:
    """OpenrConfig.thrift LinkMonitorConfig:35."""

    linkflap_initial_backoff_ms: int = 60_000
    linkflap_max_backoff_ms: int = 300_000
    use_rtt_metric: bool = True
    include_interface_regexes: List[str] = field(default_factory=list)
    exclude_interface_regexes: List[str] = field(default_factory=list)
    redistribute_interface_regexes: List[str] = field(default_factory=list)


@dataclass
class StepDetectorConfig:
    """OpenrConfig.thrift StepDetectorConfig:44."""

    fast_window_size: int = 10
    slow_window_size: int = 60
    lower_threshold: int = 2
    upper_threshold: int = 5
    ads_threshold: int = 500


@dataclass
class SparkConfig:
    """OpenrConfig.thrift SparkConfig:52."""

    neighbor_discovery_port: int = 6666
    hello_time_s: float = 20.0
    fastinit_hello_time_ms: float = 500.0
    keepalive_time_s: float = 2.0
    hold_time_s: float = 10.0
    graceful_restart_time_s: float = 30.0
    # graceful-restart warm boot (docs/Robustness.md "Graceful restart &
    # warm boot"): when set, the daemon's stop path floods restarting
    # hellos so neighbors enter the RESTART hold instead of dropping the
    # adjacency. Opt-in: a drained permanent shutdown should NOT leave
    # neighbors holding routes through the GR window.
    graceful_restart_enabled: bool = False
    step_detector_conf: StepDetectorConfig = field(
        default_factory=StepDetectorConfig
    )


@dataclass
class WatchdogConfig:
    """OpenrConfig.thrift WatchdogConfig:65."""

    interval_s: int = 20
    thread_timeout_s: int = 300
    max_memory_mb: int = 800


@dataclass
class MonitorConfig:
    """OpenrConfig.thrift MonitorConfig:71 + the continuous-telemetry
    knobs (docs/Monitoring.md): the event-log ring bound, the
    eviction-proof convergence-rollup window geometry, and the optional
    metrics push sink."""

    # bound of the LogSample ring (monitor/monitor.py). Samples evicted
    # from the ring are still covered by the windowed rollup, which folds
    # spans at record time — raising this buys raw-sample retention, not
    # report completeness.
    max_event_log: int = 100
    # convergence-rollup window geometry: per-stage histograms aggregate
    # into rollup_window_s-wide wall-clock windows, bounded at
    # rollup_max_windows (older windows fold into the evicted-events
    # count; their samples stay in the cumulative layer)
    rollup_window_s: float = 60.0
    rollup_max_windows: int = 120
    # metrics push mode: render the Prometheus exposition every
    # exporter_push_interval_s and push it to a sink — "host:port" (TCP)
    # or a file path (atomic replace) — with exponential backoff on
    # failure. None (default) = scrape-only.
    exporter_push_target: Optional[str] = None
    exporter_push_interval_s: float = 15.0


@dataclass
class PrefixAllocationConfig:
    """OpenrConfig.thrift PrefixAllocationConfig:98."""

    loopback_interface: str = "lo"
    set_loopback_addr: bool = False
    override_loopback_addr: bool = False
    prefix_allocation_mode: str = "DYNAMIC_LEAF_NODE"
    seed_prefix: Optional[str] = None
    allocate_prefix_len: Optional[int] = None


@dataclass
class AreaConfig:
    """OpenrConfig.thrift AreaConfig:135 — area id + interface/neighbor
    regex membership."""

    area_id: str
    interface_regexes: List[str] = field(default_factory=list)
    neighbor_regexes: List[str] = field(default_factory=list)


@dataclass
class DecisionConfigSection:
    """Decision knobs (Flags + OpenrConfig eor/debounce semantics) +
    the rebuild's solver backend selector (BASELINE.json north star)."""

    debounce_min_ms: float = 10.0
    debounce_max_ms: float = 250.0
    compute_lfa_paths: bool = False
    solver_backend: str = "cpu"  # 'cpu' | 'tpu'
    # (batch, graph) device-mesh shape for the tpu backend, e.g. [4, 2]
    # on a v5e-8; None/empty = single device
    solver_mesh: Optional[List[int]] = None
    # solver fault domain (docs/Robustness.md): supervision wraps the tpu
    # backend with classified retries, a CPU-fallback circuit breaker,
    # probe-driven recovery, and an every-Nth-solve warm-state audit
    solver_supervised: bool = True
    solver_failure_threshold: int = 3
    solver_max_attempts: int = 2
    solver_deadline_s: float = 30.0
    solver_probe_interval_s: float = 5.0
    solver_probe_successes: int = 2
    solver_audit_interval: int = 0
    # partial-mesh degradation: device-loss streaks shrink the solver
    # mesh over surviving chips before the breaker trips to the oracle
    solver_mesh_degrade: bool = True
    # resident blocked-FW all-pairs matrix (docs/Apsp.md) for areas up to
    # solver_apsp_max_nodes real nodes; keeps DeltaPath enabled under
    # compute_lfa_paths and serves KSP layer seeding + TE hard-scoring
    solver_apsp: bool = True
    solver_apsp_max_nodes: int = 4096
    # solver flight recorder (docs/Monitoring.md "Flight recorder &
    # profiling"): per-area SolveTrace ring bound, sampled phase-timing
    # cadence (every Nth solve takes phase-seam barriers; 0 disables),
    # and an optional forensics-dump artifact directory
    solver_trace_ring: int = 64
    solver_trace_sample_every: int = 16
    solver_forensics_dir: Optional[str] = None
    # device-memory observatory (docs/Monitoring.md "Device-memory
    # observatory"): capacity admission keeps this fraction of device
    # capacity free when predict_fit gates a layout, and an explicit
    # capacity override in bytes stands in when the backend exposes no
    # memory_stats (0 = auto-detect)
    solver_mem_headroom_frac: float = 0.10
    solver_mem_capacity_bytes: int = 0


@dataclass
class FibConfigSection:
    """Fib cold-start + warm-boot knobs (docs/Fib.md "Cold start, EOR and
    warm boot")."""

    # hold before the first full sync when NO eor_time_s gates it
    # (Fib.cpp:73-76 coldStartDuration). The seed's 0.0 default synced —
    # and wiped any surviving agent routes — before Decision had ever
    # converged; 1s gives the LSDB a fighting chance, and a node whose
    # agent carries warm-boot (stale) routes additionally gates the sync
    # on the first Decision route db regardless of this hold.
    cold_start_duration_s: float = 1.0
    # warm boot: routes recovered from the agent at start are marked
    # stale and kept forwarding until Decision's first converged route db
    # reconciles them; if convergence never arrives within this deadline
    # the stale set is force-flushed with a forensics dump
    # (fib.stale_sweep_deadline_s in the ISSUE/ops docs)
    stale_sweep_deadline_s: float = 300.0


@dataclass
class StreamConfigSection:
    """Streaming control plane knobs (docs/Streaming.md): the ctrl
    server's delta-subscription fan-out bounds and the admission queue
    in front of expensive RPCs."""

    # frames buffered per subscriber before coalescing kicks in
    subscriber_max_pending: int = 64
    # merged-delta entry budget after coalescing; beyond it the
    # subscriber's queue is dropped and a marked snapshot-resync is sent
    coalesce_budget: int = 4096
    # hard cap on concurrent subscriptions (typed server-busy beyond)
    max_subscribers: int = 1024
    # encode each delta once per filter-equivalence class and share the
    # bytes across subscribers (docs/Streaming.md "Shared-encode
    # fan-out"); false restores the per-subscriber re-encode path for
    # before/after measurement
    shared_encode: bool = True
    # admission queue for runTeOptimize / getRouteDbComputed /
    # getConvergenceReport: concurrent cost units, bounded queue wait,
    # queue depth caps (global + per client — the fairness bound)
    admission_capacity: int = 2
    admission_max_wait_s: float = 2.0
    admission_max_queue: int = 16
    admission_max_queue_per_client: int = 4


@dataclass
class JournalConfigSection:
    """State-journal knobs (docs/Journal.md): bounded record ring +
    compacted base, the sampled-overhead guard cadence, and the optional
    crash-safe on-disk log."""

    enabled: bool = False
    # in-memory record ring bound; older records fold into the base
    ring_size: int = 4096
    # per-(area, key) publication-history entries for `kvstore history`
    key_history: int = 16
    # every Nth record takes perf_counter stamps into journal.record_ms
    # (0 disables the guard, never the recording)
    sample_every: int = 16
    # durable log file (RecordLog framing); None = memory only
    path: Optional[str] = None
    # append-batch debounce; a crash loses at most this window
    flush_interval_s: float = 0.2
    # appended-tail size that forces the next flush to compact
    min_compact_bytes: int = 65536


@dataclass
class OpenrConfig:
    """OpenrConfig.thrift OpenrConfig:180."""

    node_name: str = ""
    domain: str = "openr"
    areas: List[AreaConfig] = field(default_factory=list)
    listen_addr: str = "::"
    openr_ctrl_port: int = 2018
    dryrun: bool = False
    enable_v4: bool = True
    enable_netlink_fib_handler: bool = False
    # route programming through the standalone native agent binary
    # (onl_fib_agent, the platform_linux equivalent) at fib_port instead of
    # the in-process netlink handler
    enable_fib_agent: bool = False
    eor_time_s: Optional[int] = None
    prefix_forwarding_type: PrefixForwardingType = PrefixForwardingType.IP
    prefix_forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    )
    enable_segment_routing: bool = False
    prefix_min_nexthop: Optional[int] = None
    kvstore_config: KvstoreConfig = field(default_factory=KvstoreConfig)
    link_monitor_config: LinkMonitorConfig = field(
        default_factory=LinkMonitorConfig
    )
    spark_config: SparkConfig = field(default_factory=SparkConfig)
    decision_config: DecisionConfigSection = field(
        default_factory=DecisionConfigSection
    )
    enable_watchdog: bool = False
    watchdog_config: WatchdogConfig = field(default_factory=WatchdogConfig)
    enable_prefix_allocation: bool = False
    prefix_allocation_config: PrefixAllocationConfig = field(
        default_factory=PrefixAllocationConfig
    )
    enable_ordered_fib_programming: bool = False
    fib_config: FibConfigSection = field(default_factory=FibConfigSection)
    fib_port: int = 60100
    enable_rib_policy: bool = False
    monitor_config: MonitorConfig = field(default_factory=MonitorConfig)
    stream_config: StreamConfigSection = field(
        default_factory=StreamConfigSection
    )
    journal_config: JournalConfigSection = field(
        default_factory=JournalConfigSection
    )
    enable_bgp_peering: bool = False
    bgp_use_igp_metric: bool = False
    # mutual TLS for the ctrl server and KvStore TCP peering
    # (openr/Main.cpp:517-543 TLS setup semantics)
    enable_secure_thrift_server: bool = False
    x509_cert_path: Optional[str] = None
    x509_key_path: Optional[str] = None
    x509_ca_path: Optional[str] = None
    tls_acceptable_peers: List[str] = field(default_factory=list)


_ENUM_FIELDS = {
    "prefix_forwarding_type": PrefixForwardingType,
    "prefix_forwarding_algorithm": PrefixForwardingAlgorithm,
}


def _from_dict(cls, data: Dict[str, Any]):
    """Recursive dataclass hydration; unknown keys raise (validate pass)."""
    field_map = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for key, value in data.items():
        if key not in field_map:
            raise ValueError(f"unknown config field {cls.__name__}.{key}")
        f = field_map[key]
        if key in _ENUM_FIELDS and isinstance(value, str):
            value = _ENUM_FIELDS[key][value]
        elif (
            f.default_factory is not dataclasses.MISSING  # type: ignore
            and dataclasses.is_dataclass(f.default_factory)
            and isinstance(value, dict)
        ):
            value = _from_dict(f.default_factory, value)
        elif key == "areas" and isinstance(value, list):
            value = [_from_dict(AreaConfig, v) for v in value]
        elif key == "flood_rate" and isinstance(value, dict):
            value = _from_dict(KvstoreFloodRate, value)
        elif key == "step_detector_conf" and isinstance(value, dict):
            value = _from_dict(StepDetectorConfig, value)
        kwargs[key] = value
    return cls(**kwargs)


class AreaConfiguration:
    """Compiled area membership matcher (Config.h:21, derived regex sets)."""

    def __init__(self, area: AreaConfig) -> None:
        self.area_id = area.area_id
        self._iface_res = [re.compile(r) for r in area.interface_regexes]
        self._neighbor_res = [re.compile(r) for r in area.neighbor_regexes]

    def matches_interface(self, if_name: str) -> bool:
        return any(r.fullmatch(if_name) for r in self._iface_res)

    def matches_neighbor(self, node_name: str) -> bool:
        return any(r.fullmatch(node_name) for r in self._neighbor_res)


class Config:
    """Accessor wrapper (openr/config/Config.h:34): feature predicates +
    derived per-area regex matchers."""

    DEFAULT_AREA = "0"

    def __init__(self, config: OpenrConfig) -> None:
        if not config.node_name:
            raise ValueError("node_name is required")
        self.config = config
        self.area_configurations = [
            AreaConfiguration(a) for a in config.areas
        ]

    @staticmethod
    def load_file(path: str) -> "Config":
        """Load thrift-JSON-style config file (Main.cpp:199-207)."""
        with open(path) as f:
            data = json.load(f)
        return Config(_from_dict(OpenrConfig, data))

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "Config":
        return Config(_from_dict(OpenrConfig, data))

    # -- derived -----------------------------------------------------------

    @property
    def node_name(self) -> str:
        return self.config.node_name

    def get_area_ids(self) -> List[str]:
        if not self.config.areas:
            return [self.DEFAULT_AREA]
        return [a.area_id for a in self.config.areas]

    def get_area_for(
        self, if_name: str = "", neighbor_name: str = ""
    ) -> Optional[str]:
        """First area whose regexes match (Spark area negotiation seam)."""
        if not self.area_configurations:
            return self.DEFAULT_AREA
        for area in self.area_configurations:
            if if_name and area.matches_interface(if_name):
                return area.area_id
            if neighbor_name and area.matches_neighbor(neighbor_name):
                return area.area_id
        return None

    # -- feature predicates (Config.h:60-123) ------------------------------

    def is_v4_enabled(self) -> bool:
        return self.config.enable_v4

    def is_segment_routing_enabled(self) -> bool:
        return self.config.enable_segment_routing

    def is_ordered_fib_programming_enabled(self) -> bool:
        return self.config.enable_ordered_fib_programming

    def is_netlink_fib_handler_enabled(self) -> bool:
        return self.config.enable_netlink_fib_handler

    def is_prefix_allocation_enabled(self) -> bool:
        return self.config.enable_prefix_allocation

    def is_rib_policy_enabled(self) -> bool:
        return self.config.enable_rib_policy

    def is_watchdog_enabled(self) -> bool:
        return self.config.enable_watchdog

    def is_dryrun(self) -> bool:
        return self.config.dryrun
