"""ctypes bindings for the native netlink library.

Auto-builds openr_tpu/_native/libopenr_nl.so from native/nl via `make` on
first use if the artifact is missing (the image bakes g++; no pip installs).
All calls are thin wrappers over the C ABI in native/nl/onl_netlink.h; the
blocking transactional calls are fast (single send+drain), so async callers
run them via loop.run_in_executor (see platform/netlink_fib.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libopenr_nl.so")
_MAKE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native"
)

# openr programs kernel routes with its own protocol id so it can identify
# and clean its routes (reference uses protocol 99, openr/nl docs)
RT_PROT_OPENR = 99
RT_TABLE_MAIN = 254

MPLS_NONE, MPLS_PUSH, MPLS_SWAP, MPLS_PHP = 0, 1, 2, 3


class NetlinkError(RuntimeError):
    pass


class _CLink(ctypes.Structure):
    _fields_ = [
        ("ifindex", ctypes.c_int32),
        ("up", ctypes.c_int32),
        ("name", ctypes.c_char * 32),
    ]


class _CAddr(ctypes.Structure):
    _fields_ = [
        ("ifindex", ctypes.c_int32),
        ("prefixlen", ctypes.c_int32),
        ("family", ctypes.c_int32),
        ("addr", ctypes.c_char * 64),
    ]


class _CNextHop(ctypes.Structure):
    _fields_ = [
        ("via", ctypes.c_char * 64),
        ("ifindex", ctypes.c_int32),
        ("weight", ctypes.c_int32),
        ("mpls_action", ctypes.c_int32),
        ("num_labels", ctypes.c_int32),
        ("labels", ctypes.c_int32 * 8),
    ]


class _CEvent(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_int32),
        ("ifindex", ctypes.c_int32),
        ("up", ctypes.c_int32),
        ("prefixlen", ctypes.c_int32),
        ("name", ctypes.c_char * 32),
        ("addr", ctypes.c_char * 64),
        ("state", ctypes.c_int32),
        ("lladdr", ctypes.c_char * 24),
    ]


class _CNeigh(ctypes.Structure):
    _fields_ = [
        ("ifindex", ctypes.c_int32),
        ("family", ctypes.c_int32),
        ("state", ctypes.c_int32),
        ("is_reachable", ctypes.c_int32),
        ("dest", ctypes.c_char * 64),
        ("lladdr", ctypes.c_char * 24),
    ]


_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None


def _build_native() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _MAKE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO_PATH) and not _build_native():
        _lib_error = "libopenr_nl.so missing and native build failed"
        return None
    lib = ctypes.CDLL(_SO_PATH)
    lib.onl_open.restype = ctypes.c_void_p
    lib.onl_close.argtypes = [ctypes.c_void_p]
    lib.onl_strerror.argtypes = [ctypes.c_void_p]
    lib.onl_strerror.restype = ctypes.c_char_p
    lib.onl_get_links.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(_CLink),
        ctypes.c_int,
    ]
    lib.onl_get_addrs.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(_CAddr),
        ctypes.c_int,
    ]
    lib.onl_add_addr.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.onl_del_addr.argtypes = lib.onl_add_addr.argtypes
    lib.onl_add_unicast_route.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(_CNextHop),
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.onl_del_unicast_route.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.onl_add_mpls_route.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(_CNextHop),
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.onl_del_mpls_route.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.onl_get_routes.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.onl_get_neighbors.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(_CNeigh),
        ctypes.c_int,
    ]
    lib.onl_add_neighbor.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_char_p,
    ]
    lib.onl_del_neighbor.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_char_p,
    ]
    lib.onl_subscribe.argtypes = [ctypes.c_void_p]
    lib.onl_event_fd.argtypes = [ctypes.c_void_p]
    lib.onl_next_event.argtypes = [ctypes.c_void_p, ctypes.POINTER(_CEvent)]
    _lib = lib
    return lib


def native_available() -> bool:
    """True if the native library loads and a netlink socket can open."""
    lib = _load()
    if lib is None:
        return False
    h = lib.onl_open()
    if not h:
        return False
    lib.onl_close(h)
    return True


@dataclass(frozen=True)
class Link:
    ifindex: int
    name: str
    is_up: bool


@dataclass(frozen=True)
class IfAddress:
    ifindex: int
    addr: str
    prefixlen: int
    family: int


@dataclass(frozen=True)
class Neighbor:
    """Kernel neighbor-table entry (openr/nl/NetlinkTypes.h:491 Neighbor)."""

    ifindex: int
    dest: str
    lladdr: str
    family: int
    state: int
    is_reachable: bool


@dataclass(frozen=True)
class NlNextHop:
    """Kernel-facing nexthop (openr/nl/NetlinkTypes.h NextHop builder)."""

    via: str = ""
    ifindex: int = 0
    weight: int = 1
    mpls_action: int = MPLS_NONE
    labels: Tuple[int, ...] = ()


@dataclass
class NlRoute:
    """Kernel-facing route (openr/nl/NetlinkTypes.h Route builder)."""

    dest: str  # "addr/len" or "mpls:<label>"
    nexthops: List[NlNextHop] = field(default_factory=list)


class NetlinkSocket:
    """RAII handle over the native protocol socket.

    Mirrors openr/nl/NetlinkSocket.h surface: link/addr dumps, route
    add/del/dump (unicast v4/v6 + MPLS), addr management, event reads.
    """

    def __init__(self) -> None:
        lib = _load()
        if lib is None:
            raise NetlinkError(_lib_error or "native library unavailable")
        self._lib = lib
        self._h = lib.onl_open()
        if not self._h:
            raise NetlinkError("failed to open netlink socket")

    def close(self) -> None:
        if self._h:
            self._lib.onl_close(self._h)
            self._h = None

    def __enter__(self) -> "NetlinkSocket":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _err(self) -> str:
        return self._lib.onl_strerror(self._h).decode()

    def _check(self, rc: int, what: str) -> None:
        if rc < 0:
            raise NetlinkError(f"{what}: {self._err()}")

    # -- dumps -----------------------------------------------------------

    def get_links(self) -> List[Link]:
        arr = (_CLink * 1024)()
        n = self._lib.onl_get_links(self._h, arr, 1024)
        self._check(n, "get_links")
        return [
            Link(a.ifindex, a.name.decode(), bool(a.up)) for a in arr[:n]
        ]

    def get_addrs(self) -> List[IfAddress]:
        arr = (_CAddr * 4096)()
        n = self._lib.onl_get_addrs(self._h, arr, 4096)
        self._check(n, "get_addrs")
        return [
            IfAddress(a.ifindex, a.addr.decode(), a.prefixlen, a.family)
            for a in arr[:n]
        ]

    def get_neighbors(self, family: int = 0) -> List[Neighbor]:
        """Dump the kernel neighbor (ARP/NDP) table.

        Equivalent of NetlinkProtocolSocket::getAllNeighbors
        (openr/nl/NetlinkProtocolSocket.h:170); family 0 = v4+v6.
        """
        arr = (_CNeigh * 8192)()
        n = self._lib.onl_get_neighbors(self._h, family, arr, 8192)
        self._check(n, "get_neighbors")
        return [
            Neighbor(
                a.ifindex,
                a.dest.decode(),
                a.lladdr.decode(),
                a.family,
                a.state,
                bool(a.is_reachable),
            )
            for a in arr[:n]
        ]

    def add_neighbor(self, ifindex: int, dest: str, lladdr: str) -> None:
        """Install a permanent neighbor entry (NeighborBuilder add)."""
        self._check(
            self._lib.onl_add_neighbor(
                self._h, ifindex, dest.encode(), lladdr.encode()
            ),
            "add_neighbor",
        )

    def del_neighbor(self, ifindex: int, dest: str) -> None:
        self._check(
            self._lib.onl_del_neighbor(self._h, ifindex, dest.encode()),
            "del_neighbor",
        )

    # -- addresses -------------------------------------------------------

    def add_addr(self, ifindex: int, addr: str, prefixlen: int) -> None:
        self._check(
            self._lib.onl_add_addr(
                self._h, ifindex, addr.encode(), prefixlen
            ),
            "add_addr",
        )

    def del_addr(self, ifindex: int, addr: str, prefixlen: int) -> None:
        self._check(
            self._lib.onl_del_addr(
                self._h, ifindex, addr.encode(), prefixlen
            ),
            "del_addr",
        )

    # -- routes ----------------------------------------------------------

    @staticmethod
    def _c_nexthops(nexthops: List[NlNextHop]):
        arr = (_CNextHop * max(1, len(nexthops)))()
        for i, nh in enumerate(nexthops):
            arr[i].via = nh.via.encode()
            arr[i].ifindex = nh.ifindex
            arr[i].weight = nh.weight
            arr[i].mpls_action = nh.mpls_action
            arr[i].num_labels = len(nh.labels)
            for j, label in enumerate(nh.labels[:8]):
                arr[i].labels[j] = label
        return arr

    def add_unicast_route(
        self,
        dest: str,
        nexthops: List[NlNextHop],
        proto: int = RT_PROT_OPENR,
        table: int = RT_TABLE_MAIN,
        replace: bool = True,
    ) -> None:
        assert nexthops, "route needs at least one nexthop"
        arr = self._c_nexthops(nexthops)
        self._check(
            self._lib.onl_add_unicast_route(
                self._h,
                dest.encode(),
                proto,
                table,
                arr,
                len(nexthops),
                1 if replace else 0,
            ),
            f"add_unicast_route {dest}",
        )

    def del_unicast_route(
        self,
        dest: str,
        proto: int = RT_PROT_OPENR,
        table: int = RT_TABLE_MAIN,
    ) -> None:
        self._check(
            self._lib.onl_del_unicast_route(
                self._h, dest.encode(), proto, table
            ),
            f"del_unicast_route {dest}",
        )

    def add_mpls_route(
        self, label: int, nexthops: List[NlNextHop], replace: bool = True
    ) -> None:
        assert nexthops
        arr = self._c_nexthops(nexthops)
        self._check(
            self._lib.onl_add_mpls_route(
                self._h, label, arr, len(nexthops), 1 if replace else 0
            ),
            f"add_mpls_route {label}",
        )

    def del_mpls_route(self, label: int) -> None:
        self._check(
            self._lib.onl_del_mpls_route(self._h, label),
            f"del_mpls_route {label}",
        )

    def get_routes(
        self,
        family: int = 0,
        proto: int = RT_PROT_OPENR,
        table: int = RT_TABLE_MAIN,
    ) -> List[NlRoute]:
        buf = ctypes.create_string_buffer(1 << 22)
        n = self._lib.onl_get_routes(
            self._h, family, proto, table, buf, len(buf)
        )
        self._check(n, "get_routes")
        routes: List[NlRoute] = []
        for line in buf.value.decode().splitlines():
            if not line:
                continue
            dest, _, nhs = line.partition("|")
            route = NlRoute(dest)
            for part in nhs.split(";"):
                if not part:
                    continue
                fields = part.split(",")
                via, ifindex, weight = (
                    fields[0],
                    int(fields[1]),
                    int(fields[2]),
                )
                action, labels = MPLS_NONE, ()
                if len(fields) > 3:
                    tag = fields[3]
                    if tag.startswith("swap:"):
                        action = MPLS_SWAP
                        labels = tuple(
                            int(x) for x in tag[5:].split("/") if x
                        )
                    elif tag.startswith("push:"):
                        action = MPLS_PUSH
                        labels = tuple(
                            int(x) for x in tag[5:].split("/") if x
                        )
                    elif tag == "php":
                        action = MPLS_PHP
                route.nexthops.append(
                    NlNextHop(via, ifindex, weight, action, labels)
                )
            routes.append(route)
        return routes

    # -- events ----------------------------------------------------------

    def subscribe(self) -> int:
        """Join link/addr multicast groups; returns pollable fd."""
        self._check(self._lib.onl_subscribe(self._h), "subscribe")
        return self._lib.onl_event_fd(self._h)

    def next_event(self):
        """Non-blocking event read → (kind, ifindex, up, name, addr,
        prefixlen, state, lladdr) or None. kind: 1=link 2=addr 4=neighbor
        (for neighbors, addr carries the destination IP, up =
        reachability)."""
        ev = _CEvent()
        rc = self._lib.onl_next_event(self._h, ctypes.byref(ev))
        self._check(rc, "next_event")
        if rc == 0:
            return None
        return (
            ev.kind,
            ev.ifindex,
            bool(ev.up),
            ev.name.decode(),
            ev.addr.decode(),
            ev.prefixlen,
            ev.state,
            ev.lladdr.decode(),
        )
