"""Native netlink bindings (ctypes over native/nl/libopenr_nl.so).

Python-side equivalent of the reference's netlink object model
(openr/nl/NetlinkTypes.h, NetlinkSocket.h) on top of the native protocol
core (native/nl/onl_netlink.cpp ≙ openr/nl/NetlinkProtocolSocket.{h,cpp}).
"""

from openr_tpu.nl.netlink import (
    Link,
    IfAddress,
    Neighbor,
    NetlinkError,
    NetlinkSocket,
    NlNextHop,
    NlRoute,
    native_available,
)

__all__ = [
    "Link",
    "IfAddress",
    "Neighbor",
    "NetlinkError",
    "NetlinkSocket",
    "NlNextHop",
    "NlRoute",
    "native_available",
]
