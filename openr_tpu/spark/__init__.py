"""Spark: neighbor discovery over UDP multicast hello/handshake/heartbeat.

Equivalent of openr/spark/: 3-message protocol, 5-state neighbor FSM with
graceful restart, RTT measurement from reflected timestamps smoothed by a
StepDetector, fast-init discovery, area negotiation. Socket operations go
through the IoProvider seam; MockIoProvider wires N instances in one process
with per-link latency (openr/tests/mocks/MockIoProvider.h).
"""

from openr_tpu.spark.messages import (
    SparkHandshakeMsg,
    SparkHelloMsg,
    SparkHeartbeatMsg,
    ReflectedNeighborInfo,
)
from openr_tpu.spark.io_provider import (
    IoProvider,
    MockIoNetwork,
    MockIoProvider,
    UdpIoProvider,
)
from openr_tpu.spark.spark import (
    NeighborEvent,
    NeighborEventType,
    Spark,
    SparkConfig,
    SparkNeighEvent,
    SparkNeighState,
)

__all__ = [
    "SparkHandshakeMsg",
    "SparkHelloMsg",
    "SparkHeartbeatMsg",
    "ReflectedNeighborInfo",
    "IoProvider",
    "MockIoNetwork",
    "MockIoProvider",
    "UdpIoProvider",
    "NeighborEvent",
    "NeighborEventType",
    "Spark",
    "SparkConfig",
    "SparkNeighEvent",
    "SparkNeighState",
]
