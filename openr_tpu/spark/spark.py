"""Spark neighbor discovery module.

Behavioral port of openr/spark/Spark.{h,cpp}:
  - table-driven 5-state neighbor FSM (Spark.cpp:110-178):
      IDLE -> WARM on any hello; WARM -> NEGOTIATE on bidirectional hello;
      NEGOTIATE -> ESTABLISHED on handshake (-> WARM on negotiate timeout or
      failure); ESTABLISHED -> IDLE on hold expiry or info loss, -> RESTART
      on a restarting hello; RESTART -> ESTABLISHED on hello, -> IDLE on GR
      expiry.
  - hello beacons per interface with fast-init cadence until first
    adjacency (Spark.cpp:1553, docs/Spark.md:43-46), reflecting neighbor
    timestamps for RTT measurement (updateNeighborRtt Spark.cpp:667):
      rtt = (t4 - t1) - (t3 - t2)
  - handshake negotiation incl. area matching (processHandshakeMsg
    Spark.cpp:1355); heartbeat keepalives refreshing hold timers
    (processHeartbeatMsg Spark.cpp:1501); graceful-restart flow.
  - RTT smoothed through StepDetector; RTT_CHANGE events only on steps.
"""

from __future__ import annotations

import asyncio
import enum
import re
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.messaging import ReplicateQueue
from openr_tpu.spark.io_provider import IoProvider, ReceivedPacket
from openr_tpu.spark.messages import (
    ReflectedNeighborInfo,
    SparkHandshakeMsg,
    SparkHelloMsg,
    SparkHelloPacket,
    SparkHeartbeatMsg,
)
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils import StepDetector
from openr_tpu.utils.counters import CountersMixin


class SparkNeighState(enum.Enum):
    IDLE = 0
    WARM = 1
    NEGOTIATE = 2
    ESTABLISHED = 3
    RESTART = 4


class SparkNeighEvent(enum.Enum):
    HELLO_RCVD_INFO = 0
    HELLO_RCVD_NO_INFO = 1
    HELLO_RCVD_RESTART = 2
    HEARTBEAT_RCVD = 3
    HANDSHAKE_RCVD = 4
    HEARTBEAT_TIMER_EXPIRE = 5
    NEGOTIATE_TIMER_EXPIRE = 6
    GR_TIMER_EXPIRE = 7
    NEGOTIATION_FAILURE = 8


S, E = SparkNeighState, SparkNeighEvent
# exact transition matrix from Spark.cpp:110-178; missing = invalid
_FSM: Dict[Tuple[SparkNeighState, SparkNeighEvent], SparkNeighState] = {
    (S.IDLE, E.HELLO_RCVD_INFO): S.WARM,
    (S.IDLE, E.HELLO_RCVD_NO_INFO): S.WARM,
    (S.WARM, E.HELLO_RCVD_INFO): S.NEGOTIATE,
    (S.NEGOTIATE, E.HANDSHAKE_RCVD): S.ESTABLISHED,
    (S.NEGOTIATE, E.NEGOTIATE_TIMER_EXPIRE): S.WARM,
    (S.NEGOTIATE, E.NEGOTIATION_FAILURE): S.WARM,
    (S.ESTABLISHED, E.HELLO_RCVD_NO_INFO): S.IDLE,
    (S.ESTABLISHED, E.HELLO_RCVD_RESTART): S.RESTART,
    (S.ESTABLISHED, E.HEARTBEAT_RCVD): S.ESTABLISHED,
    (S.ESTABLISHED, E.HEARTBEAT_TIMER_EXPIRE): S.IDLE,
    (S.RESTART, E.HELLO_RCVD_INFO): S.ESTABLISHED,
    (S.RESTART, E.GR_TIMER_EXPIRE): S.IDLE,
}


class NeighborEventType(enum.Enum):
    NEIGHBOR_UP = "NEIGHBOR_UP"
    NEIGHBOR_DOWN = "NEIGHBOR_DOWN"
    NEIGHBOR_RESTARTING = "NEIGHBOR_RESTARTING"
    NEIGHBOR_RESTARTED = "NEIGHBOR_RESTARTED"
    NEIGHBOR_RTT_CHANGE = "NEIGHBOR_RTT_CHANGE"


@dataclass
class NeighborEvent:
    event_type: NeighborEventType
    node_name: str
    local_if_name: str
    remote_if_name: str
    area: str
    rtt_us: int = 0
    label: int = 0
    transport_address_v4: str = ""
    transport_address_v6: str = ""
    kvstore_cmd_port: int = 0
    kvstore_host: str = ""
    openr_ctrl_thrift_port: int = 0
    # time.monotonic() stamp of the moment Spark decided to publish this
    # event — the first mark of the convergence span (LinkMonitor hands it
    # through to the KvStore publication as Publication.span_stages).
    # Host-local, like every monotonic stamp.
    ts_monotonic: float = 0.0


@dataclass
class SparkConfig:
    node_name: str
    domain: str = "default"
    # ordered (area, node-name regex) pairs for area negotiation
    # (AreaConfiguration, config/Config.h:251)
    area_configs: List[Tuple[str, str]] = field(
        default_factory=lambda: [("0", ".*")]
    )
    hello_time: float = 20.0
    fastinit_hello_time: float = 0.5
    handshake_time: float = 0.5
    keepalive_time: float = 2.0
    hold_time: float = 10.0
    graceful_restart_time: float = 30.0
    negotiate_hold_time: float = 2.0  # handshake_time * 4-ish
    transport_address_v4: str = "169.254.0.1"
    transport_address_v6: str = "fe80::1"
    kvstore_cmd_port: int = 60002
    kvstore_host: str = ""  # KvStore peer-RPC host (TCP deployments)
    openr_ctrl_thrift_port: int = 2018
    node_label: int = 0

    def area_for(self, neighbor_name: str) -> Optional[str]:
        for area, pattern in self.area_configs:
            if re.fullmatch(pattern, neighbor_name):
                return area
        return None


class _Neighbor:
    def __init__(
        self,
        spark: "Spark",
        node_name: str,
        local_if: str,
        remote_if: str,
        seq_num: int,
    ) -> None:
        self.spark = spark
        self.node_name = node_name
        self.local_if = local_if
        self.remote_if = remote_if
        self.seq_num = seq_num
        self.state = SparkNeighState.IDLE
        self.area: Optional[str] = None
        self.label = 0
        self.rtt_us = 0
        self.rtt_latest_us = 0
        self.transport_address_v4 = ""
        self.transport_address_v6 = ""
        self.kvstore_cmd_port = 0
        self.kvstore_host = ""
        self.openr_ctrl_thrift_port = 0
        # reflected timestamps for the hello we send back
        self.last_nbr_msg_sent_ts_us = 0
        self.last_my_msg_rcvd_ts_us = 0
        self.step_detector = StepDetector(
            self._on_rtt_step,
            fast_window_size=10,
            slow_window_size=60,
            lower_threshold=2.0,
            upper_threshold=5.0,
            abs_threshold=500.0,
            sample_period=1.0,
        )
        self._negotiate_timer: Optional[asyncio.TimerHandle] = None
        self._handshake_timer: Optional[asyncio.TimerHandle] = None
        self._hold_timer: Optional[asyncio.TimerHandle] = None
        self._gr_timer: Optional[asyncio.TimerHandle] = None

    def _on_rtt_step(self, new_rtt: float) -> None:
        self.rtt_us = int(new_rtt)
        if self.state == SparkNeighState.ESTABLISHED:
            self.spark.publish_event(
                NeighborEventType.NEIGHBOR_RTT_CHANGE, self
            )

    def fsm(self, event: SparkNeighEvent) -> Optional[SparkNeighState]:
        """Apply event; returns the new state or None if invalid."""
        next_state = _FSM.get((self.state, event))
        if next_state is None:
            return None
        old, self.state = self.state, next_state
        return next_state

    def cancel_timers(self) -> None:
        for t in (
            self._negotiate_timer,
            self._handshake_timer,
            self._hold_timer,
            self._gr_timer,
        ):
            if t is not None:
                t.cancel()
        self._negotiate_timer = None
        self._handshake_timer = None
        self._hold_timer = None
        self._gr_timer = None


class Spark(CountersMixin):
    def __init__(
        self,
        config: SparkConfig,
        io_provider: IoProvider,
        neighbor_events_queue: ReplicateQueue,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.config = config
        self.io = io_provider
        self.neighbor_events_queue = neighbor_events_queue
        self._loop = loop
        self.interfaces: Dict[str, bool] = {}  # ifname -> fast-init pending
        # ifname -> node -> neighbor
        self.neighbors: Dict[str, Dict[str, _Neighbor]] = {}
        self.seq_num = 0
        self._hello_timers: Dict[str, asyncio.TimerHandle] = {}
        self._heartbeat_timers: Dict[str, asyncio.TimerHandle] = {}
        self.counters: Dict[str, int] = {}
        self._stopped = False
        self.io.set_receiver(config.node_name, self._on_packet)

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    # ------------------------------------------------------------------
    # interface management (fed by LinkMonitor)
    # ------------------------------------------------------------------

    def update_interfaces(self, up_ifaces: List[str]) -> None:
        """Apply the interface set (processInterfaceUpdates Spark.cpp:1637)."""
        added = [i for i in up_ifaces if i not in self.interfaces]
        removed = [i for i in self.interfaces if i not in up_ifaces]
        for iface in removed:
            self._remove_interface(iface)
        for iface in added:
            self.interfaces[iface] = True  # fast-init pending
            self._send_hello(iface)
            self._schedule_heartbeat(iface)

    def _remove_interface(self, iface: str) -> None:
        for neighbor in list(self.neighbors.get(iface, {}).values()):
            if neighbor.state in (
                SparkNeighState.ESTABLISHED,
                SparkNeighState.RESTART,
            ):
                self.publish_event(NeighborEventType.NEIGHBOR_DOWN, neighbor)
            neighbor.cancel_timers()
        self.neighbors.pop(iface, None)
        self.interfaces.pop(iface, None)
        t = self._hello_timers.pop(iface, None)
        if t is not None:
            t.cancel()
        t = self._heartbeat_timers.pop(iface, None)
        if t is not None:
            t.cancel()

    # ------------------------------------------------------------------
    # outbound
    # ------------------------------------------------------------------

    def _io_send(self, iface: str, packet: SparkHelloPacket) -> int:
        """All outbound datagrams funnel through here so the named fault
        point can drop them: a send fault IS a dropped packet (UDP
        semantics) — the hello/heartbeat/handshake timers retransmit, so
        injected loss exercises the discovery-delay and hold-expiry paths
        without special-casing any caller."""
        try:
            fault_point("spark.packet_send", iface)
        except Exception:
            self._bump("spark.packet_send_failures")
            return self.io.now_us()
        return self.io.send(iface, packet)

    def _send_hello(
        self, iface: str, restarting: bool = False
    ) -> None:
        if self._stopped or iface not in self.interfaces:
            return
        self.seq_num += 1
        infos: Dict[str, ReflectedNeighborInfo] = {}
        for neighbor in self.neighbors.get(iface, {}).values():
            infos[neighbor.node_name] = ReflectedNeighborInfo(
                last_nbr_msg_sent_ts_us=neighbor.last_nbr_msg_sent_ts_us,
                last_my_msg_rcvd_ts_us=neighbor.last_my_msg_rcvd_ts_us,
            )
        msg = SparkHelloMsg(
            domain_name=self.config.domain,
            node_name=self.config.node_name,
            if_name=iface,
            seq_num=self.seq_num,
            neighbor_infos=infos,
            solicit_response=self.interfaces.get(iface, False),
            restarting=restarting,
            sent_ts_in_us=self.io.now_us(),
        )
        msg.sent_ts_in_us = self._io_send(
            iface, SparkHelloPacket(hello_msg=msg)
        )
        self._bump("spark.hello_packet_sent")
        # fast-init cadence until an adjacency forms on the interface
        fast = self.interfaces.get(iface, False)
        period = (
            self.config.fastinit_hello_time if fast else self.config.hello_time
        )
        old = self._hello_timers.get(iface)
        if old is not None:
            old.cancel()
        self._hello_timers[iface] = self.loop().call_later(
            period, self._send_hello, iface
        )

    def _schedule_heartbeat(self, iface: str) -> None:
        if self._stopped or iface not in self.interfaces:
            return
        self._io_send(
            iface,
            SparkHelloPacket(
                heartbeat_msg=SparkHeartbeatMsg(
                    node_name=self.config.node_name, seq_num=self.seq_num
                )
            ),
        )
        self._bump("spark.heartbeat_packet_sent")
        self._heartbeat_timers[iface] = self.loop().call_later(
            self.config.keepalive_time, self._schedule_heartbeat, iface
        )

    def _send_handshake(self, neighbor: _Neighbor) -> None:
        if (
            self._stopped
            or neighbor.state != SparkNeighState.NEGOTIATE
            or neighbor.local_if not in self.interfaces
        ):
            return
        area = self.config.area_for(neighbor.node_name)
        self._io_send(
            neighbor.local_if,
            SparkHelloPacket(
                handshake_msg=SparkHandshakeMsg(
                    node_name=self.config.node_name,
                    is_adj_established=False,
                    hold_time_ms=int(self.config.hold_time * 1000),
                    graceful_restart_time_ms=int(
                        self.config.graceful_restart_time * 1000
                    ),
                    transport_address_v6=self.config.transport_address_v6,
                    transport_address_v4=self.config.transport_address_v4,
                    openr_ctrl_thrift_port=self.config.openr_ctrl_thrift_port,
                    kvstore_cmd_port=self.config.kvstore_cmd_port,
                    kvstore_host=self.config.kvstore_host,
                    area=area if area is not None else "",
                    neighbor_node_name=neighbor.node_name,
                )
            ),
        )
        self._bump("spark.handshake_packet_sent")
        neighbor._handshake_timer = self.loop().call_later(
            self.config.handshake_time, self._send_handshake, neighbor
        )

    # ------------------------------------------------------------------
    # inbound
    # ------------------------------------------------------------------

    def _on_packet(self, received: ReceivedPacket) -> None:
        if self._stopped or received.if_name not in self.interfaces:
            return
        try:
            # named fault seam: an injected receive fault is a dropped
            # datagram — peers' retransmit timers carry discovery forward
            fault_point("spark.packet_recv", received)
        except Exception:
            self._bump("spark.packet_recv_failures")
            return
        packet = received.packet
        if packet.hello_msg is not None:
            self._process_hello(received)
        elif packet.handshake_msg is not None:
            self._process_handshake(received)
        elif packet.heartbeat_msg is not None:
            self._process_heartbeat(received)

    def _get_or_create_neighbor(
        self, iface: str, msg: SparkHelloMsg
    ) -> _Neighbor:
        by_node = self.neighbors.setdefault(iface, {})
        neighbor = by_node.get(msg.node_name)
        if neighbor is None:
            neighbor = _Neighbor(
                self, msg.node_name, iface, msg.if_name, msg.seq_num
            )
            by_node[msg.node_name] = neighbor
        return neighbor

    def _process_hello(self, received: ReceivedPacket) -> None:
        msg = received.packet.hello_msg
        if msg.node_name == self.config.node_name:
            return  # our own multicast echo
        if msg.domain_name != self.config.domain:
            self._bump("spark.invalid_domain")
            return
        iface = received.if_name
        neighbor = self._get_or_create_neighbor(iface, msg)
        neighbor.seq_num = msg.seq_num
        neighbor.remote_if = msg.if_name
        neighbor.last_nbr_msg_sent_ts_us = msg.sent_ts_in_us
        neighbor.last_my_msg_rcvd_ts_us = received.recv_ts_us
        self._bump("spark.hello_packet_recv")

        our_info = msg.neighbor_infos.get(self.config.node_name)
        # RTT from reflected timestamps (Spark.cpp:667):
        # t1 = our hello sent, t2 = nbr received it, t3 = nbr hello sent,
        # t4 = we received it; rtt = (t4 - t1) - (t3 - t2)
        if our_info is not None and our_info.last_nbr_msg_sent_ts_us > 0:
            rtt = (
                received.recv_ts_us - our_info.last_nbr_msg_sent_ts_us
            ) - (msg.sent_ts_in_us - our_info.last_my_msg_rcvd_ts_us)
            if rtt > 0:
                neighbor.rtt_latest_us = rtt
                if neighbor.rtt_us == 0:
                    neighbor.rtt_us = rtt
                neighbor.step_detector.add_value(
                    time.monotonic(), float(rtt)
                )

        state = neighbor.state
        if state == SparkNeighState.IDLE:
            neighbor.fsm(
                SparkNeighEvent.HELLO_RCVD_INFO
                if our_info is not None
                else SparkNeighEvent.HELLO_RCVD_NO_INFO
            )
            if our_info is None:
                # solicit a fast response for quick bidirectional discovery
                self._send_hello(iface)
        elif state == SparkNeighState.WARM:
            if our_info is not None:
                neighbor.fsm(SparkNeighEvent.HELLO_RCVD_INFO)
                self._start_negotiation(neighbor)
        elif state == SparkNeighState.ESTABLISHED:
            if msg.restarting:
                neighbor.fsm(SparkNeighEvent.HELLO_RCVD_RESTART)
                self._neighbor_restarting(neighbor)
            elif our_info is None:
                # neighbor forgot about us: hard down
                neighbor.fsm(SparkNeighEvent.HELLO_RCVD_NO_INFO)
                self._neighbor_down(neighbor)
            # else: refresh only (heartbeats maintain hold)
        elif state == SparkNeighState.RESTART:
            if msg.restarting:
                # double restart: the neighbor announced another graceful
                # restart before completing the first one — re-arm the GR
                # window from this announcement (no FSM transition; the
                # hold simply extends so back-to-back restarts survive)
                self._neighbor_restarting(neighbor, rearm=True)
            elif our_info is not None:
                neighbor.fsm(SparkNeighEvent.HELLO_RCVD_INFO)
                self._neighbor_restarted(neighbor)
            else:
                # the fresh incarnation is soliciting rediscovery (its
                # hellos don't know us yet): reply immediately, same as
                # the IDLE fast path — a GR window must not be spent
                # waiting out our regular hello cadence
                self._send_hello(iface)

    def _start_negotiation(self, neighbor: _Neighbor) -> None:
        self._send_handshake(neighbor)
        if neighbor._negotiate_timer is not None:
            neighbor._negotiate_timer.cancel()
        neighbor._negotiate_timer = self.loop().call_later(
            self.config.negotiate_hold_time,
            self._negotiate_timeout,
            neighbor,
        )

    def _negotiate_timeout(self, neighbor: _Neighbor) -> None:
        if neighbor.state == SparkNeighState.NEGOTIATE:
            neighbor.fsm(SparkNeighEvent.NEGOTIATE_TIMER_EXPIRE)
            if neighbor._handshake_timer is not None:
                neighbor._handshake_timer.cancel()

    def _process_handshake(self, received: ReceivedPacket) -> None:
        msg = received.packet.handshake_msg
        if msg.node_name == self.config.node_name:
            return
        iface = received.if_name
        neighbor = self.neighbors.get(iface, {}).get(msg.node_name)
        if neighbor is None:
            return
        self._bump("spark.handshake_packet_recv")
        # a handshake directed at another node is not for us
        if (
            msg.neighbor_node_name is not None
            and msg.neighbor_node_name != self.config.node_name
        ):
            return
        # respond so the peer can also establish (unless it already has)
        if not msg.is_adj_established and neighbor.state in (
            SparkNeighState.NEGOTIATE,
            SparkNeighState.ESTABLISHED,
        ):
            area = self.config.area_for(msg.node_name)
            self._io_send(
                iface,
                SparkHelloPacket(
                    handshake_msg=SparkHandshakeMsg(
                        node_name=self.config.node_name,
                        is_adj_established=True,
                        hold_time_ms=int(self.config.hold_time * 1000),
                        graceful_restart_time_ms=int(
                            self.config.graceful_restart_time * 1000
                        ),
                        transport_address_v6=self.config.transport_address_v6,
                        transport_address_v4=self.config.transport_address_v4,
                        openr_ctrl_thrift_port=(
                            self.config.openr_ctrl_thrift_port
                        ),
                        kvstore_cmd_port=self.config.kvstore_cmd_port,
                        kvstore_host=self.config.kvstore_host,
                        area=area if area is not None else "",
                        neighbor_node_name=msg.node_name,
                    )
                ),
            )
        if neighbor.state != SparkNeighState.NEGOTIATE:
            return
        # area negotiation: both sides must agree
        my_area = self.config.area_for(msg.node_name)
        if my_area is None or (msg.area and msg.area != my_area):
            self._bump("spark.invalid_area")
            neighbor.fsm(SparkNeighEvent.NEGOTIATION_FAILURE)
            if neighbor._handshake_timer is not None:
                neighbor._handshake_timer.cancel()
            if neighbor._negotiate_timer is not None:
                neighbor._negotiate_timer.cancel()
                neighbor._negotiate_timer = None
            return
        neighbor.area = my_area
        neighbor.transport_address_v4 = msg.transport_address_v4
        neighbor.transport_address_v6 = msg.transport_address_v6
        neighbor.kvstore_cmd_port = msg.kvstore_cmd_port
        neighbor.kvstore_host = msg.kvstore_host
        neighbor.openr_ctrl_thrift_port = msg.openr_ctrl_thrift_port
        neighbor.fsm(SparkNeighEvent.HANDSHAKE_RCVD)
        neighbor.cancel_timers()
        self.interfaces[neighbor.local_if] = False  # leave fast-init
        self._start_hold_timer(neighbor)
        self.publish_event(NeighborEventType.NEIGHBOR_UP, neighbor)

    def _process_heartbeat(self, received: ReceivedPacket) -> None:
        msg = received.packet.heartbeat_msg
        iface = received.if_name
        neighbor = self.neighbors.get(iface, {}).get(msg.node_name)
        if neighbor is None or neighbor.state != SparkNeighState.ESTABLISHED:
            return
        self._bump("spark.heartbeat_packet_recv")
        neighbor.fsm(SparkNeighEvent.HEARTBEAT_RCVD)
        self._start_hold_timer(neighbor)  # refresh

    # ------------------------------------------------------------------
    # neighbor lifecycle
    # ------------------------------------------------------------------

    def _start_hold_timer(self, neighbor: _Neighbor) -> None:
        if neighbor._hold_timer is not None:
            neighbor._hold_timer.cancel()
        neighbor._hold_timer = self.loop().call_later(
            self.config.hold_time, self._hold_expired, neighbor
        )

    def _hold_expired(self, neighbor: _Neighbor) -> None:
        if neighbor.state == SparkNeighState.ESTABLISHED:
            neighbor.fsm(SparkNeighEvent.HEARTBEAT_TIMER_EXPIRE)
            self._neighbor_down(neighbor)

    def _neighbor_down(self, neighbor: _Neighbor) -> None:
        neighbor.cancel_timers()
        self.publish_event(NeighborEventType.NEIGHBOR_DOWN, neighbor)
        self.neighbors.get(neighbor.local_if, {}).pop(
            neighbor.node_name, None
        )
        self.interfaces[neighbor.local_if] = True  # back to fast-init

    def _neighbor_restarting(
        self, neighbor: _Neighbor, rearm: bool = False
    ) -> None:
        neighbor.cancel_timers()
        if not rearm:
            # gauge of neighbors currently held through a GR window;
            # restarted/expired exits decrement it
            self._bump("spark.gr_holds_active")
        self.publish_event(NeighborEventType.NEIGHBOR_RESTARTING, neighbor)
        neighbor._gr_timer = self.loop().call_later(
            self.config.graceful_restart_time, self._gr_expired, neighbor
        )

    def _gr_expired(self, neighbor: _Neighbor) -> None:
        if neighbor.state == SparkNeighState.RESTART:
            neighbor.fsm(SparkNeighEvent.GR_TIMER_EXPIRE)
            self._bump("spark.gr_holds_active", -1)
            self._bump("spark.gr_hold_expiries")
            self._neighbor_down(neighbor)

    def _neighbor_restarted(self, neighbor: _Neighbor) -> None:
        if neighbor._gr_timer is not None:
            neighbor._gr_timer.cancel()
        self._bump("spark.gr_holds_active", -1)
        self._start_hold_timer(neighbor)
        self.publish_event(NeighborEventType.NEIGHBOR_RESTARTED, neighbor)

    def publish_event(
        self, event_type: NeighborEventType, neighbor: _Neighbor
    ) -> None:
        self.neighbor_events_queue.push(
            NeighborEvent(
                ts_monotonic=time.monotonic(),
                event_type=event_type,
                node_name=neighbor.node_name,
                local_if_name=neighbor.local_if,
                remote_if_name=neighbor.remote_if,
                area=neighbor.area or "",
                rtt_us=neighbor.rtt_us,
                label=neighbor.label,
                transport_address_v4=neighbor.transport_address_v4,
                transport_address_v6=neighbor.transport_address_v6,
                kvstore_cmd_port=neighbor.kvstore_cmd_port,
                kvstore_host=neighbor.kvstore_host,
                openr_ctrl_thrift_port=neighbor.openr_ctrl_thrift_port,
            )
        )

    # ------------------------------------------------------------------

    def get_neighbors(
        self, state: Optional[SparkNeighState] = None
    ) -> List[_Neighbor]:
        out = []
        for by_node in self.neighbors.values():
            for neighbor in by_node.values():
                if state is None or neighbor.state == state:
                    out.append(neighbor)
        return out

    def flood_restarting(self) -> None:
        """Announce graceful restart on all interfaces (Spark GR exit).

        Called by the daemon's stop path when
        `spark_config.graceful_restart_enabled` is set: neighbors that
        hear the restarting hello enter their RESTART hold (keeping the
        adjacency and the routes through it for `graceful_restart_time`)
        instead of tearing the adjacency down on hold expiry."""
        if self._stopped:
            return
        for iface in self.interfaces:
            self._send_hello(iface, restarting=True)
            self._bump("spark.gr_hellos_sent")

    def stop(self) -> None:
        self._stopped = True
        for t in self._hello_timers.values():
            t.cancel()
        for t in self._heartbeat_timers.values():
            t.cancel()
        for by_node in self.neighbors.values():
            for neighbor in by_node.values():
                neighbor.cancel_timers()

