"""IoProvider: the socket seam for Spark.

The reference routes all UDP multicast syscalls through IoProvider
(openr/spark/IoProvider.h) so tests can substitute MockIoProvider
(openr/tests/mocks/MockIoProvider.h:25-60): N Spark instances in one process
glued by in-memory mailboxes with configurable per-link latency. The same
seam here; the real UDP provider wraps asyncio datagram transports.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from openr_tpu.spark.messages import SparkHelloPacket


@dataclass
class ReceivedPacket:
    if_name: str  # interface it arrived on
    packet: SparkHelloPacket
    recv_ts_us: int


class IoProvider:
    """Send/receive seam. Timestamps are microseconds (kernel-timestamp
    equivalents, used for RTT measurement)."""

    def set_receiver(self, instance_id: str, callback) -> None:
        raise NotImplementedError

    def send(self, if_name: str, packet: SparkHelloPacket) -> int:
        """Send on interface; returns the send timestamp in us."""
        raise NotImplementedError

    def now_us(self) -> int:
        return int(time.monotonic() * 1_000_000)


class MockIoNetwork:
    """Shared virtual network: connects (instance, iface) endpoints in
    pairs with per-link latency (ConnectedIfPairs)."""

    def __init__(self) -> None:
        # (instance, iface) -> list of ((instance, iface), latency_s)
        self._links: Dict[Tuple[str, str], List[Tuple[Tuple[str, str], float]]] = {}
        self._receivers: Dict[str, object] = {}
        self._partitioned: set = set()

    def connect(
        self,
        a: Tuple[str, str],
        b: Tuple[str, str],
        latency_ms: float = 1.0,
    ) -> None:
        self._links.setdefault(a, []).append((b, latency_ms / 1000.0))
        self._links.setdefault(b, []).append((a, latency_ms / 1000.0))

    def disconnect(self, a: Tuple[str, str], b: Tuple[str, str]) -> None:
        self._partitioned.add((a, b))
        self._partitioned.add((b, a))

    def reconnect(self, a: Tuple[str, str], b: Tuple[str, str]) -> None:
        self._partitioned.discard((a, b))
        self._partitioned.discard((b, a))

    def provider(self, instance_id: str) -> "MockIoProvider":
        return MockIoProvider(self, instance_id)

    def _register(self, instance_id: str, callback) -> None:
        self._receivers[instance_id] = callback

    def _send(
        self, src: Tuple[str, str], packet: SparkHelloPacket
    ) -> int:
        now_us = int(time.monotonic() * 1_000_000)
        loop = asyncio.get_event_loop()
        for dst, latency in self._links.get(src, []):
            if (src, dst) in self._partitioned:
                continue
            dst_instance, dst_iface = dst
            callback = self._receivers.get(dst_instance)
            if callback is None:
                continue
            loop.call_later(
                latency,
                callback,
                ReceivedPacket(
                    if_name=dst_iface,
                    packet=packet,
                    recv_ts_us=int(
                        (time.monotonic() + latency) * 1_000_000
                    ),
                ),
            )
        return now_us


class MockIoProvider(IoProvider):
    def __init__(self, network: MockIoNetwork, instance_id: str) -> None:
        self._network = network
        self.instance_id = instance_id

    def set_receiver(self, instance_id: str, callback) -> None:
        self._network._register(instance_id, callback)

    def send(self, if_name: str, packet: SparkHelloPacket) -> int:
        return self._network._send((self.instance_id, if_name), packet)
