"""IoProvider: the socket seam for Spark.

The reference routes all UDP multicast syscalls through IoProvider
(openr/spark/IoProvider.h) so tests can substitute MockIoProvider
(openr/tests/mocks/MockIoProvider.h:25-60): N Spark instances in one process
glued by in-memory mailboxes with configurable per-link latency. The same
seam here; the real UDP provider wraps asyncio datagram transports.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from openr_tpu.spark.messages import (
    SparkHelloPacket,
    packet_from_bytes,
    packet_to_bytes,
)


@dataclass
class ReceivedPacket:
    if_name: str  # interface it arrived on
    packet: SparkHelloPacket
    recv_ts_us: int


class IoProvider:
    """Send/receive seam. Timestamps are microseconds (kernel-timestamp
    equivalents, used for RTT measurement)."""

    def set_receiver(self, instance_id: str, callback) -> None:
        raise NotImplementedError

    def send(self, if_name: str, packet: SparkHelloPacket) -> int:
        """Send on interface; returns the send timestamp in us."""
        raise NotImplementedError

    def now_us(self) -> int:
        return int(time.monotonic() * 1_000_000)


class UdpIoProvider(IoProvider):
    """Real UDP multicast provider (the production IoProvider).

    One socket per interface, bound to the Spark port and joined to the
    discovery multicast group on that interface — the reference's
    ff02::1:6666 scheme (openr/common/Constants.h:132, Spark.h:424), with
    an IPv4 group supported for environments without usable link-local
    IPv6 (e.g. loopback in containers, where same-host instances share the
    port via SO_REUSEPORT and the kernel delivers the group to every
    member). Receive timestamps are taken at datagram arrival — the
    userspace stand-in for the reference's kernel timestamps
    (spark/IoProvider.h recvfrom with SO_TIMESTAMPNS).
    """

    def __init__(
        self,
        port: int = 6666,
        group: str = "ff02::1",
        *,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.port = port
        self.group = group
        self._v6 = ":" in group
        self._loop = loop
        self._callback = None
        # if_name -> (socket, asyncio transport, ifindex or None)
        self._endpoints: Dict[str, Tuple[object, object, Optional[int]]] = {}
        self._opening: set = set()  # interfaces with an open in flight
        self._closed = False

    # -- socket plumbing -------------------------------------------------

    def _make_socket(self, if_name: str):
        import socket
        import struct

        if self._v6:
            sock = socket.socket(socket.AF_INET6, socket.SOCK_DGRAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind(("::", self.port))
            ifindex = socket.if_nametoindex(if_name)
            mreq = socket.inet_pton(
                socket.AF_INET6, self.group
            ) + struct.pack("@I", ifindex)
            sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_JOIN_GROUP, mreq)
            sock.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_IF, ifindex
            )
            sock.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_LOOP, 1
            )
            sock.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_HOPS, 1
            )
        else:
            if_addr = _ipv4_addr_of(if_name)
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind(("", self.port))
            mreq = socket.inet_aton(self.group) + socket.inet_aton(if_addr)
            sock.setsockopt(
                socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq
            )
            sock.setsockopt(
                socket.IPPROTO_IP,
                socket.IP_MULTICAST_IF,
                socket.inet_aton(if_addr),
            )
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP, 1)
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_TTL, 1)
        try:
            # attribute arrivals to the right interface on multi-homed
            # hosts (the reference binds one socket per interface too)
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_BINDTODEVICE, if_name.encode()
            )
        except (OSError, AttributeError):
            pass  # unprivileged: wildcard-bound socket still works
        sock.setblocking(False)
        return sock

    async def add_interface(self, if_name: str) -> None:
        """Open + join the multicast socket for one interface."""
        if if_name in self._endpoints or self._closed:
            return
        import socket as socket_mod


        sock = self._make_socket(if_name)
        ifindex = (
            socket_mod.if_nametoindex(if_name) if self._v6 else None
        )
        loop = self._loop or asyncio.get_event_loop()
        provider = self

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data: bytes, addr) -> None:
                callback = provider._callback
                if callback is None:
                    return
                try:
                    packet = packet_from_bytes(data)
                except (ValueError, KeyError, TypeError, AttributeError):
                    return  # not a Spark packet; ignore
                callback(
                    ReceivedPacket(
                        if_name=if_name,
                        packet=packet,
                        recv_ts_us=provider.now_us(),
                    )
                )

        transport, _ = await loop.create_datagram_endpoint(
            _Proto, sock=sock
        )
        if self._closed:  # closed while this open was in flight
            transport.close()
            return
        self._endpoints[if_name] = (sock, transport, ifindex)

    def close(self) -> None:
        self._closed = True
        self._callback = None
        for _, transport, _ifindex in self._endpoints.values():
            transport.close()
        self._endpoints.clear()
        self._opening.clear()

    # -- IoProvider surface ----------------------------------------------

    def set_receiver(self, instance_id: str, callback) -> None:
        self._callback = callback

    def send(self, if_name: str, packet: SparkHelloPacket) -> int:
        endpoint = self._endpoints.get(if_name)
        now = self.now_us()
        if endpoint is None:
            # first send on an unopened interface: schedule the socket
            # open and drop this packet — Spark's fast-init hello timer
            # retries within tens of ms (Spark.cpp fast-init cadence)
            if if_name not in self._opening:
                self._opening.add(if_name)

                async def _open() -> None:
                    try:
                        await self.add_interface(if_name)
                    except OSError as exc:
                        # interface down / unaddressed: next send retries
                        import logging

                        logging.getLogger(__name__).warning(
                            "spark: open %s failed: %s", if_name, exc
                        )
                    finally:
                        self._opening.discard(if_name)

                loop = self._loop or asyncio.get_event_loop()
                loop.create_task(_open())
            return now
        _sock, transport, ifindex = endpoint
        data = packet_to_bytes(packet)
        if self._v6:
            transport.sendto(data, (self.group, self.port, 0, ifindex))
        else:
            transport.sendto(data, (self.group, self.port))
        return now


def _ipv4_addr_of(if_name: str) -> str:
    """Primary IPv4 address of an interface (for IP_MULTICAST_IF)."""
    if if_name == "lo":
        return "127.0.0.1"
    import fcntl
    import socket
    import struct

    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # SIOCGIFADDR
        packed = fcntl.ioctl(
            sock.fileno(),
            0x8915,
            struct.pack("256s", if_name[:15].encode()),
        )
        return socket.inet_ntoa(packed[20:24])
    finally:
        sock.close()


class MockIoNetwork:
    """Shared virtual network: connects (instance, iface) endpoints in
    pairs with per-link latency (ConnectedIfPairs)."""

    def __init__(self) -> None:
        # (instance, iface) -> list of ((instance, iface), latency_s)
        self._links: Dict[Tuple[str, str], List[Tuple[Tuple[str, str], float]]] = {}
        self._receivers: Dict[str, object] = {}
        self._partitioned: set = set()

    def connect(
        self,
        a: Tuple[str, str],
        b: Tuple[str, str],
        latency_ms: float = 1.0,
    ) -> None:
        self._links.setdefault(a, []).append((b, latency_ms / 1000.0))
        self._links.setdefault(b, []).append((a, latency_ms / 1000.0))

    def disconnect(self, a: Tuple[str, str], b: Tuple[str, str]) -> None:
        self._partitioned.add((a, b))
        self._partitioned.add((b, a))

    def reconnect(self, a: Tuple[str, str], b: Tuple[str, str]) -> None:
        self._partitioned.discard((a, b))
        self._partitioned.discard((b, a))

    def provider(self, instance_id: str) -> "MockIoProvider":
        return MockIoProvider(self, instance_id)

    def _register(self, instance_id: str, callback) -> None:
        self._receivers[instance_id] = callback

    def _send(
        self, src: Tuple[str, str], packet: SparkHelloPacket
    ) -> int:
        now_us = int(time.monotonic() * 1_000_000)
        loop = asyncio.get_event_loop()
        for dst, latency in self._links.get(src, []):
            if (src, dst) in self._partitioned:
                continue
            dst_instance, dst_iface = dst
            callback = self._receivers.get(dst_instance)
            if callback is None:
                continue
            loop.call_later(
                latency,
                callback,
                ReceivedPacket(
                    if_name=dst_iface,
                    packet=packet,
                    recv_ts_us=int(
                        (time.monotonic() + latency) * 1_000_000
                    ),
                ),
            )
        return now_us


class MockIoProvider(IoProvider):
    def __init__(self, network: MockIoNetwork, instance_id: str) -> None:
        self._network = network
        self.instance_id = instance_id

    def set_receiver(self, instance_id: str, callback) -> None:
        self._network._register(instance_id, callback)

    def send(self, if_name: str, packet: SparkHelloPacket) -> int:
        return self._network._send((self.instance_id, if_name), packet)
