"""IoProvider: the socket seam for Spark.

The reference routes all UDP multicast syscalls through IoProvider
(openr/spark/IoProvider.h) so tests can substitute MockIoProvider
(openr/tests/mocks/MockIoProvider.h:25-60): N Spark instances in one process
glued by in-memory mailboxes with configurable per-link latency. The same
seam here; the real UDP provider wraps asyncio datagram transports.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import struct
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from openr_tpu.spark.messages import (
    SparkHelloPacket,
    packet_from_bytes,
    packet_to_bytes,
)


@dataclass
class ReceivedPacket:
    if_name: str  # interface it arrived on
    packet: SparkHelloPacket
    recv_ts_us: int


class IoProvider:
    """Send/receive seam. Timestamps are microseconds (kernel-timestamp
    equivalents, used for RTT measurement)."""

    def set_receiver(self, instance_id: str, callback) -> None:
        raise NotImplementedError

    def send(self, if_name: str, packet: SparkHelloPacket) -> int:
        """Send on interface; returns the send timestamp in us."""
        raise NotImplementedError

    def now_us(self) -> int:
        return int(time.monotonic() * 1_000_000)


class UdpIoProvider(IoProvider):
    """Real UDP multicast provider (the production IoProvider).

    One socket per interface, bound to the Spark port and joined to the
    discovery multicast group on that interface — the reference's
    ff02::1:6666 scheme (openr/common/Constants.h:132, Spark.h:424), with
    an IPv4 group supported for environments without usable link-local
    IPv6 (e.g. loopback in containers, where same-host instances share the
    port via SO_REUSEPORT and the kernel delivers the group to every
    member). Receive timestamps come from the kernel via SO_TIMESTAMPNS
    ancillary data (the reference's scheme, spark/IoProvider.h), rebased
    onto the monotonic clock Spark's RTT math uses; when the option is
    unsupported the arrival-time fallback applies.
    """

    def __init__(
        self,
        port: int = 6666,
        group: str = "ff02::1",
        *,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.port = port
        self.group = group
        self._v6 = ":" in group
        self._loop = loop
        self._callback = None
        # if_name -> (socket, event loop, ifindex or None)
        self._endpoints: Dict[str, Tuple[object, object, Optional[int]]] = {}
        self._opening: set = set()  # interfaces with an open in flight
        self._closed = False
        # kernel timestamps are CLOCK_REALTIME; Spark's RTT math subtracts
        # monotonic now_us() values, so rebase with a fixed offset sampled
        # once (NTP slew is absorbed by the RTT step detector)
        self._mono_minus_real_us = int(
            time.monotonic() * 1_000_000 - time.time() * 1_000_000
        )

    # -- socket plumbing -------------------------------------------------

    def _make_socket(self, if_name: str):
        if self._v6:
            sock = socket.socket(socket.AF_INET6, socket.SOCK_DGRAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind(("::", self.port))
            ifindex = socket.if_nametoindex(if_name)
            mreq = socket.inet_pton(
                socket.AF_INET6, self.group
            ) + struct.pack("@I", ifindex)
            sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_JOIN_GROUP, mreq)
            sock.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_IF, ifindex
            )
            sock.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_LOOP, 1
            )
            sock.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_HOPS, 1
            )
        else:
            if_addr = _ipv4_addr_of(if_name)
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind(("", self.port))
            mreq = socket.inet_aton(self.group) + socket.inet_aton(if_addr)
            sock.setsockopt(
                socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq
            )
            sock.setsockopt(
                socket.IPPROTO_IP,
                socket.IP_MULTICAST_IF,
                socket.inet_aton(if_addr),
            )
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP, 1)
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_TTL, 1)
        try:
            # attribute arrivals to the right interface on multi-homed
            # hosts (the reference binds one socket per interface too)
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_BINDTODEVICE, if_name.encode()
            )
        except (OSError, AttributeError):
            pass  # unprivileged: wildcard-bound socket still works
        try:
            # kernel receive timestamps (spark/IoProvider.h SO_TIMESTAMPNS)
            sock.setsockopt(socket.SOL_SOCKET, _SO_TIMESTAMPNS, 1)
        except OSError:
            pass  # fallback: arrival-time stamps in _on_readable
        sock.setblocking(False)
        return sock

    async def add_interface(self, if_name: str) -> None:
        """Open + join the multicast socket for one interface."""
        if if_name in self._endpoints or self._closed:
            return
        import socket as socket_mod


        sock = self._make_socket(if_name)
        ifindex = (
            socket_mod.if_nametoindex(if_name) if self._v6 else None
        )
        loop = self._loop or asyncio.get_event_loop()
        if self._closed:  # closed while this open was in flight
            sock.close()
            return
        # raw reader (not a DatagramProtocol): recvmsg exposes the
        # SCM_TIMESTAMPNS ancillary data asyncio transports hide
        loop.add_reader(sock.fileno(), self._on_readable, if_name, sock)
        self._endpoints[if_name] = (sock, loop, ifindex)

    def _on_readable(self, if_name: str, sock) -> None:
        """Drain the socket; each datagram carries its kernel receive
        timestamp (SCM_TIMESTAMPNS cmsg) when the option took."""
        while True:
            try:
                data, ancdata, _flags, _addr = sock.recvmsg(65535, 256)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # socket closed under us
            if not data:
                return
            recv_us = None
            for level, ctype, cdata in ancdata:
                if (
                    level == socket.SOL_SOCKET
                    and ctype == _SO_TIMESTAMPNS
                    and len(cdata) >= 16
                ):
                    sec, nsec = _TIMESPEC.unpack_from(cdata)
                    rt_us = sec * 1_000_000 + nsec // 1_000
                    # rebase with the offset sampled NOW: its error is only
                    # the realtime-vs-monotonic divergence over the queue
                    # window (effectively zero), so NTP slew never
                    # accumulates as RTT bias. The stored offset exists
                    # only to LOG large realtime clock steps — queue delay
                    # shifts both clocks equally and cannot false-trigger.
                    offset_now = int(
                        time.monotonic() * 1_000_000
                        - time.time() * 1_000_000
                    )
                    if abs(offset_now - self._mono_minus_real_us) > 100_000:
                        logging.getLogger(__name__).info(
                            "realtime clock step detected: offset moved "
                            "%dus", offset_now - self._mono_minus_real_us
                        )
                    self._mono_minus_real_us = offset_now
                    recv_us = rt_us + offset_now
            callback = self._callback
            if callback is None:
                continue
            try:
                packet = packet_from_bytes(data)
            except (ValueError, KeyError, TypeError, AttributeError):
                continue  # not a Spark packet; ignore
            callback(
                ReceivedPacket(
                    if_name=if_name,
                    packet=packet,
                    recv_ts_us=(
                        recv_us if recv_us is not None else self.now_us()
                    ),
                )
            )

    def close(self) -> None:
        self._closed = True
        self._callback = None
        for sock, loop, _ifindex in self._endpoints.values():
            try:
                loop.remove_reader(sock.fileno())
            except (OSError, ValueError, RuntimeError):
                # RuntimeError: the event loop is already closed; the
                # remaining sockets must still be closed below
                pass
            sock.close()
        self._endpoints.clear()
        self._opening.clear()

    # -- IoProvider surface ----------------------------------------------

    def set_receiver(self, instance_id: str, callback) -> None:
        self._callback = callback

    def send(self, if_name: str, packet: SparkHelloPacket) -> int:
        endpoint = self._endpoints.get(if_name)
        now = self.now_us()
        if endpoint is None:
            # first send on an unopened interface: schedule the socket
            # open and drop this packet — Spark's fast-init hello timer
            # retries within tens of ms (Spark.cpp fast-init cadence)
            if if_name not in self._opening:
                self._opening.add(if_name)

                async def _open() -> None:
                    try:
                        await self.add_interface(if_name)
                    except OSError as exc:
                        # interface down / unaddressed: next send retries
                        import logging

                        logging.getLogger(__name__).warning(
                            "spark: open %s failed: %s", if_name, exc
                        )
                    finally:
                        self._opening.discard(if_name)

                loop = self._loop or asyncio.get_event_loop()
                loop.create_task(_open())
            return now
        sock, _loop, ifindex = endpoint
        data = packet_to_bytes(packet)
        try:
            if self._v6:
                sock.sendto(data, (self.group, self.port, 0, ifindex))
            else:
                sock.sendto(data, (self.group, self.port))
        except OSError:
            pass  # dropped datagram (incl. EAGAIN): Spark's timers retransmit
        return now


# SOL_SOCKET option/cmsg number for nanosecond receive timestamps
# (asm-generic sockios: SO_TIMESTAMPNS_OLD == SCM_TIMESTAMPNS == 35)
_SO_TIMESTAMPNS = getattr(socket, "SO_TIMESTAMPNS", 35)
_TIMESPEC = struct.Struct("@qq")


def _ipv4_addr_of(if_name: str) -> str:
    """Primary IPv4 address of an interface (for IP_MULTICAST_IF)."""
    if if_name == "lo":
        return "127.0.0.1"
    import fcntl

    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # SIOCGIFADDR
        packed = fcntl.ioctl(
            sock.fileno(),
            0x8915,
            struct.pack("256s", if_name[:15].encode()),
        )
        return socket.inet_ntoa(packed[20:24])
    finally:
        sock.close()


class MockIoNetwork:
    """Shared virtual network: connects (instance, iface) endpoints in
    pairs with per-link latency (ConnectedIfPairs)."""

    def __init__(self) -> None:
        # (instance, iface) -> list of ((instance, iface), latency_s)
        self._links: Dict[Tuple[str, str], List[Tuple[Tuple[str, str], float]]] = {}
        self._receivers: Dict[str, object] = {}
        self._partitioned: set = set()
        # optional chaos overlay (testing/chaos.ChaosMesh): seeded
        # per-direction loss / duplication / extra delay / partition
        # applied to every delivery on top of the base link latency
        self.chaos = None

    def connect(
        self,
        a: Tuple[str, str],
        b: Tuple[str, str],
        latency_ms: float = 1.0,
    ) -> None:
        self._links.setdefault(a, []).append((b, latency_ms / 1000.0))
        self._links.setdefault(b, []).append((a, latency_ms / 1000.0))

    def disconnect(self, a: Tuple[str, str], b: Tuple[str, str]) -> None:
        self._partitioned.add((a, b))
        self._partitioned.add((b, a))

    def reconnect(self, a: Tuple[str, str], b: Tuple[str, str]) -> None:
        self._partitioned.discard((a, b))
        self._partitioned.discard((b, a))

    def provider(self, instance_id: str) -> "MockIoProvider":
        return MockIoProvider(self, instance_id)

    def interfaces_of(self, instance_id: str) -> List[str]:
        """Interfaces of one instance with at least one link — what a
        respawned node must bring back up after a whole-node restart
        (the fabric keeps the wiring across daemon incarnations)."""
        return sorted(
            {
                iface
                for (inst, iface) in self._links
                if inst == instance_id
            }
        )

    def _register(self, instance_id: str, callback) -> None:
        self._receivers[instance_id] = callback

    def _send(
        self, src: Tuple[str, str], packet: SparkHelloPacket
    ) -> int:
        now_us = int(time.monotonic() * 1_000_000)
        loop = asyncio.get_event_loop()
        for dst, latency in self._links.get(src, []):
            if (src, dst) in self._partitioned:
                continue
            dst_instance, dst_iface = dst
            callback = self._receivers.get(dst_instance)
            if callback is None:
                continue
            copies, extra = 1, 0.0
            if self.chaos is not None:
                verdict = self.chaos.packet_verdict(src[0], dst_instance)
                if verdict is None:
                    continue  # dropped by the chaos schedule
                copies, extra = verdict
            for _ in range(copies):
                loop.call_later(
                    latency + extra,
                    callback,
                    ReceivedPacket(
                        if_name=dst_iface,
                        packet=packet,
                        recv_ts_us=int(
                            (time.monotonic() + latency + extra) * 1_000_000
                        ),
                    ),
                )
        return now_us


class MockIoProvider(IoProvider):
    def __init__(self, network: MockIoNetwork, instance_id: str) -> None:
        self._network = network
        self.instance_id = instance_id

    def set_receiver(self, instance_id: str, callback) -> None:
        self._network._register(instance_id, callback)

    def send(self, if_name: str, packet: SparkHelloPacket) -> int:
        return self._network._send((self.instance_id, if_name), packet)
