"""Spark wire messages (openr/if/Spark.thrift equivalents).

SparkHelloMsg:43 — periodic discovery beacon carrying reflected neighbor
timestamps for RTT measurement and bidirectionality detection.
SparkHandshakeMsg:67 — negotiation (transport addresses, ports, area).
SparkHeartbeatMsg:93 — liveness keepalive after establishment.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass
class ReflectedNeighborInfo:
    """Timestamps echoed back to a neighbor (Spark.thrift:30-40)."""

    last_nbr_msg_sent_ts_us: int = 0  # when the nbr last sent us a hello
    last_my_msg_rcvd_ts_us: int = 0  # when we received it


@dataclass
class SparkHelloMsg:
    domain_name: str
    node_name: str
    if_name: str
    seq_num: int
    neighbor_infos: Dict[str, ReflectedNeighborInfo] = field(
        default_factory=dict
    )
    version: int = 1
    solicit_response: bool = False
    restarting: bool = False
    sent_ts_in_us: int = 0


@dataclass
class SparkHandshakeMsg:
    node_name: str
    is_adj_established: bool
    hold_time_ms: int
    graceful_restart_time_ms: int
    transport_address_v6: str
    transport_address_v4: str
    openr_ctrl_thrift_port: int
    kvstore_cmd_port: int
    area: str
    neighbor_node_name: Optional[str] = None
    # host where this node's KvStore peer RPC listens (TCP deployments);
    # distinct from the data-plane transport addresses above
    kvstore_host: str = ""


@dataclass
class SparkHeartbeatMsg:
    node_name: str
    seq_num: int


@dataclass
class SparkHelloPacket:
    """Union envelope (Spark.thrift SparkHelloPacket:103)."""

    hello_msg: Optional[SparkHelloMsg] = None
    handshake_msg: Optional[SparkHandshakeMsg] = None
    heartbeat_msg: Optional[SparkHeartbeatMsg] = None


# ---------------------------------------------------------------------------
# Wire codec — the reference serializes SparkHelloPacket with thrift compact
# protocol onto the UDP multicast socket (Spark.cpp sendHelloMsg); here the
# envelope rides JSON (one datagram per packet).
# ---------------------------------------------------------------------------


def packet_to_bytes(packet: SparkHelloPacket) -> bytes:
    return json.dumps(
        dataclasses.asdict(packet), separators=(",", ":")
    ).encode()


def packet_from_bytes(data: bytes) -> SparkHelloPacket:
    d = json.loads(data)
    hello = d.get("hello_msg")
    handshake = d.get("handshake_msg")
    heartbeat = d.get("heartbeat_msg")
    return SparkHelloPacket(
        hello_msg=(
            SparkHelloMsg(
                **{
                    **hello,
                    "neighbor_infos": {
                        k: ReflectedNeighborInfo(**v)
                        for k, v in (hello.get("neighbor_infos") or {}).items()
                    },
                }
            )
            if hello is not None
            else None
        ),
        handshake_msg=(
            SparkHandshakeMsg(**handshake) if handshake is not None else None
        ),
        heartbeat_msg=(
            SparkHeartbeatMsg(**heartbeat) if heartbeat is not None else None
        ),
    )
