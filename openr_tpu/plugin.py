"""Plugin extension seam (reference: openr/plugin/Plugin.h:24-34).

An external integration (the reference's use case is a BGP speaker; the
rebuild's is also the slot where an alternative route-computation backend
can inject static routes) receives the daemon's queues and config:

  - `prefix_updates_queue`   — push PrefixEvent batches to originate
                               prefixes through PrefixManager
  - `static_routes_queue`    — push StaticRoutesUpdate deltas straight into
                               Decision (MPLS label -> nexthops), bypassing
                               SPF (Decision.cpp:868-907 semantics)
  - `route_updates_reader`   — RQueue reader of computed DecisionRouteUpdate
                               deltas (to re-advertise into BGP etc.)
  - `config`                 — the running Config

`plugin_start`/`plugin_stop` are process-wide hooks, default no-op
(Plugin.cpp:11-19); a deployment replaces them via `set_plugin`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from openr_tpu.messaging import RQueue, ReplicateQueue


@dataclass
class PluginArgs:
    prefix_updates_queue: ReplicateQueue
    static_routes_queue: ReplicateQueue
    route_updates_reader: RQueue
    config: object


_start_hook: Optional[Callable[[PluginArgs], None]] = None
_stop_hook: Optional[Callable[[], None]] = None


def set_plugin(
    start: Callable[[PluginArgs], None],
    stop: Optional[Callable[[], None]] = None,
) -> None:
    """Install a plugin implementation (before the daemon starts)."""
    global _start_hook, _stop_hook
    _start_hook = start
    _stop_hook = stop


def has_plugin() -> bool:
    """Whether a plugin is installed; the daemon skips building PluginArgs
    (which registers a route-updates queue reader that must be drained)
    when nothing would consume them."""
    return _start_hook is not None


def plugin_start(args: PluginArgs) -> None:
    """Invoked by the daemon when BGP peering is enabled (Main.cpp:589-595)."""
    if _start_hook is not None:
        _start_hook(args)


def plugin_stop() -> None:
    if _stop_hook is not None:
        _stop_hook()
