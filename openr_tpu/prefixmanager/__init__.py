"""Prefix origination (PrefixManager).

Equivalent of openr/prefix-manager/PrefixManager.{h,cpp}.
"""

from openr_tpu.prefixmanager.prefix_manager import (
    PrefixEventCommand,
    PrefixManager,
    PrefixManagerConfig,
    PrefixUpdateRequest,
)

__all__ = [
    "PrefixEventCommand",
    "PrefixManager",
    "PrefixManagerConfig",
    "PrefixUpdateRequest",
]
