"""PrefixManager: tracks prefixes this node originates, advertises them
into KvStore, and redistributes computed routes across areas.

Behavioral port of openr/prefix-manager/PrefixManager.{h,cpp}:
  - per-type prefix map; for the same prefix advertised under several
    types, the lowest PrefixType wins deterministically
    (PrefixManager.h:178-181).
  - per-prefix keys 'prefix:<node>:<area>:[<prefix>]' with persist
    semantics and a tombstone (deletePrefix) on withdraw; keysToClear
    tracks stale keys seen in KvStore so they get withdrawn
    (PrefixManager.cpp:159-192).
  - throttled KvStore sync batching multiple API calls
    (syncKvStoreThrottled_, PrefixManager.h:166).
  - non-ephemeral state persisted in the config store so originated
    prefixes survive restart (persistPrefixDb).
  - consumes PrefixUpdateRequest queue (ADD/WITHDRAW/SYNC per type) and
    Decision route updates for cross-area redistribution: learned unicast
    routes are re-advertised into areas they did NOT come from, with
    bestArea appended to area_stack and type normalized to RIB
    (PrefixManager.cpp:603-645).
"""

from __future__ import annotations

import asyncio
import enum
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.kvstore import KvStoreClient
from openr_tpu.messaging import QueueClosedError, RQueue
from openr_tpu.types import (
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    PrefixType,
    prefix_key,
    replace,
)
from openr_tpu.utils import AsyncThrottle, serializer
from openr_tpu.utils.ownership import owned_by
from openr_tpu.utils.counters import CountersMixin

log = logging.getLogger(__name__)

CONFIG_STORE_KEY = "prefix-manager-config"
# deterministic type preference: lowest enum position wins
_TYPE_ORDER = {t: i for i, t in enumerate(PrefixType)}


class PrefixEventCommand(enum.Enum):
    """openr/if/PrefixManager.thrift PrefixUpdateCommand:16."""

    ADD_PREFIXES = "ADD_PREFIXES"
    WITHDRAW_PREFIXES = "WITHDRAW_PREFIXES"
    WITHDRAW_PREFIXES_BY_TYPE = "WITHDRAW_PREFIXES_BY_TYPE"
    SYNC_PREFIXES_BY_TYPE = "SYNC_PREFIXES_BY_TYPE"


@dataclass
class PrefixUpdateRequest:
    """openr/if/PrefixManager.thrift PrefixUpdateRequest:23."""

    cmd: PrefixEventCommand
    type: Optional[PrefixType] = None
    prefixes: List[PrefixEntry] = field(default_factory=list)


@dataclass
class PrefixManagerConfig:
    node_name: str
    areas: List[str] = field(default_factory=lambda: ["0"])
    ttl_ms: int = -(2**31)  # TTL_INFINITY by default
    sync_throttle: float = 0.005
    persist: bool = True


@dataclass
class _Entry:
    """PrefixEntry + destination areas (PrefixManager.h:92-106)."""

    entry: PrefixEntry
    dst_areas: Set[str]


@owned_by("prefix-manager-loop")
class PrefixManager(CountersMixin):
    def __init__(
        self,
        config: PrefixManagerConfig,
        kvstore_client: KvStoreClient,
        config_store=None,
        prefix_updates: Optional[RQueue] = None,
        route_updates: Optional[RQueue] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.config = config
        self.client = kvstore_client
        self.config_store = config_store
        self.prefix_updates = prefix_updates
        self.route_updates = route_updates
        self._loop = loop

        # type -> prefix -> _Entry (ordered by type preference at lookup)
        self.prefix_map: Dict[PrefixType, Dict[IpPrefix, _Entry]] = {}
        self.keys_to_clear: Set[Tuple[str, str]] = set()  # (area, key)
        self._advertised: Set[Tuple[str, str]] = set()
        self._sync_throttle = AsyncThrottle(
            config.sync_throttle, self.sync_kvstore, loop=loop
        )
        self._tasks: List[asyncio.Task] = []
        self.counters: Dict[str, int] = {}
        self._load_persisted()
        # reclaim stale keys from a previous incarnation
        for area in config.areas:
            pub = self.client.kvstore.dump_all(area=area)
            marker = prefix_key(config.node_name)
            for key, value in pub.key_vals.items():
                if key.startswith(marker + ":") or key == marker:
                    self.keys_to_clear.add((area, key))

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self.prefix_updates is not None:
            self._tasks.append(
                self.loop().create_task(self._consume_requests())
            )
        if self.route_updates is not None:
            self._tasks.append(self.loop().create_task(self._consume_routes()))
        if self.prefix_map:
            self._sync_throttle()

    def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        self._tasks.clear()
        self._sync_throttle.cancel()

    async def _consume_requests(self) -> None:
        while True:
            try:
                req = await self.prefix_updates.get()
            except (QueueClosedError, asyncio.CancelledError):
                return
            self.process_request(req)

    async def _consume_routes(self) -> None:
        while True:
            try:
                update = await self.route_updates.get()
            except (QueueClosedError, asyncio.CancelledError):
                return
            self.process_decision_route_updates(update)

    # ------------------------------------------------------------------
    # write APIs
    # ------------------------------------------------------------------

    def process_request(self, req: PrefixUpdateRequest) -> None:
        if req.cmd == PrefixEventCommand.ADD_PREFIXES:
            self.advertise_prefixes(req.prefixes)
        elif req.cmd == PrefixEventCommand.WITHDRAW_PREFIXES:
            self.withdraw_prefixes(req.prefixes)
        elif req.cmd == PrefixEventCommand.WITHDRAW_PREFIXES_BY_TYPE:
            assert req.type is not None
            self.withdraw_prefixes_by_type(req.type)
        elif req.cmd == PrefixEventCommand.SYNC_PREFIXES_BY_TYPE:
            assert req.type is not None
            self.sync_prefixes_by_type(req.type, req.prefixes)

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def advertise_prefixes(
        self,
        prefixes: List[PrefixEntry],
        dst_areas: Optional[Set[str]] = None,
    ) -> bool:
        dst = set(dst_areas) if dst_areas is not None else set(
            self.config.areas
        )
        changed = False
        for entry in prefixes:
            by_prefix = self.prefix_map.setdefault(entry.type, {})
            existing = by_prefix.get(entry.prefix)
            new = _Entry(entry, set(dst))
            if existing is not None:
                new.dst_areas |= existing.dst_areas
                if (
                    existing.entry == entry
                    and existing.dst_areas == new.dst_areas
                ):
                    continue
            by_prefix[entry.prefix] = new
            changed = True
        if changed:
            self._persist()
            self._sync_throttle()
        return changed

    def withdraw_prefixes(self, prefixes: List[PrefixEntry]) -> bool:
        changed = False
        for entry in prefixes:
            by_prefix = self.prefix_map.get(entry.type, {})
            if by_prefix.pop(entry.prefix, None) is not None:
                changed = True
        if changed:
            self._persist()
            self._sync_throttle()
        return changed

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def withdraw_prefixes_by_type(self, ptype: PrefixType) -> bool:
        removed = bool(self.prefix_map.pop(ptype, None))
        if removed:
            self._persist()
            self._sync_throttle()
        return removed

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def sync_prefixes_by_type(
        self, ptype: PrefixType, prefixes: List[PrefixEntry]
    ) -> bool:
        desired = {e.prefix: e for e in prefixes}
        current = self.prefix_map.get(ptype, {})
        if {p: e.entry for p, e in current.items()} == desired:
            return False
        self.prefix_map[ptype] = {
            p: _Entry(e, set(self.config.areas)) for p, e in desired.items()
        }
        self._persist()
        self._sync_throttle()
        return True

    # ------------------------------------------------------------------
    # read APIs
    # ------------------------------------------------------------------

    def get_prefixes(self) -> List[PrefixEntry]:
        return [
            e.entry
            for by_prefix in self.prefix_map.values()
            for e in by_prefix.values()
        ]

    def get_prefixes_by_type(self, ptype: PrefixType) -> List[PrefixEntry]:
        return [e.entry for e in self.prefix_map.get(ptype, {}).values()]

    # ------------------------------------------------------------------
    # KvStore sync
    # ------------------------------------------------------------------

    def _best_entries(self) -> Dict[IpPrefix, _Entry]:
        """Collapse types: lowest PrefixType wins per prefix."""
        best: Dict[IpPrefix, Tuple[int, _Entry]] = {}
        for ptype, by_prefix in self.prefix_map.items():
            rank = _TYPE_ORDER[ptype]
            for prefix, entry in by_prefix.items():
                cur = best.get(prefix)
                if cur is None or rank < cur[0]:
                    best[prefix] = (rank, entry)
        return {p: e for p, (_, e) in best.items()}

    def sync_kvstore(self) -> None:
        """Advertise the current best set as per-prefix keys; tombstone
        everything stale (PrefixManager.cpp syncKvStore)."""
        self._bump("prefix_manager.kvstore_syncs")
        node = self.config.node_name
        now_advertised: Set[Tuple[str, str]] = set()
        for prefix, entry in self._best_entries().items():
            for area in entry.dst_areas:
                key = prefix_key(node, prefix, area)
                db = PrefixDatabase(
                    this_node_name=node,
                    prefix_entries=[entry.entry],
                    area=area,
                )
                self.client.persist_key(
                    key,
                    serializer.dumps(db),
                    area=area,
                    ttl=self.config.ttl_ms,
                )
                now_advertised.add((area, key))
                self.keys_to_clear.discard((area, key))

        for area, key in (self._advertised - now_advertised) | set(
            self.keys_to_clear
        ):
            tombstone = PrefixDatabase(
                this_node_name=node, delete_prefix=True, area=area
            )
            self.client.clear_key(
                key, serializer.dumps(tombstone), area=area
            )
            self._bump("prefix_manager.keys_cleared")
        self.keys_to_clear.clear()
        self._advertised = now_advertised

    # ------------------------------------------------------------------
    # cross-area redistribution
    # ------------------------------------------------------------------

    def process_decision_route_updates(self, update) -> None:
        """Re-originate learned routes into other areas
        (PrefixManager.cpp:603-645)."""
        if len(self.config.areas) == 1:
            return
        to_advertise: List[Tuple[PrefixEntry, Set[str]]] = []
        to_withdraw: List[PrefixEntry] = []
        for route in update.unicast_routes_to_update:
            best = route.best_prefix_entry
            if best is None:
                continue
            entry = replace(
                best,
                type=PrefixType.RIB,
                area_stack=tuple(best.area_stack)
                + ((route.best_area,) if route.best_area else ()),
            )
            dst = set(self.config.areas)
            for nh in route.nexthops:
                if nh.area is not None:
                    dst.discard(nh.area)
            if dst:
                to_advertise.append((entry, dst))
        for prefix in update.unicast_routes_to_delete:
            to_withdraw.append(
                PrefixEntry(prefix=prefix, type=PrefixType.RIB)
            )
        for entry, dst in to_advertise:
            self.advertise_prefixes([entry], dst_areas=dst)
        if to_withdraw:
            self.withdraw_prefixes(to_withdraw)

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def _persist(self) -> None:
        if self.config_store is None or not self.config.persist:
            return
        self.config_store.store_obj(
            CONFIG_STORE_KEY,
            {
                ptype.value: list(
                    (e.entry, sorted(e.dst_areas))
                    for e in by_prefix.values()
                )
                for ptype, by_prefix in self.prefix_map.items()
            },
        )

    def _load_persisted(self) -> None:
        if self.config_store is None or not self.config.persist:
            return
        state = self.config_store.load_obj(CONFIG_STORE_KEY)
        if not isinstance(state, dict):
            return
        for type_name, entries in state.items():
            try:
                ptype = PrefixType(type_name)
            except ValueError:
                continue
            by_prefix = self.prefix_map.setdefault(ptype, {})
            for entry, dst_areas in entries:
                by_prefix[entry.prefix] = _Entry(entry, set(dst_areas))
