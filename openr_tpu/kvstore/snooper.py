"""KvStore snooper: live view of LSDB churn on a running node.

Port of the reference tool (openr/kvstore/tools/KvStoreSnooper.cpp):
connects to a node's ctrl server, subscribes to the filtered KvStore
stream, and prints each delta — decoded adjacency / prefix databases for
`adj:`/`prefix:` keys, raw version bumps for everything else.

Usage:  python -m openr_tpu.kvstore.snooper [--host H] [--port P]
                [--area A] [--prefix adj: --prefix prefix:]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, Optional

from openr_tpu.ctrl.client import BlockingCtrlClient
from openr_tpu.utils.serializer import loads as deserialize


def _describe(key: str, value: Dict) -> str:
    version = value.get("version")
    originator = value.get("originator_id")
    head = f"{key} v={version} from={originator} ttl={value.get('ttl')}"
    blob = value.get("value")
    if blob is None:
        return head + " (ttl refresh)"
    try:
        import base64

        obj = deserialize(base64.b64decode(blob))
    except Exception:
        return head + f" ({len(blob)}B opaque)"
    if key.startswith("adj:"):
        adjs = getattr(obj, "adjacencies", None)
        if adjs is not None:
            neighbors = ", ".join(
                f"{a.other_node_name}/{a.if_name}:{a.metric}" for a in adjs
            )
            overloaded = " OVERLOADED" if obj.is_overloaded else ""
            return f"{head}{overloaded} adjs=[{neighbors}]"
    if key.startswith("prefix:"):
        entries = getattr(obj, "prefix_entries", None)
        if entries is not None:
            pfx = ", ".join(str(e.prefix) for e in entries)
            return f"{head} prefixes=[{pfx}]"
    return head + f" ({type(obj).__name__})"


def snoop(
    host: str,
    port: int,
    area: str = "0",
    prefixes: Optional[Iterable[str]] = None,
    out=sys.stdout,
    max_frames: Optional[int] = None,
    ssl_context=None,
) -> int:
    """Stream publications and print them; returns frames consumed."""
    client = BlockingCtrlClient(host, port, ssl_context=ssl_context)
    frames = 0
    try:
        for pub in client.subscribe(
            "subscribeKvStoreFilter",
            area=area,
            prefixes=list(prefixes or []),
        ):
            tag = "SNAPSHOT" if frames == 0 else "DELTA"
            for key, value in sorted(pub.get("key_vals", {}).items()):
                print(f"[{tag}] {_describe(key, value)}", file=out)
            for key in pub.get("expired_keys", []):
                print(f"[{tag}] {key} EXPIRED", file=out)
            out.flush()
            frames += 1
            if max_frames is not None and frames >= max_frames:
                break
    finally:
        client.close()
    return frames


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2018)
    p.add_argument("--area", default="0")
    p.add_argument(
        "--prefix",
        action="append",
        dest="prefixes",
        help="key prefix filter (repeatable), e.g. adj: or prefix:",
    )
    args = p.parse_args(argv)
    try:
        snoop(args.host, args.port, args.area, args.prefixes)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
