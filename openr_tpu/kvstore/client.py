"""In-process KvStore client with persist semantics.

Equivalent of openr/kvstore/KvStoreClientInternal.{h,cpp}: persist_key keeps a
key advertised under our originator id — if a peer overwrites it (higher
version from another originator) the client re-advertises with a bumped
version (checkPersistKeyInStore / keyValUpdated semantics); TTL-carrying keys
are refreshed at ttl/4 cadence with ttlVersion bumps.

Warm boot (docs/Robustness.md "Graceful restart & warm boot"): when a
PersistentStore is attached, every self-originated advertisement records
its version as a durable **version floor**. A restarted daemon boots with
an empty local store but its peers still hold the previous incarnation's
replicas at version N through the GR window; without the floor the fresh
node would advertise v1, lose the CRDT merge everywhere, and only heal
after the clobber-detection round trip. With it, the first re-advertisement
goes out at N+1 and strictly supersedes every stale replica immediately —
counted in `kvstore.restart_syncs`.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Dict, List, Optional, Tuple

from openr_tpu.kvstore.store import KvStore
from openr_tpu.messaging import QueueClosedError
from openr_tpu.types import TTL_INFINITY, Publication, Value

# PersistentStore key holding {"<area>|<key>": last-advertised version};
# shared by every client of one daemon (read-merge-write, floors only grow)
VERSION_FLOOR_KEY = "kvstore-version-floors"


class KvStoreClient:
    def __init__(
        self,
        kvstore: KvStore,
        node_id: Optional[str] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        config_store=None,  # optional PersistentStore (version floors)
    ) -> None:
        self.kvstore = kvstore
        self.node_id = node_id or kvstore.node_id
        self._loop = loop
        self.config_store = config_store
        # "<area>|<key>" -> highest version this node ever advertised
        self._version_floors: Dict[str, int] = {}
        if config_store is not None:
            try:
                loaded = config_store.load_obj(VERSION_FLOOR_KEY)
            except Exception:
                loaded = None  # a corrupt floor record is a cold start
            if loaded:
                self._version_floors = {
                    str(k): int(v) for k, v in dict(loaded).items()
                }
        # (area, key) -> desired value bytes + ttl
        self._persisted: Dict[Tuple[str, str], Tuple[bytes, int]] = {}
        self._key_callbacks: Dict[
            Tuple[str, str], List[Callable[[str, Optional[Value]], None]]
        ] = {}
        self._ttl_timers: Dict[Tuple[str, str], asyncio.TimerHandle] = {}
        self._reader = kvstore.updates_queue.get_reader()
        self._task = self.loop().create_task(self._watch())

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    # ------------------------------------------------------------------

    def set_key(
        self,
        key: str,
        value: bytes,
        area: str = "0",
        ttl: int = TTL_INFINITY,
        span_stages=None,
    ) -> None:
        """Advertise with a version higher than whatever is in the store.

        `span_stages` — monotonic pre-publish convergence-span marks
        (Publication.span_stages) — ride through to the store's local
        publication so Decision's span covers the producing module's
        latency too (LinkMonitor's spark→advertise chain)."""
        existing = self.kvstore.get_key(key, area=area)
        version = (existing.version + 1) if existing is not None else 1
        floor = self._version_floors.get(f"{area}|{key}", 0)
        if floor >= version:
            # warm boot: peers hold our previous incarnation's replica at
            # `floor`; re-advertise strictly above it so the fresh value
            # wins the CRDT merge everywhere immediately
            version = floor + 1
            self.kvstore.db(area)._bump("kvstore.restart_syncs")
        self._record_version_floor(area, key, version)
        self.kvstore.set_key(
            key,
            Value(
                version=version,
                originator_id=self.node_id,
                value=value,
                ttl=ttl,
            ),
            area=area,
            span_stages=span_stages,
        )

    def persist_key(
        self,
        key: str,
        value: bytes,
        area: str = "0",
        ttl: int = TTL_INFINITY,
        span_stages=None,
    ) -> None:
        """Advertise and keep advertised: re-advertise if overwritten."""
        self._persisted[(area, key)] = (value, ttl)
        existing = self.kvstore.get_key(key, area=area)
        if (
            existing is not None
            and existing.originator_id == self.node_id
            and existing.value == value
        ):
            self._schedule_ttl_refresh(area, key, existing, ttl)
            return  # already ours and current
        self.set_key(key, value, area=area, ttl=ttl, span_stages=span_stages)
        stored = self.kvstore.get_key(key, area=area)
        if stored is not None:
            self._schedule_ttl_refresh(area, key, stored, ttl)

    def unset_key(self, key: str, area: str = "0") -> None:
        """Stop persisting; the key ages out by TTL (or stays for others)."""
        self._persisted.pop((area, key), None)
        timer = self._ttl_timers.pop((area, key), None)
        if timer is not None:
            timer.cancel()

    def clear_key(
        self, key: str, value: bytes = b"", area: str = "0", ttl: int = 1000
    ) -> None:
        """Actively supersede the key with a short-ttl tombstone value."""
        self.unset_key(key, area=area)
        self.set_key(key, value, area=area, ttl=ttl)

    def get_key(self, key: str, area: str = "0") -> Optional[Value]:
        return self.kvstore.get_key(key, area=area)

    def subscribe_key(
        self,
        key: str,
        callback: Callable[[str, Optional[Value]], None],
        area: str = "0",
    ) -> None:
        self._key_callbacks.setdefault((area, key), []).append(callback)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for timer in self._ttl_timers.values():
            timer.cancel()
        self._ttl_timers.clear()

    # ------------------------------------------------------------------

    def _record_version_floor(self, area: str, key: str, version: int) -> None:
        """Persist the advertised version so the NEXT incarnation starts
        above it. Read-merge-write against the shared config-store record
        (several clients — LinkMonitor's and the daemon's — share one
        store; floors only grow, so max-merge is exact). The write rides
        the PersistentStore's debounced write-behind."""
        fk = f"{area}|{key}"
        if self._version_floors.get(fk, 0) >= version:
            return
        self._version_floors[fk] = version
        if self.config_store is None:
            return
        try:
            stored = dict(
                self.config_store.load_obj(VERSION_FLOOR_KEY) or {}
            )
        except Exception:
            stored = {}
        if stored.get(fk, 0) < version:
            stored[fk] = version
            self.config_store.store_obj(VERSION_FLOOR_KEY, stored)

    def _schedule_ttl_refresh(
        self, area: str, key: str, stored: Value, ttl: int
    ) -> None:
        if ttl == TTL_INFINITY:
            return
        old = self._ttl_timers.pop((area, key), None)
        if old is not None:
            old.cancel()
        self._ttl_timers[(area, key)] = self.loop().call_later(
            ttl / 1000.0 / 4,  # refresh at ttl/4 (Constants.h kTtlRefresh)
            self._refresh_ttl,
            area,
            key,
        )

    def _refresh_ttl(self, area: str, key: str) -> None:
        self._ttl_timers.pop((area, key), None)
        desired = self._persisted.get((area, key))
        if desired is None:
            return
        value_bytes, ttl = desired
        existing = self.kvstore.get_key(key, area=area)
        if existing is None or existing.originator_id != self.node_id:
            return  # _watch will re-advertise
        refresh = Value(
            version=existing.version,
            originator_id=self.node_id,
            value=None,
            ttl=ttl,
            ttl_version=existing.ttl_version + 1,
        )
        self.kvstore.db(area).set_key_vals({key: refresh})
        updated = self.kvstore.get_key(key, area=area)
        if updated is not None:
            self._schedule_ttl_refresh(area, key, updated, ttl)

    async def _watch(self) -> None:
        """Re-advertise persisted keys when peers overwrite them and fire
        key subscriptions."""
        try:
            while True:
                pub: Publication = await self._reader.get()
                for key, value in pub.key_vals.items():
                    for cb in self._key_callbacks.get((pub.area, key), []):
                        cb(key, value)
                    desired = self._persisted.get((pub.area, key))
                    if desired is None:
                        continue
                    if value.value is None:
                        continue  # ttl refresh, not a clobber
                    value_bytes, ttl = desired
                    if (
                        value.originator_id != self.node_id
                        or value.value != value_bytes
                    ):
                        # someone clobbered our key: take it back
                        self.set_key(
                            key, value_bytes, area=pub.area, ttl=ttl
                        )
                for key in pub.expired_keys:
                    for cb in self._key_callbacks.get((pub.area, key), []):
                        cb(key, None)
                    desired = self._persisted.get((pub.area, key))
                    if desired is not None:
                        value_bytes, ttl = desired
                        self.set_key(key, value_bytes, area=pub.area, ttl=ttl)
        except (QueueClosedError, asyncio.CancelledError):
            pass
