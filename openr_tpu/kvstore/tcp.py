"""TCP peer transport for KvStore: real sockets between stores.

The reference reaches peers over ZMQ ROUTER sockets (legacy) or thrift
peer clients (openr/kvstore/KvStore.h:130,453); the sync/flood RPCs are
KEY_SET / KEY_DUMP plus the DUAL command channel (KvStore.cpp:892).
Here the same four RPCs ride newline-delimited JSON over TCP — the exact
framing the ctrl server uses (openr_tpu.ctrl.server) — so two OpenrDaemon
processes peer across real sockets:

  request:  {"id": N, "method": "kv.set|kv.dump|kv.dual|kv.floodTopoSet",
             "params": {...}}
  response: {"id": N, "result": ...} | {"id": N, "error": "..."}

Peer addresses are "host:port" strings (thrift::PeerSpec.peerAddr
equivalent). The client keeps one persistent connection per peer —
requests are serialized per connection, concurrent peers are independent —
and surfaces any socket/protocol failure as KvStoreTransportError so the
peer FSM (IDLE -> SYNCING -> INITIALIZED with exponential backoff,
KvStore.h:421) drives reconnects.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, Optional, Tuple

from openr_tpu.kvstore import wire
from openr_tpu.kvstore.transport import (
    KvStoreTransport,
    KvStoreTransportError,
)
from openr_tpu.types import KeyVals, Publication

log = logging.getLogger(__name__)

_MAX_LINE = 256 * 1024 * 1024  # a full-sync dump of a large LSDB is one line


class KvStoreTcpServer:
    """Serves one KvStore's peer-RPC surface on a TCP listen socket."""

    def __init__(
        self,
        store,
        host: str = "127.0.0.1",
        port: int = 0,
        ssl_context=None,
        tls_acceptable_peers=None,
    ) -> None:
        self._store = store
        self.host = host
        self.port = port  # 0 = ephemeral; real port filled in by start()
        self._ssl_context = ssl_context
        self._tls_acceptable_peers = tls_acceptable_peers
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _note_reject(self, kind: str) -> None:
        """Record one typed wire rejection on the store's counters
        (kvstore.wire.rejected.{kind}); tolerate store stand-ins without
        the hook (unit-test doubles)."""
        note = getattr(self._store, "note_wire_reject", None)
        if note is not None:
            note(kind)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_conn,
            self.host,
            self.port,
            limit=_MAX_LINE,
            ssl=self._ssl_context,
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # sever live peer connections: wait_closed() (3.12+) blocks on
            # open handlers, and peers hold persistent connections
            for writer in list(self._writers):
                writer.close()
            await self._server.wait_closed()
            self._server = None

    async def _serve_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._writers.add(writer)
        if self._ssl_context is not None:
            from openr_tpu.utils.tls import enforce_acceptable_peer

            if not enforce_acceptable_peer(
                writer, self._tls_acceptable_peers, log, "kvstore tcp"
            ):
                self._writers.discard(writer)
                return
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    req = json.loads(line)
                except ValueError:
                    self._note_reject("malformed")
                    req = {}
                req_id = req.get("id") if isinstance(req, dict) else None
                try:
                    if not isinstance(req, dict) or "method" not in req:
                        raise ValueError("malformed request")
                    reply = {
                        "id": req_id,
                        "result": self._dispatch(
                            req.get("method"), req.get("params") or {}
                        ),
                    }
                except Exception as exc:  # malformed request or handler error
                    # typed decode rejections (wire.WireDecodeError /
                    # native.NativeDecodeError) carry a .kind; count them
                    # and keep serving — a hostile frame must never take
                    # down the connection loop, let alone the store
                    kind = getattr(exc, "kind", None)
                    if kind is not None:
                        self._note_reject(kind)
                    reply = {
                        "id": req_id,
                        "error": f"{type(exc).__name__}: {exc}",
                    }
                writer.write(json.dumps(reply).encode() + b"\n")
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except ValueError as exc:
            # readline() raises when a frame exceeds the stream limit; make
            # the failure diagnosable instead of an unretrieved-task mystery
            self._note_reject("oversized")
            log.error("kvstore tcp: dropping connection, %s", exc)
        finally:
            self._writers.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    def _dispatch(self, method: str, params: Dict[str, Any]) -> Any:
        area = params.get("area", "0")
        if method == "kv.set":
            self._store.handle_set_key_vals(
                area,
                wire.key_vals_from_json(params.get("key_vals")),
                params.get("node_ids"),
                wire.perf_events_from_json(params.get("perf_events")),
            )
            return {}
        if method == "kv.dump":
            hashes = params.get("key_val_hashes")
            pub = self._store.handle_dump(
                area,
                wire.key_vals_from_json(hashes) if hashes is not None else None,
            )
            return wire.publication_to_json(pub)
        if method == "kv.dual":
            self._store.handle_dual_messages(
                area, wire.dual_messages_from_json(params.get("msgs") or {})
            )
            return {}
        if method == "kv.floodTopoSet":
            self._store.handle_flood_topo_set(
                area,
                params["root_id"],
                params["src_id"],
                params["set_child"],
                params.get("all_roots", False),
            )
            return {}
        raise ValueError(f"unknown method {method!r}")


class _PeerConn:
    """One persistent connection; requests serialized under a lock."""

    def __init__(self, host: str, port: int, ssl_context=None) -> None:
        self.host = host
        self.port = port
        self._ssl_context = ssl_context
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.lock = asyncio.Lock()
        self._next_id = 0

    async def _ensure(self, connect_timeout: float) -> None:
        if self.writer is None or self.writer.is_closing():
            self.reader, self.writer = await asyncio.wait_for(
                asyncio.open_connection(
                    self.host,
                    self.port,
                    limit=_MAX_LINE,
                    ssl=self._ssl_context,
                ),
                timeout=connect_timeout,
            )

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            self.writer = None
            self.reader = None

    async def call(
        self,
        method: str,
        params: Dict[str, Any],
        connect_timeout: float,
        rpc_timeout: float,
    ) -> Any:
        # timeouts apply inside the lock: a request queued behind a slow
        # full-sync dump must not have its clock running (nor kill the
        # connection the dump is still using) while it waits its turn
        async with self.lock:
            await self._ensure(connect_timeout)
            return await asyncio.wait_for(
                self._exchange(method, params), timeout=rpc_timeout
            )

    async def _exchange(self, method: str, params: Dict[str, Any]) -> Any:
        self._next_id += 1
        req_id = self._next_id
        self.writer.write(
            json.dumps(
                {"id": req_id, "method": method, "params": params}
            ).encode()
            + b"\n"
        )
        await self.writer.drain()
        try:
            line = await self.reader.readline()
        except ValueError as exc:
            # reply frame exceeded the stream limit: surface a diagnosable
            # transport error (and drop the now-desynced connection) instead
            # of leaking a bare ValueError into the sync FSM
            self.close()
            raise KvStoreTransportError(
                f"reply exceeds {_MAX_LINE}-byte frame limit: {exc}"
            )
        if not line:
            raise ConnectionError("peer closed connection")
        reply = json.loads(line)
        if reply.get("id") != req_id:
            raise ConnectionError(
                f"out-of-order reply {reply.get('id')} != {req_id}"
            )
        if "error" in reply:
            raise KvStoreTransportError(reply["error"])
        return reply.get("result")


class TcpTransport(KvStoreTransport):
    """KvStoreTransport over TCP; peer_addr is "host:port"."""

    def __init__(
        self,
        connect_timeout: float = 5.0,
        rpc_timeout: float = 120.0,
        ssl_context=None,
    ) -> None:
        self._ssl_context = ssl_context
        self._conns: Dict[Tuple[str, int], _PeerConn] = {}
        # connect_timeout bounds connection establishment; rpc_timeout
        # bounds a whole exchange and must stay generous — a full-sync
        # dump of a large LSDB is one (big) response line
        self._connect_timeout = connect_timeout
        self._rpc_timeout = rpc_timeout

    def set_ssl_context(self, ssl_context) -> None:
        """Install a client TLS context before any peer connection exists
        (the daemon wires TLS from config after constructing the
        transport); refuses once plaintext connections are cached."""
        if self._conns:
            # a bare assert would vanish under python -O and silently allow
            # mixed plaintext/TLS peering
            raise RuntimeError(
                "cannot enable TLS: plaintext peer connections already "
                "established"
            )
        self._ssl_context = ssl_context

    @staticmethod
    def _parse(peer_addr: str) -> Tuple[str, int]:
        host, _, port = peer_addr.rpartition(":")
        if not host or not port.isdigit():
            raise KvStoreTransportError(f"bad peer address {peer_addr!r}")
        return host, int(port)

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()

    async def _call(
        self, peer_addr: str, method: str, params: Dict[str, Any]
    ) -> Any:
        key = self._parse(peer_addr)
        conn = self._conns.get(key)
        if conn is None:
            conn = self._conns[key] = _PeerConn(
                *key, ssl_context=self._ssl_context
            )
        try:
            return await conn.call(
                method, params, self._connect_timeout, self._rpc_timeout
            )
        except KvStoreTransportError:
            raise  # remote handler error: connection is still good
        except Exception as exc:
            # socket-level failure: close so the next attempt (after the
            # peer FSM's backoff) reconnects fresh; the conn object stays
            # in _conns so queued callers re-ensure on it rather than
            # orphaning a live socket
            conn.close()
            raise KvStoreTransportError(
                f"{method} to {peer_addr} failed: "
                f"{type(exc).__name__}: {exc}"
            ) from exc

    async def set_key_vals(
        self,
        peer_addr: str,
        area: str,
        key_vals: KeyVals,
        node_ids: Optional[list] = None,
        perf_events=None,
    ) -> None:
        await self._call(
            peer_addr,
            "kv.set",
            {
                "area": area,
                "key_vals": wire.key_vals_to_json(key_vals),
                "node_ids": node_ids,
                "perf_events": wire.perf_events_to_json(perf_events),
            },
        )

    async def dump_key_vals(
        self,
        peer_addr: str,
        area: str,
        key_val_hashes: Optional[KeyVals] = None,
    ) -> Publication:
        result = await self._call(
            peer_addr,
            "kv.dump",
            {
                "area": area,
                "key_val_hashes": (
                    wire.key_vals_to_json(key_val_hashes)
                    if key_val_hashes is not None
                    else None
                ),
            },
        )
        return wire.publication_from_json(result)

    async def dual_messages(self, peer_addr: str, area: str, msgs) -> None:
        await self._call(
            peer_addr,
            "kv.dual",
            {"area": area, "msgs": wire.dual_messages_to_json(msgs)},
        )

    async def flood_topo_set(
        self,
        peer_addr: str,
        area: str,
        root_id: str,
        src_id: str,
        set_child: bool,
        all_roots: bool = False,
    ) -> None:
        await self._call(
            peer_addr,
            "kv.floodTopoSet",
            {
                "area": area,
                "root_id": root_id,
                "src_id": src_id,
                "set_child": set_child,
                "all_roots": all_roots,
            },
        )
