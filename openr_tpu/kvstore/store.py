"""KvStore: per-area replicated store with CRDT merge, TTL, sync, flooding.

Behavioral port of openr/kvstore/KvStore.{h,cpp}:
  - merge_key_values (KvStore.cpp:261-411): the CRDT merge — higher version
    wins; same version → higher originatorId; same originator → higher value
    bytes; identical value → retain higher ttlVersion; ttl-refresh updates
    (no value) bump ttl/ttlVersion only.
  - compare_values (KvStore.cpp:416-450): 3-way ordering used by the
    difference dump; -2 = unknown (hash mismatch but no bodies).
  - TTL countdown queue (KvStore.h:64-80, cleanup KvStore.cpp:2594-2644):
    lazily-invalidated heap entries; expiry floods expiredKeys.
  - 3-way full sync (KvStore.cpp:1381/1331/2705): requester sends its
    hashes; responder returns better/missing keys + tobeUpdatedKeys; the
    requester finalizes by pushing those keys back.
  - flooding (KvStore.cpp:2851-2970): nodeIds path vector appended with our
    id, never flood back to the sender, token-bucket rate limiting with a
    merge buffer (KvStore.cpp:2648-2702).
  - peer FSM IDLE → SYNCING → INITIALIZED (KvStore.h:46-62) with
    exponential backoff on transport failure.

Flood tracing (docs/Monitoring.md): every flooded publication carries a
wall-clock PerfEvents hop trace next to the nodeIds path vector —
KVSTORE_FLOOD_ORIGINATED at the origin, one KVSTORE_FLOOD_RECEIVED per
hop — so each store exports per-hop flood latency (`kvstore.flood.hop_ms`),
origin-to-here latency (`kvstore.flood.e2e_ms`), flood-buffer queue delay
(`kvstore.flood.buffer_delay_ms`) and a redundant-flood ratio
(`kvstore.flood.duplicates` / `kvstore.flood.received`), and emits one
FLOOD_TRACE LogSample per received flood for the cross-node convergence
report (monitor/report.py, ctrl getConvergenceReport).
"""

from __future__ import annotations

import asyncio
import enum
import heapq
import random
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.messaging import ReplicateQueue
from openr_tpu.monitor.monitor import LogSample
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils.ownership import owned_by
from openr_tpu.types import (
    KeyVals,
    PerfEvents,
    Publication,
    TTL_INFINITY,
    Value,
    generate_hash,
)
from openr_tpu.utils import AsyncThrottle, ExponentialBackoff
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin
from openr_tpu.kvstore.transport import KvStoreTransport

# flood-hop PerfEvent descriptors (ride the KEY_SET RPC, wire.py); Decision
# maps them onto convergence-span stages (decision.py:_FLOOD_*)
FLOOD_ORIGINATED_EVENT = "KVSTORE_FLOOD_ORIGINATED"
FLOOD_RECEIVED_EVENT = "KVSTORE_FLOOD_RECEIVED"
# one LogSample per received flooded publication (docs/Monitoring.md
# event catalog): hop count, per-hop + origin-to-here latency, duplicate flag
FLOOD_TRACE_EVENT = "FLOOD_TRACE"
# peer-health quarantine ladder events (docs/Monitoring.md event catalog):
# one sample when a peer trips into quarantine (with the forensics dump id)
# and one when the probe path recovers it
PEER_QUARANTINED_EVENT = "KVSTORE_PEER_QUARANTINED"
PEER_RECOVERED_EVENT = "KVSTORE_PEER_RECOVERED"
# hop-trace length bound: the origin stamp plus the most recent hops. On
# large-diameter topologies (a 256-node emulated ring) an unbounded trace
# is O(diameter) per-copy per-forward — O(diameter²) allocations per
# publication — for stamps nothing reads: per-hop latency uses the LAST
# stamp, origin-to-here the FIRST. The nodeIds path vector stays complete
# (it is load-bearing for loop prevention); only the timing trace is capped.
FLOOD_TRACE_MAX_EVENTS = 17


# ---------------------------------------------------------------------------
# pure functions
# ---------------------------------------------------------------------------


def merge_key_values(
    store: KeyVals,
    key_vals: KeyVals,
    filters: Optional["KvStoreFilters"] = None,
) -> KeyVals:
    """Merge key_vals into store; return the accepted updates to flood."""
    native_merge = getattr(store, "native_merge", None)
    if native_merge is not None:
        return native_merge(key_vals, filters)
    updates: KeyVals = {}
    for key, value in key_vals.items():
        if filters is not None and not filters.key_match(key, value):
            continue

        # versions start at 1 (KvStore.cpp:277-279); reject anything lower
        if value.version < 1:
            continue

        # TTL must be infinite or positive
        if value.ttl != TTL_INFINITY and value.ttl <= 0:
            continue

        existing = store.get(key)
        my_version = existing.version if existing is not None else 0
        if value.version < my_version:
            continue  # stale

        update_all = False
        update_ttl = False
        if value.value is not None:
            if value.version > my_version:
                update_all = True
            elif value.originator_id > existing.originator_id:
                update_all = True
            elif value.originator_id == existing.originator_id:
                if existing.value is None or value.value > existing.value:
                    # deterministic winner on divergent same-version values
                    update_all = True
                elif value.value == existing.value:
                    if value.ttl_version > existing.ttl_version:
                        update_ttl = True

        # ttl refresh (no value body)
        if (
            value.value is None
            and existing is not None
            and value.version == existing.version
            and value.originator_id == existing.originator_id
            and value.ttl_version > existing.ttl_version
        ):
            update_ttl = True

        if not update_all and not update_ttl:
            continue

        if update_all:
            new_value = value.copy()
            if new_value.hash is None:
                new_value.hash = generate_hash(
                    new_value.version, new_value.originator_id, new_value.value
                )
            store[key] = new_value
            # flood the hash-filled copy (the reference fills the hash at
            # the originator before storing/flooding) so every forwarded
            # frame is integrity-checkable end to end
            updates[key] = new_value
        elif update_ttl:
            existing.ttl = value.ttl
            existing.ttl_version = value.ttl_version
            updates[key] = value
    return updates


def compare_values(v1: Value, v2: Value) -> int:
    """1: v1 better, -1: v2 better, 0: same, -2: unknown."""
    if v1.version != v2.version:
        return 1 if v1.version > v2.version else -1
    if v1.originator_id != v2.originator_id:
        return 1 if v1.originator_id > v2.originator_id else -1
    if v1.hash is not None and v2.hash is not None and v1.hash == v2.hash:
        if v1.ttl_version != v2.ttl_version:
            return 1 if v1.ttl_version > v2.ttl_version else -1
        return 0
    if v1.value is not None and v2.value is not None:
        if v1.value == v2.value:
            if v1.ttl_version != v2.ttl_version:
                return 1 if v1.ttl_version > v2.ttl_version else -1
            return 0
        return 1 if v1.value > v2.value else -1
    return -2


class KvStoreFilters:
    """Key-prefix and originator filters (KvStore.h:82-119)."""

    def __init__(
        self,
        key_prefixes: Optional[List[str]] = None,
        originator_ids: Optional[Set[str]] = None,
    ) -> None:
        self.key_prefixes = key_prefixes or []
        self.originator_ids = originator_ids or set()

    def _prefix_match(self, key: str) -> bool:
        if not self.key_prefixes:
            return True
        return any(key.startswith(p) for p in self.key_prefixes)

    def key_match(self, key: str, value: Value) -> bool:
        """OR semantics: match by prefix or by originator."""
        if not self.key_prefixes and not self.originator_ids:
            return True
        if self.key_prefixes and self._prefix_match(key):
            return True
        if self.originator_ids and value.originator_id in self.originator_ids:
            return True
        return False

    def key_match_all(self, key: str, value: Value) -> bool:
        """AND semantics."""
        return self._prefix_match(key) and (
            not self.originator_ids
            or value.originator_id in self.originator_ids
        )


# ---------------------------------------------------------------------------
# peers
# ---------------------------------------------------------------------------


class PeerState(enum.Enum):
    IDLE = "IDLE"
    SYNCING = "SYNCING"
    INITIALIZED = "INITIALIZED"


class PeerEvent(enum.Enum):
    PEER_ADD = "PEER_ADD"
    SYNC_RESP_RCVD = "SYNC_RESP_RCVD"
    SYNC_TIMEOUT = "SYNC_TIMEOUT"
    API_ERROR = "API_ERROR"


# state transition matrix (KvStore.h:421)
_PEER_FSM: Dict[Tuple[PeerState, PeerEvent], PeerState] = {
    (PeerState.IDLE, PeerEvent.PEER_ADD): PeerState.SYNCING,
    (PeerState.SYNCING, PeerEvent.SYNC_RESP_RCVD): PeerState.INITIALIZED,
    (PeerState.SYNCING, PeerEvent.SYNC_TIMEOUT): PeerState.IDLE,
    (PeerState.SYNCING, PeerEvent.API_ERROR): PeerState.IDLE,
    (PeerState.INITIALIZED, PeerEvent.SYNC_TIMEOUT): PeerState.IDLE,
    (PeerState.INITIALIZED, PeerEvent.API_ERROR): PeerState.IDLE,
    (PeerState.INITIALIZED, PeerEvent.SYNC_RESP_RCVD): PeerState.INITIALIZED,
}


class PeerHealth(enum.Enum):
    """Per-peer scoring ladder (mirror of the solver breaker FSM):
    consecutive transport failures walk HEALTHY → SUSPECT → QUARANTINED;
    a quarantined peer receives no floods, only probe-driven full syncs
    (QUARANTINED ⇄ PROBING), and recovers with hysteresis after
    `peer_probe_successes` consecutive probe successes."""

    HEALTHY = "HEALTHY"
    SUSPECT = "SUSPECT"
    QUARANTINED = "QUARANTINED"
    PROBING = "PROBING"


@dataclass(frozen=True)
class PeerSpec:
    """Addressing info for one peer (thrift::PeerSpec equivalent)."""

    peer_addr: str  # transport address (node id for in-process)
    support_flood_optimization: bool = False


@dataclass
class _Peer:
    spec: PeerSpec
    backoff: ExponentialBackoff
    state: PeerState = PeerState.IDLE
    health: PeerHealth = PeerHealth.HEALTHY
    failures: int = 0  # consecutive transport failures
    probes: int = 0
    probe_streak: int = 0  # consecutive probe successes (hysteresis)
    floods_skipped: int = 0
    quarantined_at: float = 0.0
    probe_backoff: Optional[ExponentialBackoff] = None


@dataclass
class _DampingEntry:
    """Flood-storm damping state for one (key, originator): an exponential
    penalty (decayed with `damping_half_life_s`) accrued on every
    value-bearing accepted update; crossing `damping_suppress_limit` puts
    the key behind a hold-down until the penalty decays below
    `damping_reuse_limit` (or `damping_max_hold_s` elapses), at which point
    the CURRENT store value is flooded — latest always wins on release."""

    penalty: float = 0.0
    last_decay: float = 0.0  # monotonic ts of the last decay application
    held: bool = False
    held_since: float = 0.0


@dataclass
class _TtlEntry:
    expiry: float
    key: str
    epoch: int  # store-write epoch; stale entries fail the epoch check

    def __lt__(self, other: "_TtlEntry") -> bool:
        return self.expiry < other.expiry


class _TokenBucket:
    """Flood rate limiter (folly::BasicTokenBucket equivalent)."""

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = rate
        self.burst = burst
        self._tokens = burst
        self._last = time.monotonic()

    def consume(self, n: float = 1.0) -> bool:
        now = time.monotonic()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


# ---------------------------------------------------------------------------
# KvStoreDb — one area
# ---------------------------------------------------------------------------


@dataclass
class KvStoreParams:
    node_id: str
    ttl_decrement_ms: int = 1  # decrement applied when forwarding ttls
    flood_rate: Optional[float] = None  # msgs/sec; None = unlimited
    flood_burst: float = 32.0
    flood_buffer_delay: float = 0.1  # kFloodPendingPublication (100ms)
    sync_max_backoff: float = 8.0
    filters: Optional[KvStoreFilters] = None
    # DUAL flood-topology optimization: flood on a spanning tree instead of
    # the full peer mesh (KvstoreConfig.enable_flood_optimization)
    enable_flood_optimization: bool = False
    is_flood_root: bool = False
    # keep the key->Value table and CRDT merge in the native C++ engine
    # (native/kvstore); falls back to the Python dict if the library is
    # unavailable
    use_native_store: bool = False
    # deterministic seed for jittered backoffs / anti-entropy peer choice;
    # None derives a per-node seed from the node id (still deterministic)
    jitter_seed: Optional[int] = None
    # flood-storm damping (per-(key, originator) exponential penalty)
    damping_enabled: bool = True
    damping_penalty: float = 1000.0  # accrued per value-bearing update
    damping_suppress_limit: float = 8000.0  # hold-down trip threshold
    damping_reuse_limit: float = 2000.0  # release threshold after decay
    damping_half_life_s: float = 8.0
    damping_max_hold_s: float = 30.0  # hard cap on any hold-down
    damping_sweep_s: float = 0.5  # decay/release sweep cadence
    # adjacency withdrawals must propagate immediately; TTL expiry is
    # structurally exempt (expired_keys never pass through damping)
    damping_exempt_prefixes: Tuple[str, ...] = ("adj:",)
    # peer-health quarantine ladder
    quarantine_enabled: bool = True
    peer_suspect_failures: int = 3  # consecutive failures → SUSPECT
    peer_quarantine_failures: int = 6  # consecutive failures → QUARANTINED
    peer_probe_min_backoff: float = 0.1
    peer_probe_max_backoff: float = 2.0
    peer_probe_successes: int = 2  # hysteresis before recovery
    # adaptive anti-entropy: periodic rounds arm only when flood health is
    # off budget (duplicate ratio, sync/flood failures, wire rejects)
    anti_entropy_enabled: bool = True
    anti_entropy_interval_s: float = 60.0
    flood_duplicate_budget: float = 0.5  # duplicates/received per interval
    # directory for quarantine forensics artifacts (None = in-memory only)
    forensics_dir: Optional[str] = None


@owned_by("kvstore-loop")
class KvStoreDb(CountersMixin, HistogramsMixin):
    def __init__(
        self,
        area: str,
        params: KvStoreParams,
        transport: KvStoreTransport,
        updates_queue: ReplicateQueue,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        histograms: Optional[Dict] = None,
        log_sample_fn=None,
    ) -> None:
        self.area = area
        self.params = params
        self.transport = transport
        self.updates_queue = updates_queue
        self._loop = loop
        # flood-latency histograms; the multi-area container passes ONE
        # shared dict so per-node flood stats aggregate across areas (the
        # monitor reads the container's `histograms` attribute)
        self.histograms: Dict = histograms if histograms is not None else {}
        # sink for FLOOD_TRACE LogSamples (the daemon's monitor queue push;
        # None drops them — flood counters/histograms still record)
        self._log_sample_fn = log_sample_fn
        self.store: KeyVals = {}
        if params.use_native_store:
            from openr_tpu.kvstore.native import (
                NativeKvTable,
                native_kv_available,
            )

            if native_kv_available():
                self.store = NativeKvTable()
        self.peers: Dict[str, _Peer] = {}
        self._ttl_heap: List[_TtlEntry] = []
        # per-key write epoch: bumped on every accepted update so TTL heap
        # entries from superseded writes can never evict the current value
        self._ttl_epochs: Dict[str, int] = {}
        self._ttl_timer: Optional[asyncio.TimerHandle] = None
        self._flood_limiter = (
            _TokenBucket(params.flood_rate, params.flood_burst)
            if params.flood_rate
            else None
        )
        # pending buffered flood keys (merge buffer under rate limiting)
        self._publication_buffer: Set[str] = set()
        self._buffer_flush = AsyncThrottle(
            params.flood_buffer_delay, self._flush_buffered, loop=loop
        )
        # flood-buffer queue-delay bookkeeping: when the first key entered
        # the buffer, plus the oldest buffered publication's span stages /
        # hop trace (the merged flush re-attaches them, same oldest-event
        # rule Decision's debounce uses)
        self._buffer_first_ts: Optional[float] = None
        self._buffer_span_stages: Optional[List[Tuple[str, float]]] = None
        self._buffer_perf_events: Optional[PerfEvents] = None
        self._retry_pending: Set[str] = set()
        self._sync_tasks: Set[asyncio.Task] = set()
        self.counters: Dict[str, int] = {}
        # deterministic per-node rng: decorrelated-jitter backoffs and
        # anti-entropy peer choice replay identically under a fixed seed
        seed = (
            params.jitter_seed
            if params.jitter_seed is not None
            else zlib.crc32(f"{params.node_id}/{area}".encode())
        )
        self._rng = random.Random(seed)
        # monotonic expiry deadline per finite-ttl key: the authoritative
        # remaining-lifetime record (stored Value.ttl is the ORIGINAL ttl)
        self._ttl_expiry: Dict[str, float] = {}
        # flood-storm damping state + lazy decay/release sweep timer
        self._damping: Dict[Tuple[str, str], _DampingEntry] = {}
        self._damping_timer: Optional[asyncio.TimerHandle] = None
        # adaptive anti-entropy: lazy timer + counter snapshot from the
        # previous tick (flood-health deltas are per-interval)
        self._ae_timer: Optional[asyncio.TimerHandle] = None
        self._ae_last: Dict[str, int] = {}
        # quarantine forensics recorder (lazy, PR 13 flight-recorder flow)
        self._forensics = None
        # DUAL flood-topology optimization (KvStore.h:193 inherits DualNode;
        # composed here): SPT per flood-root, flood only to SPT peers
        self.dual: Optional["_KvDualNode"] = None
        if params.enable_flood_optimization:
            self.dual = _KvDualNode(self)

    # -- basic API ---------------------------------------------------------

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    def get_key(self, key: str) -> Optional[Value]:
        return self.store.get(key)

    def get_key_vals(self, keys: List[str]) -> Publication:
        pub = Publication(area=self.area, ts_monotonic=time.monotonic())
        for key in keys:
            v = self.store.get(key)
            if v is not None:
                pub.key_vals[key] = v
        return pub

    def dump_all(
        self,
        filters: Optional[KvStoreFilters] = None,
        match_all: bool = False,
    ) -> Publication:
        pub = Publication(area=self.area, ts_monotonic=time.monotonic())
        filters = filters or KvStoreFilters()
        match = filters.key_match_all if match_all else filters.key_match
        for key, value in self.store.items():
            if match(key, value):
                pub.key_vals[key] = value
        return pub

    def dump_hashes(
        self, filters: Optional[KvStoreFilters] = None
    ) -> Publication:
        pub = Publication(area=self.area, ts_monotonic=time.monotonic())
        filters = filters or KvStoreFilters()
        for key, value in self.store.items():
            if filters.key_match(key, value):
                pub.key_vals[key] = Value(
                    version=value.version,
                    originator_id=value.originator_id,
                    value=None,
                    ttl=value.ttl,
                    ttl_version=value.ttl_version,
                    hash=value.hash,
                )
        return pub

    def dump_difference(
        self, my_key_vals: KeyVals, req_key_vals: KeyVals
    ) -> Publication:
        """3-way sync difference (KvStore.cpp:1331-1375): keyVals = keys
        where we are better/only-us; tobe_updated_keys = keys where the
        requester is better/only-them."""
        pub = Publication(area=self.area, ts_monotonic=time.monotonic())
        pub.tobe_updated_keys = []
        for key in set(my_key_vals) | set(req_key_vals):
            mine = my_key_vals.get(key)
            theirs = req_key_vals.get(key)
            if mine is None:
                pub.tobe_updated_keys.append(key)
                continue
            if theirs is None:
                pub.key_vals[key] = mine
                continue
            rc = compare_values(mine, theirs)
            if rc in (1, -2):
                pub.key_vals[key] = mine
            if rc in (-1, -2):
                pub.tobe_updated_keys.append(key)
        return pub

    # -- local writes ------------------------------------------------------

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def set_key_vals(
        self, key_vals: KeyVals, span_stages=None
    ) -> KeyVals:
        """Local API write (thrift setKvStoreKeyVals): merge + flood.

        `span_stages` — monotonic pre-publish convergence-span marks from
        the producing module (LinkMonitor's spark→advertise chain) — ride
        the local publication so Decision's span starts at the Spark event,
        not at this store's publish stamp."""
        updates = merge_key_values(self.store, key_vals, self.params.filters)
        self._update_ttl_countdown(updates)
        if updates:
            self._bump("kvstore.updated_key_vals", len(updates))
            flood = self._damp_updates(updates)
            if flood:
                self.flood_publication(
                    Publication(
                        key_vals=flood,
                        area=self.area,
                        span_stages=span_stages,
                    )
                )
        return updates

    def handle_set_key_vals(
        self,
        key_vals: KeyVals,
        node_ids: Optional[List[str]],
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        """KEY_SET arriving from a peer (flooded publication).

        Flood-hop accounting happens here: the incoming wall-clock hop
        trace (`perf_events`) yields this hop's latency and the
        origin-to-here latency; the nodeIds path vector is the hop count;
        a merge that accepts nothing is a redundant (duplicate) flood."""
        recv_wall_ms = time.time() * 1e3
        hop_count = len(node_ids) if node_ids else 0
        self._bump("kvstore.flood.received")
        self.counters["kvstore.flood.hop_count_last"] = hop_count
        hop_ms: Optional[float] = None
        e2e_ms: Optional[float] = None
        if perf_events is not None and perf_events.events:
            hop_ms = max(0.0, recv_wall_ms - perf_events.events[-1].unix_ts)
            e2e_ms = max(0.0, recv_wall_ms - perf_events.events[0].unix_ts)
            self._observe("kvstore.flood.hop_ms", hop_ms)
            self._observe("kvstore.flood.e2e_ms", e2e_ms)
        if node_ids is not None and self.params.node_id in node_ids:
            self._bump("kvstore.looped_publications")
            self._bump("kvstore.flood.duplicates")
            self._emit_flood_trace(
                node_ids, hop_count, len(key_vals), 0, hop_ms, e2e_ms
            )
            return  # path-vector loop prevention (KvStore.cpp:2874-2884)
        updates = merge_key_values(self.store, key_vals, self.params.filters)
        self._update_ttl_countdown(updates)
        if not updates:
            self._bump("kvstore.flood.duplicates")
        self._emit_flood_trace(
            node_ids, hop_count, len(key_vals), len(updates), hop_ms, e2e_ms
        )
        flood = self._damp_updates(updates) if updates else updates
        if flood:
            traced = perf_events.copy() if perf_events is not None else None
            if traced is not None:
                traced.add_fine(self.params.node_id, FLOOD_RECEIVED_EVENT)
                if len(traced.events) > FLOOD_TRACE_MAX_EVENTS:
                    traced.events = [traced.events[0]] + traced.events[
                        -(FLOOD_TRACE_MAX_EVENTS - 1):
                    ]
            self.flood_publication(
                Publication(
                    key_vals=flood,
                    area=self.area,
                    node_ids=list(node_ids or []),
                    perf_events=traced,
                )
            )

    def _emit_flood_trace(
        self,
        node_ids: Optional[List[str]],
        hop_count: int,
        keys: int,
        updated: int,
        hop_ms: Optional[float],
        e2e_ms: Optional[float],
    ) -> None:
        if self._log_sample_fn is None:
            return
        sample = LogSample()
        sample.add_string("event", FLOOD_TRACE_EVENT)
        sample.add_string("area", self.area)
        sample.add_string("origin", node_ids[0] if node_ids else "")
        sample.add_int("hop_count", hop_count)
        sample.add_int("keys", keys)
        sample.add_int("updated", updated)
        sample.add_int("duplicate", 0 if updated else 1)
        if hop_ms is not None:
            sample.add_double("hop_ms", hop_ms)
        if e2e_ms is not None:
            sample.add_double("e2e_ms", e2e_ms)
        try:
            self._log_sample_fn(sample)
        except Exception:
            # a closed monitor queue must never break the flood path
            self._bump("kvstore.flood.trace_drops")

    def handle_dump(self, key_val_hashes: Optional[KeyVals]) -> Publication:
        """KEY_DUMP serving side; with hashes, serve the 3-way difference."""
        pub = self.dump_all()
        if key_val_hashes is not None:
            pub = self.dump_difference(pub.key_vals, key_val_hashes)
        self._update_publication_ttl(pub)
        # full-sync responses are publications too: stamp so any downstream
        # span seeded from this object never starts from a missing stamp
        pub.ts_monotonic = time.monotonic()
        return pub

    # -- flood-storm damping -----------------------------------------------

    def _damp_updates(self, updates: KeyVals) -> KeyVals:
        """Filter accepted updates through the per-(key, originator)
        damping penalty. Held keys stay merged in the store (the CRDT is
        untouched) but are withheld from flooding AND from the local
        updates queue, bounding Decision/journal/stream churn during event
        storms. TTL refreshes (no value body) never accrue penalty and
        always pass; exempt prefixes (adjacency keys) always pass."""
        if not self.params.damping_enabled:
            return updates
        now = time.monotonic()
        half_life = self.params.damping_half_life_s
        flood: KeyVals = {}
        for key, value in updates.items():
            if value.value is None or key.startswith(
                self.params.damping_exempt_prefixes
            ):
                flood[key] = value
                continue
            slot = (key, value.originator_id)
            entry = self._damping.get(slot)
            if entry is None:
                entry = _DampingEntry(last_decay=now)
                self._damping[slot] = entry
            else:
                entry.penalty *= 0.5 ** ((now - entry.last_decay) / half_life)
                entry.last_decay = now
            entry.penalty += self.params.damping_penalty
            if entry.held:
                self._bump("kvstore.damping.suppressed")
            elif entry.penalty >= self.params.damping_suppress_limit:
                entry.held = True
                entry.held_since = now
                self._bump("kvstore.damping.holds")
                self._bump("kvstore.damping.suppressed")
            else:
                flood[key] = value
        if self._damping:
            self._set_damping_gauge()
            self._arm_damping_sweep()
        return flood

    def _arm_damping_sweep(self) -> None:
        if self._damping_timer is not None:
            return
        try:
            loop = self.loop()
        except RuntimeError:
            # no event loop (synchronous unit-test context): decay state
            # is tracked per-entry, so the sweep arms on the next damped
            # update that happens inside a loop — nothing is lost
            return
        self._damping_timer = loop.call_later(
            self.params.damping_sweep_s, self._damping_sweep
        )

    def _set_damping_gauge(self) -> None:
        self.counters["kvstore.damping.active_last"] = sum(
            1 for e in self._damping.values() if e.held
        )

    def _damping_sweep(self) -> None:
        """Decay penalties; release hold-downs whose penalty fell below the
        reuse limit (or that hit the hard hold cap) by flooding the CURRENT
        store value — the latest accepted write always wins on release."""
        self._damping_timer = None
        now = time.monotonic()
        half_life = self.params.damping_half_life_s
        release_keys: Set[str] = set()
        for slot, entry in list(self._damping.items()):
            entry.penalty *= 0.5 ** ((now - entry.last_decay) / half_life)
            entry.last_decay = now
            if entry.held and (
                entry.penalty <= self.params.damping_reuse_limit
                or now - entry.held_since >= self.params.damping_max_hold_s
            ):
                entry.held = False
                entry.penalty = min(
                    entry.penalty, self.params.damping_reuse_limit
                )
                self._observe(
                    "kvstore.damping.hold_ms",
                    (now - entry.held_since) * 1e3,
                )
                self._bump("kvstore.damping.released")
                release_keys.add(slot[0])
            if not entry.held and entry.penalty < 1.0:
                del self._damping[slot]
        self._set_damping_gauge()
        if release_keys:
            pub = Publication(area=self.area)
            for key in sorted(release_keys):
                value = self.store.get(key)
                if value is not None:
                    pub.key_vals[key] = value
            if pub.key_vals:
                self.flood_publication(pub, rate_limit=False)
        if self._damping:
            self._arm_damping_sweep()

    # -- flooding ----------------------------------------------------------

    def flood_publication(
        self,
        publication: Publication,
        rate_limit: bool = True,
        _from_buffer: bool = False,
    ) -> None:
        if (
            self._flood_limiter is not None
            and rate_limit
            and not self._flood_limiter.consume(1)
        ):
            self._buffer_publication(publication)
            self._buffer_flush()
            return
        if self._publication_buffer and not _from_buffer:
            self._buffer_publication(publication)
            self._flush_buffered()
            return

        self._update_publication_ttl(publication, decrement=True)
        if not publication.key_vals and not publication.expired_keys:
            return

        sender_id: Optional[str] = None
        if publication.node_ids:
            sender_id = publication.node_ids[-1]
        if publication.node_ids is None:
            publication.node_ids = []
        publication.node_ids.append(self.params.node_id)

        # hop-trace origin stamp: a publication with no inbound sender is
        # being originated HERE — start the wall-clock flood trace every
        # downstream hop measures per-hop latency against
        if (
            publication.key_vals
            and publication.perf_events is None
            and sender_id is None
        ):
            publication.perf_events = PerfEvents()
            publication.perf_events.add_fine(
                self.params.node_id, FLOOD_ORIGINATED_EVENT
            )

        # internal subscribers (Decision et al.); the monotonic stamp seeds
        # Decision's convergence span (this store's clock — always restamp:
        # a shared in-process publication object may carry another node's)
        publication.ts_monotonic = time.monotonic()
        self.updates_queue.push(publication)
        self._bump("kvstore.num_updates")

        if not publication.key_vals:
            return  # expiry-only publications stay local

        for peer_name in self.get_flood_peers():
            peer = self.peers[peer_name]
            if sender_id is not None and sender_id == peer_name:
                continue  # never flood back to the sender
            if peer.state == PeerState.IDLE:
                continue
            if peer.health in (PeerHealth.QUARANTINED, PeerHealth.PROBING):
                # quarantined peers get no floods — only the probe-driven
                # full syncs the quarantine loop issues
                peer.floods_skipped += 1
                self._bump("kvstore.quarantine.floods_skipped")
                continue
            self._spawn(
                self._send_key_vals(
                    peer_name,
                    dict(publication.key_vals),
                    list(publication.node_ids),
                    (
                        publication.perf_events.copy()
                        if publication.perf_events is not None
                        else None
                    ),
                )
            )

    def get_flood_peers(self, record: bool = True) -> List[str]:
        """SPT peers when flood optimization has a ready tree, else all
        peers (KvStore.cpp:2819-2839). `record=False` for introspection
        reads (SPT dump) so they don't inflate the flood-ratio counter."""
        if self.dual is not None:
            root_id = self.dual.get_spt_root_id()
            spt_peers = self.dual.get_spt_peers(root_id)
            if spt_peers:
                if record:
                    self._bump("kvstore.flood_via_spt")
                return [p for p in spt_peers if p in self.peers]
        return list(self.peers)

    def _buffer_publication(self, publication: Publication) -> None:
        self._bump("kvstore.rate_limit_suppress")
        if self._buffer_first_ts is None:
            self._buffer_first_ts = time.monotonic()
        # the merged flush keeps the OLDEST buffered publication's span
        # stages and hop trace (Decision's oldest-event-of-a-batch rule)
        if self._buffer_span_stages is None:
            self._buffer_span_stages = publication.span_stages
        if self._buffer_perf_events is None:
            self._buffer_perf_events = publication.perf_events
        self._publication_buffer.update(publication.key_vals.keys())
        self._publication_buffer.update(publication.expired_keys)

    def _flush_buffered(self) -> None:
        self._buffer_flush.cancel()
        if not self._publication_buffer:
            return
        if self._buffer_first_ts is not None:
            self._observe(
                "kvstore.flood.buffer_delay_ms",
                (time.monotonic() - self._buffer_first_ts) * 1e3,
            )
        pub = Publication(
            area=self.area,
            span_stages=self._buffer_span_stages,
            perf_events=self._buffer_perf_events,
        )
        self._buffer_first_ts = None
        self._buffer_span_stages = None
        self._buffer_perf_events = None
        for key in self._publication_buffer:
            value = self.store.get(key)
            if value is not None:
                pub.key_vals[key] = value
            else:
                pub.expired_keys.append(key)
        self._publication_buffer.clear()
        # forwarded as merged publication, not rate limited again
        self.flood_publication(pub, rate_limit=False, _from_buffer=True)

    async def _send_key_vals(
        self,
        peer_name: str,
        key_vals: KeyVals,
        node_ids: List[str],
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        peer = self.peers.get(peer_name)
        if peer is None:
            return
        try:
            # named fault seam: an injected send failure exercises the
            # API_ERROR peer-state path without a real transport fault
            fault_point("kvstore.flood_send", peer_name)
            await self.transport.set_key_vals(
                peer.spec.peer_addr,
                self.area,
                key_vals,
                node_ids,
                perf_events=perf_events,
            )
            self._bump("kvstore.thrift.num_flood_pub")
            self._note_peer_success(peer_name)
        except Exception:
            self._bump("kvstore.thrift.num_flood_pub_failure")
            self._note_peer_failure(peer_name)
            self._peer_event(peer_name, PeerEvent.API_ERROR)

    # -- peers + full sync -------------------------------------------------

    def add_peers(self, peers: Dict[str, PeerSpec]) -> None:
        for name, spec in peers.items():
            existing = self.peers.get(name)
            if existing is not None and existing.spec == spec:
                continue
            self.peers[name] = _Peer(
                spec=spec,
                # decorrelated jitter (the Fib resync pattern): concurrent
                # sync failures across peers/nodes retry decorrelated
                # instead of thundering back in lockstep
                backoff=ExponentialBackoff(
                    0.064,
                    self.params.sync_max_backoff,
                    jitter=True,
                    rng=self._rng,
                ),
            )
            self._peer_event(name, PeerEvent.PEER_ADD)
            if self.dual is not None:
                self.dual.peer_up(name, 1)  # KvStore peers at unit metric
            self._spawn(self._full_sync(name))
        if (
            self.params.anti_entropy_enabled
            and self._ae_timer is None
            and self.peers
        ):
            self._ae_timer = self.loop().call_later(
                self.params.anti_entropy_interval_s, self._anti_entropy_tick
            )

    def del_peers(self, names: List[str]) -> None:
        for name in names:
            if self.peers.pop(name, None) is not None and (
                self.dual is not None
            ):
                self.dual.peer_down(name)

    def get_peers(self) -> Dict[str, PeerSpec]:
        return {name: p.spec for name, p in self.peers.items()}

    def peer_state(self, name: str) -> Optional[PeerState]:
        peer = self.peers.get(name)
        return peer.state if peer else None

    def _peer_event(self, name: str, event: PeerEvent) -> None:
        peer = self.peers.get(name)
        if peer is None:
            return
        next_state = _PEER_FSM.get((peer.state, event))
        if next_state is not None:
            peer.state = next_state
        if event == PeerEvent.API_ERROR:
            peer.backoff.report_error()
            if peer.health in (PeerHealth.QUARANTINED, PeerHealth.PROBING):
                return  # the probe loop owns recovery
            if name not in self._retry_pending:
                self._retry_pending.add(name)
                self._spawn(self._retry_sync(name))

    async def _retry_sync(self, name: str) -> None:
        try:
            peer = self.peers.get(name)
            if peer is None:
                return
            wait = peer.backoff.get_time_remaining_until_retry()
            self._observe("kvstore.full_sync_backoff_ms", wait * 1e3)
            await asyncio.sleep(wait)
            peer = self.peers.get(name)
            if (
                peer is not None
                and peer.state == PeerState.IDLE
                and peer.health
                not in (PeerHealth.QUARANTINED, PeerHealth.PROBING)
            ):
                peer.state = PeerState.SYNCING
                self._retry_pending.discard(name)
                await self._full_sync(name)
        finally:
            self._retry_pending.discard(name)

    async def _full_sync(self, peer_name: str) -> None:
        """3-way full sync with one peer (requester side)."""
        peer = self.peers.get(peer_name)
        if peer is None:
            return
        my_hashes = self.dump_hashes().key_vals
        try:
            # named fault seam: an injected dump failure exercises the
            # full-sync retry/backoff path (docs/Robustness.md catalog)
            fault_point("kvstore.full_sync", peer_name)
            pub = await self.transport.dump_key_vals(
                peer.spec.peer_addr, self.area, my_hashes
            )
        except Exception:
            self._bump("kvstore.full_sync_failure")
            self._note_peer_failure(peer_name)
            self._peer_event(peer_name, PeerEvent.API_ERROR)
            return
        peer.backoff.report_success()
        self._note_peer_success(peer_name)
        self._bump("kvstore.thrift.num_full_sync")
        # merge their better keys and flood resulting updates onward
        self.handle_set_key_vals(pub.key_vals, [peer_name])
        self._peer_event(peer_name, PeerEvent.SYNC_RESP_RCVD)
        # push back keys the peer is missing / has worse
        if pub.tobe_updated_keys:
            await self._finalize_full_sync(pub.tobe_updated_keys, peer_name)

    async def _finalize_full_sync(
        self, keys: List[str], peer_name: str
    ) -> None:
        updates: KeyVals = {}
        for key in keys:
            value = self.store.get(key)
            if value is not None:
                updates[key] = value
        pub = Publication(key_vals=updates, area=self.area)
        self._update_publication_ttl(pub)
        if not pub.key_vals:
            return
        peer = self.peers.get(peer_name)
        if peer is None or peer.state == PeerState.IDLE:
            return
        self._bump("kvstore.thrift.num_finalized_sync")
        try:
            await self.transport.set_key_vals(
                peer.spec.peer_addr,
                self.area,
                pub.key_vals,
                [self.params.node_id],
            )
        except Exception:
            self._note_peer_failure(peer_name)
            self._peer_event(peer_name, PeerEvent.API_ERROR)

    # -- peer-health quarantine --------------------------------------------

    def _note_peer_failure(self, name: str) -> None:
        """Score one transport failure toward this peer: consecutive
        failures walk the HEALTHY → SUSPECT → QUARANTINED ladder."""
        peer = self.peers.get(name)
        if peer is None or not self.params.quarantine_enabled:
            return
        if peer.health in (PeerHealth.QUARANTINED, PeerHealth.PROBING):
            return  # probe-loop failures are scored by the probe loop
        peer.failures += 1
        if peer.failures >= self.params.peer_quarantine_failures:
            self._quarantine_peer(name)
        elif (
            peer.failures >= self.params.peer_suspect_failures
            and peer.health == PeerHealth.HEALTHY
        ):
            peer.health = PeerHealth.SUSPECT
            self._bump("kvstore.quarantine.suspects")

    def _note_peer_success(self, name: str) -> None:
        peer = self.peers.get(name)
        if peer is None:
            return
        if peer.health in (PeerHealth.QUARANTINED, PeerHealth.PROBING):
            return  # only probe hysteresis recovers a quarantined peer
        peer.failures = 0
        if peer.health == PeerHealth.SUSPECT:
            peer.health = PeerHealth.HEALTHY

    def _set_quarantine_gauge(self) -> None:
        self.counters["kvstore.quarantine.active_last"] = sum(
            1
            for p in self.peers.values()
            if p.health in (PeerHealth.QUARANTINED, PeerHealth.PROBING)
        )

    def _quarantine_peer(self, name: str) -> None:
        peer = self.peers.get(name)
        if peer is None or peer.health == PeerHealth.QUARANTINED:
            return
        peer.health = PeerHealth.QUARANTINED
        peer.quarantined_at = time.monotonic()
        peer.probe_streak = 0
        peer.probe_backoff = ExponentialBackoff(
            self.params.peer_probe_min_backoff,
            self.params.peer_probe_max_backoff,
            jitter=True,
            rng=self._rng,
        )
        self._bump("kvstore.quarantine.trips")
        self._set_quarantine_gauge()
        self._dump_quarantine_forensics(name, peer)
        self._spawn(self._probe_quarantined(name))

    def _dump_quarantine_forensics(self, name: str, peer: _Peer) -> None:
        """Snapshot a quarantine-trip forensics artifact through the PR 13
        flight-recorder dump path and emit one KVSTORE_PEER_QUARANTINED
        LogSample carrying the dump id."""
        forensics_id = ""
        try:
            from openr_tpu.solver.flight_recorder import FlightRecorder

            if self._forensics is None:
                self._forensics = FlightRecorder(
                    node=self.params.node_id,
                    forensics_dir=self.params.forensics_dir,
                )
            dump = self._forensics.dump(
                "kvstore_peer_quarantined",
                counters=dict(self.counters),
                extra={
                    "peer": name,
                    "area": self.area,
                    "failures": peer.failures,
                    "peer_state": peer.state.value,
                    "peer_health": dict(self.get_peer_health()),
                },
            )
            forensics_id = dump["id"]
            self._bump("kvstore.forensics_dumps")
        except Exception:
            pass  # forensics must never break the store loop
        if self._log_sample_fn is not None:
            sample = LogSample()
            sample.add_string("event", PEER_QUARANTINED_EVENT)
            sample.add_string("area", self.area)
            sample.add_string("peer", name)
            sample.add_int("failures", peer.failures)
            sample.add_string("forensics_id", forensics_id)
            try:
                self._log_sample_fn(sample)
            except Exception:
                pass  # a closed monitor queue must never break the loop

    async def _probe_quarantined(self, name: str) -> None:
        """Recovery loop for one quarantined peer: jittered-backoff probes
        through the full-sync dump path; `peer_probe_successes` consecutive
        successes recover the peer (hysteresis against flapping links)."""
        while True:
            peer = self.peers.get(name)
            if peer is None or peer.health not in (
                PeerHealth.QUARANTINED,
                PeerHealth.PROBING,
            ):
                return
            peer.probe_backoff.report_error()
            await asyncio.sleep(
                peer.probe_backoff.get_time_remaining_until_retry()
            )
            peer = self.peers.get(name)
            if peer is None or peer.health not in (
                PeerHealth.QUARANTINED,
                PeerHealth.PROBING,
            ):
                return
            peer.health = PeerHealth.PROBING
            peer.probes += 1
            self._bump("kvstore.quarantine.probes")
            my_hashes = self.dump_hashes().key_vals
            try:
                # named fault seam: an injected probe failure keeps the
                # peer quarantined through another backoff round
                fault_point("kvstore.quarantine_probe", name)
                pub = await self.transport.dump_key_vals(
                    peer.spec.peer_addr, self.area, my_hashes
                )
            except Exception:
                self._bump("kvstore.quarantine.probe_failures")
                peer.probe_streak = 0
                peer.health = PeerHealth.QUARANTINED
                continue
            peer.probe_streak += 1
            if peer.probe_streak >= self.params.peer_probe_successes:
                self._recover_peer(name, pub)
                return
            peer.health = PeerHealth.QUARANTINED

    def _recover_peer(self, name: str, pub: Publication) -> None:
        """Probe hysteresis satisfied: merge the probe's full-sync dump,
        restore the peer FSM, and resume flooding toward the peer."""
        peer = self.peers.get(name)
        if peer is None:
            return
        peer.health = PeerHealth.HEALTHY
        peer.failures = 0
        peer.probe_streak = 0
        peer.backoff.report_success()
        if peer.state == PeerState.IDLE:
            peer.state = PeerState.SYNCING
        held_ms = (time.monotonic() - peer.quarantined_at) * 1e3
        self._observe("kvstore.quarantine.duration_ms", held_ms)
        self._bump("kvstore.quarantine.recoveries")
        self._set_quarantine_gauge()
        self._bump("kvstore.thrift.num_full_sync")
        self.handle_set_key_vals(pub.key_vals, [name])
        self._peer_event(name, PeerEvent.SYNC_RESP_RCVD)
        if pub.tobe_updated_keys:
            self._spawn(
                self._finalize_full_sync(pub.tobe_updated_keys, name)
            )
        if self._log_sample_fn is not None:
            sample = LogSample()
            sample.add_string("event", PEER_RECOVERED_EVENT)
            sample.add_string("area", self.area)
            sample.add_string("peer", name)
            sample.add_int("probes", peer.probes)
            sample.add_double("quarantined_ms", held_ms)
            try:
                self._log_sample_fn(sample)
            except Exception:
                pass  # a closed monitor queue must never break the loop

    def get_peer_health(self) -> Dict[str, Dict]:
        """Per-peer quarantine-ladder snapshot (ctrl getKvStorePeerHealth /
        `breeze kvstore peer-health`)."""
        now = time.monotonic()
        out: Dict[str, Dict] = {}
        for name, peer in self.peers.items():
            quarantined = peer.health in (
                PeerHealth.QUARANTINED,
                PeerHealth.PROBING,
            )
            out[name] = {
                "state": peer.state.value,
                "health": peer.health.value,
                "failures": peer.failures,
                "probes": peer.probes,
                "probe_streak": peer.probe_streak,
                "floods_skipped": peer.floods_skipped,
                "quarantined_ms": (
                    round((now - peer.quarantined_at) * 1e3, 1)
                    if quarantined
                    else 0.0
                ),
            }
        return out

    # -- adaptive anti-entropy ---------------------------------------------

    def _flood_health_degraded(self) -> bool:
        """Per-interval flood-health check: any sync/flood failure or wire
        reject, or a duplicate/received ratio off budget, counts as
        degraded and arms an anti-entropy round."""
        watched = (
            "kvstore.flood.received",
            "kvstore.flood.duplicates",
            "kvstore.full_sync_failure",
            "kvstore.thrift.num_flood_pub_failure",
            "kvstore.wire.rejected_total",
        )
        deltas: Dict[str, int] = {}
        for counter in watched:
            current = self.counters.get(counter, 0)
            deltas[counter] = current - self._ae_last.get(counter, 0)
            self._ae_last[counter] = current
        if (
            deltas["kvstore.full_sync_failure"] > 0
            or deltas["kvstore.thrift.num_flood_pub_failure"] > 0
            or deltas["kvstore.wire.rejected_total"] > 0
        ):
            return True
        received = deltas["kvstore.flood.received"]
        return (
            received >= 4
            and deltas["kvstore.flood.duplicates"] / received
            > self.params.flood_duplicate_budget
        )

    def _anti_entropy_tick(self) -> None:
        self._ae_timer = None
        if not self.peers:
            return  # re-armed by the next add_peers
        degraded = self._flood_health_degraded()
        self.counters["kvstore.anti_entropy.armed_last"] = int(degraded)
        if degraded:
            candidates = [
                name
                for name, peer in self.peers.items()
                if peer.health
                not in (PeerHealth.QUARANTINED, PeerHealth.PROBING)
            ]
            if candidates:
                peer_name = candidates[self._rng.randrange(len(candidates))]
                self._spawn(self._anti_entropy_round(peer_name))
        self._ae_timer = self.loop().call_later(
            self.params.anti_entropy_interval_s, self._anti_entropy_tick
        )

    async def _anti_entropy_round(self, peer_name: str) -> None:
        """One 3-way repair round against a healthy peer: the hash dump
        ships only divergent keys in either direction."""
        peer = self.peers.get(peer_name)
        if peer is None:
            return
        t0 = time.monotonic()
        my_hashes = self.dump_hashes().key_vals
        try:
            # named fault seam: a failed repair round scores the peer and
            # re-arms on the next degraded interval
            fault_point("kvstore.anti_entropy", peer_name)
            pub = await self.transport.dump_key_vals(
                peer.spec.peer_addr, self.area, my_hashes
            )
        except Exception:
            self._bump("kvstore.anti_entropy.round_failures")
            self._note_peer_failure(peer_name)
            self._peer_event(peer_name, PeerEvent.API_ERROR)
            return
        self._bump("kvstore.anti_entropy.rounds")
        self._note_peer_success(peer_name)
        if pub.key_vals:
            self._bump("kvstore.anti_entropy.keys_repaired", len(pub.key_vals))
            self.handle_set_key_vals(pub.key_vals, [peer_name])
        if pub.tobe_updated_keys:
            await self._finalize_full_sync(pub.tobe_updated_keys, peer_name)
        self._observe(
            "kvstore.anti_entropy.round_ms", (time.monotonic() - t0) * 1e3
        )

    # -- TTL ---------------------------------------------------------------

    def _update_ttl_countdown(self, key_vals: KeyVals) -> None:
        """Register countdown entries for accepted updates. Every accepted
        update bumps the key's epoch so entries from superseded writes (even
        ones with identical version/originator/ttlVersion, e.g. the
        value-bytes tiebreak) can never evict the refreshed value."""
        now = time.monotonic()
        for key, value in key_vals.items():
            epoch = self._ttl_epochs.get(key, 0) + 1
            self._ttl_epochs[key] = epoch
            if value.ttl == TTL_INFINITY:
                self._ttl_expiry.pop(key, None)
                continue
            self._ttl_expiry[key] = now + value.ttl / 1000.0
            entry = _TtlEntry(
                expiry=now + value.ttl / 1000.0, key=key, epoch=epoch
            )
            if (
                not self._ttl_heap or entry.expiry <= self._ttl_heap[0].expiry
            ):
                self._schedule_ttl_timer(value.ttl / 1000.0)
            heapq.heappush(self._ttl_heap, entry)

    def _schedule_ttl_timer(self, delay: float) -> None:
        if self._ttl_timer is not None:
            self._ttl_timer.cancel()
        self._ttl_timer = self.loop().call_later(
            max(0.0, delay), self.cleanup_ttl_countdown_queue
        )

    def cleanup_ttl_countdown_queue(self) -> None:
        """Evict expired keys; lazily drop invalidated heap entries."""
        self._ttl_timer = None
        expired: List[str] = []
        now = time.monotonic()
        while self._ttl_heap and self._ttl_heap[0].expiry <= now:
            top = heapq.heappop(self._ttl_heap)
            if (
                top.key in self.store
                and self._ttl_epochs.get(top.key) == top.epoch
            ):
                expired.append(top.key)
                del self.store[top.key]
                del self._ttl_epochs[top.key]
                self._ttl_expiry.pop(top.key, None)
                self._bump("kvstore.expired_key_vals")
        if self._ttl_heap:
            self._schedule_ttl_timer(self._ttl_heap[0].expiry - now)
        if expired:
            self.flood_publication(
                Publication(expired_keys=expired, area=self.area)
            )

    def _update_publication_ttl(
        self, publication: Publication, decrement: bool = False
    ) -> None:
        """Serve the REMAINING ttl (countdown deadline minus now), drop
        about-to-expire keys, decrement forwarded TTLs
        (KvStore.cpp:2038 updatePublicationTtl).

        Stored Values keep their ORIGINAL ttl; serving that here would
        re-arm a dead originator's keys to full lifetime on every full
        sync / dump — with refreshes lost on a hostile network, such keys
        would never age out anywhere (the immortal-key bug). Publications
        always carry a copy so the stored Value is never mutated."""
        dec = self.params.ttl_decrement_ms
        now = time.monotonic()
        for key in list(publication.key_vals.keys()):
            value = publication.key_vals[key]
            if value.ttl == TTL_INFINITY:
                continue
            expiry = self._ttl_expiry.get(key)
            remaining = (
                int((expiry - now) * 1000.0)
                if expiry is not None
                else value.ttl
            )
            if decrement:
                remaining -= dec
            if remaining <= 0:
                del publication.key_vals[key]
                continue
            if remaining != value.ttl:
                new_value = value.copy()
                new_value.ttl = remaining
                publication.key_vals[key] = new_value

    # -- misc --------------------------------------------------------------

    def _spawn(self, coro) -> None:
        task = self.loop().create_task(coro)
        self._sync_tasks.add(task)
        task.add_done_callback(self._sync_tasks.discard)


    def stop(self) -> None:
        if self._ttl_timer is not None:
            self._ttl_timer.cancel()
            self._ttl_timer = None
        if self._damping_timer is not None:
            self._damping_timer.cancel()
            self._damping_timer = None
        if self._ae_timer is not None:
            self._ae_timer.cancel()
            self._ae_timer = None
        self._buffer_flush.cancel()
        for task in list(self._sync_tasks):
            task.cancel()

    # -- DUAL flood-topology integration -----------------------------------

    def handle_dual_messages(self, msgs) -> None:
        """Peer-delivered DUAL messages (KvStore.cpp:892)."""
        if self.dual is not None:
            self.dual.process_dual_messages(msgs)

    def handle_flood_topo_set(
        self, root_id: str, src_id: str, set_child: bool, all_roots: bool
    ) -> None:
        """processFloodTopoSet (KvStore.cpp:2238-2267)."""
        if self.dual is None:
            return
        if all_roots and not set_child:
            for dual in self.dual.duals.values():
                dual.remove_child(src_id)
            return
        if not self.dual.has_dual(root_id):
            return
        dual = self.dual.get_dual(root_id)
        if set_child:
            dual.add_child(src_id)
        else:
            dual.remove_child(src_id)

    def get_spt_infos(self) -> Dict:
        """processFloodTopoGet (KvStore.cpp:2202-2234): SPT state dump."""
        out: Dict = {"spt_infos": {}, "flood_root_id": None, "flood_peers": []}
        if self.dual is None:
            out["flood_peers"] = list(self.peers)
            return out
        for root_id, dual in self.dual.duals.items():
            out["spt_infos"][root_id] = {
                "passive": dual.sm.state.name == "PASSIVE",
                "cost": dual.distance,
                "parent": dual.nexthop,
                "children": sorted(dual.children()),
            }
        out["flood_root_id"] = self.dual.get_spt_root_id()
        out["flood_peers"] = self.get_flood_peers(record=False)
        return out


class _KvDualNode:
    """DualNode subclass-equivalent bound to one KvStoreDb (the reference
    makes KvStoreDb inherit DualNode, KvStore.h:193; composition here).

    Nexthop changes drive the flood topology: unset-child on the old
    parent, set-child + full-sync on the new one (KvStore.cpp:2315-2360).
    """

    def __init__(self, db: KvStoreDb) -> None:
        from openr_tpu.dual import DualNode

        outer = self

        class _Node(DualNode):
            def send_dual_messages(self, neighbor, msgs) -> bool:
                return outer._send(neighbor, msgs)

            def process_nexthop_change(self, root_id, old_nh, new_nh):
                outer._nexthop_change(root_id, old_nh, new_nh)

        self.db = db
        self._node = _Node(
            db.params.node_id, is_root=db.params.is_flood_root
        )

    # -- DualNode facade -------------------------------------------------

    def __getattr__(self, name):
        return getattr(self._node, name)

    @property
    def duals(self):
        return self._node.duals

    # -- wiring ----------------------------------------------------------

    async def _dual_rpc(self, peer_name: str, counter: str, coro) -> None:
        """Await a DUAL/flood-topo transport call, surfacing failures as
        counters + an API_ERROR peer event (the reference's thenError path,
        KvStore.cpp:1161-1169) instead of dying unobserved in the task."""
        try:
            await coro
            self.db._bump(f"kvstore.thrift.num_{counter}")
        except Exception:
            self.db._bump(f"kvstore.thrift.num_{counter}_failure")
            self.db._peer_event(peer_name, PeerEvent.API_ERROR)

    def _send(self, neighbor: str, msgs) -> bool:
        peer = self.db.peers.get(neighbor)
        if peer is None:
            return False
        self.db._spawn(
            self._dual_rpc(
                neighbor,
                "dual_msg",
                self.db.transport.dual_messages(
                    peer.spec.peer_addr, self.db.area, msgs
                ),
            )
        )
        return True

    def _topo_set(self, peer_name: str, root_id: str, set_child: bool) -> None:
        self.db._spawn(
            self._dual_rpc(
                peer_name,
                "flood_topo_set",
                self.db.transport.flood_topo_set(
                    self.db.peers[peer_name].spec.peer_addr,
                    self.db.area,
                    root_id,
                    self.db.params.node_id,
                    set_child,
                ),
            )
        )

    def _nexthop_change(self, root_id, old_nh, new_nh) -> None:
        if new_nh is not None and new_nh in self.db.peers:
            self._topo_set(new_nh, root_id, True)
            # full sync with the new parent so the SPT edge carries a
            # consistent store (KvStore.cpp:2342-2349)
            self.db._spawn(self.db._full_sync(new_nh))
        if old_nh is not None and old_nh in self.db.peers:
            self._topo_set(old_nh, root_id, False)


# ---------------------------------------------------------------------------
# KvStore — multi-area container
# ---------------------------------------------------------------------------


class KvStore:
    """Container of per-area KvStoreDbs sharing one transport + node id."""

    def __init__(
        self,
        node_id: str,
        areas: List[str],
        transport,
        params: Optional[KvStoreParams] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        log_sample_fn=None,
    ) -> None:
        import dataclasses

        from openr_tpu.kvstore.transport import (
            BoundTransport,
            InProcessTransport,
        )

        self.node_id = node_id
        params = params or KvStoreParams(node_id=node_id)
        # never mutate the caller's params object (it may be shared)
        self.params = dataclasses.replace(params, node_id=node_id)
        if isinstance(transport, InProcessTransport):
            transport.register(node_id, self)
            transport = BoundTransport(transport, node_id)
        self.updates_queue: ReplicateQueue = ReplicateQueue()
        # one histograms dict shared by every area db: per-node flood
        # latency stats aggregate across areas, and the monitor (which
        # registers this container, not the dbs) reads them live
        self.histograms: Dict = {}
        self.dbs: Dict[str, KvStoreDb] = {
            area: KvStoreDb(
                area,
                self.params,
                transport,
                self.updates_queue,
                loop,
                histograms=self.histograms,
                log_sample_fn=log_sample_fn,
            )
            for area in areas
        }

    def db(self, area: str = "0") -> KvStoreDb:
        return self.dbs[area]

    def note_wire_reject(self, kind: str) -> None:
        """Typed wire-decode rejection (oversized / truncated / malformed /
        hash_mismatch) observed by a transport serving this store. Counters
        live on the per-area dbs; route through the first db so the
        kvstore.wire.* namespace reaches getCounters."""
        db = next(iter(self.dbs.values()), None)
        if db is None:
            return
        db._bump("kvstore.wire.rejected_total")
        db._bump(f"kvstore.wire.rejected.{kind}")

    def get_peer_health(self, area: str = "0") -> Dict[str, Dict]:
        return self.dbs[area].get_peer_health()

    @property
    def counters(self) -> Dict[str, int]:
        """Merged per-area counters for the monitor registry (counters live
        on the KvStoreDbs; without this the kvstore.* namespace would be
        invisible to getCounters)."""
        merged: Dict[str, int] = {}
        for db in self.dbs.values():
            for name, value in db.counters.items():
                merged[name] = merged.get(name, 0) + value
        return merged

    # -- local API (OpenrCtrl surface) ------------------------------------

    def set_key(
        self,
        key: str,
        value: Value,
        area: str = "0",
        span_stages=None,
    ) -> None:
        self.dbs[area].set_key_vals({key: value}, span_stages=span_stages)

    def get_key(self, key: str, area: str = "0") -> Optional[Value]:
        return self.dbs[area].get_key(key)

    def dump_all(self, area: str = "0", **kw) -> Publication:
        return self.dbs[area].dump_all(**kw)

    def add_peers(self, peers: Dict[str, PeerSpec], area: str = "0") -> None:
        self.dbs[area].add_peers(peers)

    def del_peers(self, names: List[str], area: str = "0") -> None:
        self.dbs[area].del_peers(names)

    # -- transport server side --------------------------------------------

    def handle_set_key_vals(
        self,
        area: str,
        key_vals: KeyVals,
        node_ids: Optional[List[str]],
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        db = self.dbs.get(area)
        if db is not None:
            db.handle_set_key_vals(key_vals, node_ids, perf_events)

    def handle_dual_messages(self, area: str, msgs) -> None:
        db = self.dbs.get(area)
        if db is not None:
            db.handle_dual_messages(msgs)

    def handle_flood_topo_set(
        self,
        area: str,
        root_id: str,
        src_id: str,
        set_child: bool,
        all_roots: bool,
    ) -> None:
        db = self.dbs.get(area)
        if db is not None:
            db.handle_flood_topo_set(root_id, src_id, set_child, all_roots)

    def handle_dump(
        self, area: str, key_val_hashes: Optional[KeyVals]
    ) -> Publication:
        db = self.dbs.get(area)
        if db is None:
            return Publication(area=area)
        return db.handle_dump(key_val_hashes)

    def stop(self) -> None:
        for db in self.dbs.values():
            db.stop()
        self.updates_queue.close()
