"""ctypes bindings for the native KvStore engine (native/kvstore).

The C++ library owns the key->Value table and runs the CRDT merge
(mergeKeyValues semantics, openr/kvstore/KvStore.cpp:261-411) natively;
Python keeps the protocol machinery (flooding, sync FSM, TTL timers) and
sees the table through `NativeKvTable`, a MutableMapping adapter speaking
the compact record format documented in native/kvstore/onl_kvstore.h.

Auto-builds openr_tpu/_native/libopenr_kv.so via `make` on first use, like
the netlink binding. `native_kv_available()` gates callers; everything
falls back to the pure-Python dict store when the toolchain is missing.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Dict, Iterator, MutableMapping, Optional, Tuple

from openr_tpu.types import KeyVals, Value, generate_hash

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libopenr_kv.so")
_MAKE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native"
)

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> None:
    subprocess.run(
        ["make", "-C", _MAKE_DIR],
        check=True,
        capture_output=True,
        timeout=120,
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    try:
        if not os.path.exists(_SO_PATH):
            _build()
        lib = ctypes.CDLL(_SO_PATH)
    except Exception:
        return None
    lib.okv_create.restype = ctypes.c_void_p
    lib.okv_destroy.argtypes = [ctypes.c_void_p]
    lib.okv_merge.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.okv_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.okv_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    lib.okv_erase.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.okv_size.argtypes = [ctypes.c_void_p]
    lib.okv_size.restype = ctypes.c_size_t
    lib.okv_dump.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.okv_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    _lib = lib
    return _lib


def native_kv_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# record (de)serialization — mirrors native/kvstore/onl_kvstore.h
# ---------------------------------------------------------------------------


def _pack_record(key: str, v: Value) -> bytes:
    kb = key.encode()
    ob = v.originator_id.encode()
    parts = [struct.pack("<I", len(kb)), kb, struct.pack("<q", v.version)]
    parts += [struct.pack("<I", len(ob)), ob]
    if v.value is not None:
        parts += [b"\x01", struct.pack("<I", len(v.value)), v.value]
    else:
        parts += [b"\x00"]
    parts += [struct.pack("<q", v.ttl), struct.pack("<q", v.ttl_version)]
    if v.hash is not None:
        parts += [b"\x01", struct.pack("<q", v.hash)]
    else:
        parts += [b"\x00"]
    return b"".join(parts)


def _pack_records(key_vals: KeyVals) -> bytes:
    body = b"".join(_pack_record(k, v) for k, v in key_vals.items())
    return struct.pack("<I", len(key_vals)) + body


# hard ceilings on decoded record fields: a truncated or bit-flipped
# buffer must fail typed, not blind-slice garbage into the table
_MAX_KEY_BYTES = 8192
_MAX_VALUE_BYTES = 16 * 1024 * 1024
_MAX_RECORD_COUNT = 4 * 1024 * 1024


class NativeDecodeError(ValueError):
    """Typed rejection of a corrupt native record buffer.

    kind ∈ {"oversized", "truncated", "malformed"} — same counter mapping
    as wire.WireDecodeError (kvstore.wire.rejected.{kind})."""

    def __init__(self, kind: str, detail: str = "") -> None:
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind


def _take(buf: bytes, off: int, n: int) -> int:
    """Bounds-check a read of n bytes at off; return the new offset."""
    if n < 0 or off + n > len(buf):
        raise NativeDecodeError(
            "truncated", f"need {n}B at offset {off}, have {len(buf)}B total"
        )
    return off + n


def _unpack_records(buf: bytes) -> KeyVals:
    end = _take(buf, 0, 4)
    (count,) = struct.unpack_from("<I", buf, 0)
    if count > _MAX_RECORD_COUNT:
        raise NativeDecodeError("oversized", f"{count} records")
    off = end
    out: KeyVals = {}
    for _ in range(count):
        end = _take(buf, off, 4)
        (klen,) = struct.unpack_from("<I", buf, off)
        if klen > _MAX_KEY_BYTES:
            raise NativeDecodeError("oversized", f"key {klen}B")
        off = _take(buf, end, klen)
        try:
            key = buf[end:off].decode()
        except UnicodeDecodeError as exc:
            raise NativeDecodeError("malformed", "key not utf-8") from exc
        end = _take(buf, off, 8)
        (version,) = struct.unpack_from("<q", buf, off)
        off = _take(buf, end, 4)
        (olen,) = struct.unpack_from("<I", buf, end)
        if olen > _MAX_KEY_BYTES:
            raise NativeDecodeError("oversized", f"originator {olen}B")
        end = _take(buf, off, olen)
        try:
            orig = buf[off:end].decode()
        except UnicodeDecodeError as exc:
            raise NativeDecodeError(
                "malformed", "originator not utf-8"
            ) from exc
        off = _take(buf, end, 1)
        has_value = buf[end]
        if has_value not in (0, 1):
            raise NativeDecodeError("malformed", "bad value-present flag")
        value = None
        if has_value:
            end = _take(buf, off, 4)
            (vlen,) = struct.unpack_from("<I", buf, off)
            if vlen > _MAX_VALUE_BYTES:
                raise NativeDecodeError("oversized", f"value {vlen}B")
            off = _take(buf, end, vlen)
            value = bytes(buf[end:off])
        end = _take(buf, off, 16)
        ttl, ttl_version = struct.unpack_from("<qq", buf, off)
        off = _take(buf, end, 1)
        has_hash = buf[end]
        if has_hash not in (0, 1):
            raise NativeDecodeError("malformed", "bad hash-present flag")
        hash_ = None
        if has_hash:
            end = _take(buf, off, 8)
            (hash_,) = struct.unpack_from("<q", buf, off)
            off = end
        out[key] = Value(version, orig, value, ttl, ttl_version, hash_)
    return out


def _call_out(fn, *args) -> bytes:
    """Invoke a C function with trailing (uint8_t**, size_t*) outputs."""
    lib = _load()
    assert lib is not None
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    rc = fn(*args, ctypes.byref(out), ctypes.byref(out_len))
    if rc < 0:
        raise RuntimeError("native kvstore: malformed buffer")
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.okv_free(out)


# ---------------------------------------------------------------------------
# the table adapter
# ---------------------------------------------------------------------------


class NativeKvTable(MutableMapping):
    """Mapping view over a native store handle.

    KvStoreDb treats its store as Dict[str, Value]; this adapter satisfies
    that contract while keeping the records (and the merge hot path) in
    C++. `native_merge` is the fast path `merge_key_values` dispatches to.
    """

    def __init__(self) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native kvstore library unavailable")
        self._lib = lib
        self._h = lib.okv_create()

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.okv_destroy(h)
            self._h = None

    # -- mapping protocol --------------------------------------------------

    def __getitem__(self, key: str) -> Value:
        kb = key.encode()
        buf = _call_out(self._lib.okv_get, self._h, kb, len(kb))
        records = _unpack_records(buf)
        if not records:
            raise KeyError(key)
        return records[key]

    def __setitem__(self, key: str, value: Value) -> None:
        rec = _pack_record(key, value)
        if self._lib.okv_set(self._h, rec, len(rec)) != 0:
            raise RuntimeError("native kvstore: set failed")

    def __delitem__(self, key: str) -> None:
        kb = key.encode()
        if not self._lib.okv_erase(self._h, kb, len(kb)):
            raise KeyError(key)

    def __contains__(self, key: object) -> bool:
        try:
            self[key]  # type: ignore[index]
            return True
        except KeyError:
            return False

    def __len__(self) -> int:
        return self._lib.okv_size(self._h)

    def __iter__(self) -> Iterator[str]:
        return iter(self._snapshot())

    def items(self):
        return self._snapshot().items()

    def values(self):
        return self._snapshot().values()

    def _snapshot(self) -> Dict[str, Value]:
        return _unpack_records(_call_out(self._lib.okv_dump, self._h))

    # -- merge fast path ---------------------------------------------------

    def native_merge(self, key_vals: KeyVals, filters=None) -> KeyVals:
        """CRDT merge in C++; same contract as merge_key_values."""
        to_merge: KeyVals = {}
        for key, value in key_vals.items():
            if filters is not None and not filters.key_match(key, value):
                continue
            if value.value is not None and value.hash is None:
                # reference computes the hash at the originator
                # (mergeKeyValues fills it before storing); pre-fill so the
                # engine only compares
                value = value.copy()
                value.hash = generate_hash(
                    value.version, value.originator_id, value.value
                )
            to_merge[key] = value
        if not to_merge:
            return {}
        buf = _pack_records(to_merge)
        out = _call_out(self._lib.okv_merge, self._h, buf, len(buf))
        (count,) = struct.unpack_from("<I", out, 0)
        off = 4
        updates: KeyVals = {}
        for _ in range(count):
            (klen,) = struct.unpack_from("<I", out, off)
            off += 4
            key = out[off:off + klen].decode()
            off += klen
            updates[key] = to_merge[key]
        return updates
