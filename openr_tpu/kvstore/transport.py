"""KvStore peer transport seam.

The reference reaches peers over ZMQ ROUTER sockets or thrift clients
(openr/kvstore/KvStore.h:130,453). Here the transport is an explicit
interface; InProcessTransport wires stores directly (the KvStoreWrapper
multi-store trick, openr/kvstore/KvStoreWrapper.h:30) with an optional
per-link delay and a drop set for partition tests.
"""

from __future__ import annotations

import asyncio
from typing import TYPE_CHECKING, Dict, Optional, Set, Tuple

from openr_tpu.types import KeyVals, PerfEvents, Publication

if TYPE_CHECKING:
    from openr_tpu.kvstore.store import KvStore


class KvStoreTransportError(RuntimeError):
    pass


class KvStoreTransport:
    """Async RPC surface between stores (the thrift client equivalent)."""

    async def set_key_vals(
        self,
        peer_addr: str,
        area: str,
        key_vals: KeyVals,
        node_ids: Optional[list] = None,
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        """KEY_SET: push key/values to a peer (flooding + finalize-sync).
        `perf_events` is the wall-clock flood-hop trace riding next to the
        nodeIds path vector (docs/Monitoring.md flood tracing)."""
        raise NotImplementedError

    async def dump_key_vals(
        self,
        peer_addr: str,
        area: str,
        key_val_hashes: Optional[KeyVals] = None,
    ) -> Publication:
        """KEY_DUMP: fetch the peer's store; with hashes, the peer returns
        only differing keys plus tobe_updated_keys (3-way sync)."""
        raise NotImplementedError

    async def dual_messages(self, peer_addr: str, area: str, msgs) -> None:
        """DUAL_CMD: deliver DUAL messages (KvStore.cpp:892)."""
        raise NotImplementedError

    async def flood_topo_set(
        self,
        peer_addr: str,
        area: str,
        root_id: str,
        src_id: str,
        set_child: bool,
        all_roots: bool = False,
    ) -> None:
        """FLOOD_TOPO_SET: (un)register src as an SPT child
        (KvStore.cpp:2270-2282)."""
        raise NotImplementedError


class InProcessTransport(KvStoreTransport):
    """Directly wired stores with optional latency/partitions."""

    def __init__(self, delay: float = 0.0) -> None:
        self._stores: Dict[str, "KvStore"] = {}
        self._delay = delay
        # (src, dst) pairs currently partitioned
        self._dropped: Set[Tuple[str, str]] = set()

    def register(self, node_id: str, store: "KvStore") -> None:
        self._stores[node_id] = store

    def partition(self, a: str, b: str) -> None:
        self._dropped.add((a, b))
        self._dropped.add((b, a))

    def heal(self, a: str, b: str) -> None:
        self._dropped.discard((a, b))
        self._dropped.discard((b, a))

    def _target(self, caller: str, peer_addr: str) -> "KvStore":
        if (caller, peer_addr) in self._dropped:
            raise KvStoreTransportError(
                f"partitioned: {caller} -> {peer_addr}"
            )
        store = self._stores.get(peer_addr)
        if store is None:
            raise KvStoreTransportError(f"unknown peer {peer_addr}")
        return store

    # NOTE: callers pass their own node id via the bound transport handle
    # (see KvStore._bound_transport); peer_addr is the target node id.

    async def call_set(
        self,
        caller: str,
        peer_addr: str,
        area: str,
        key_vals: KeyVals,
        node_ids: Optional[list],
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        if self._delay:
            await asyncio.sleep(self._delay)
        target = self._target(caller, peer_addr)
        target.handle_set_key_vals(area, key_vals, node_ids, perf_events)

    async def call_dump(
        self,
        caller: str,
        peer_addr: str,
        area: str,
        key_val_hashes: Optional[KeyVals],
    ) -> Publication:
        if self._delay:
            await asyncio.sleep(self._delay)
        target = self._target(caller, peer_addr)
        return target.handle_dump(area, key_val_hashes)

    async def call_dual(
        self, caller: str, peer_addr: str, area: str, msgs
    ) -> None:
        if self._delay:
            await asyncio.sleep(self._delay)
        target = self._target(caller, peer_addr)
        target.handle_dual_messages(area, msgs)

    async def call_flood_topo_set(
        self,
        caller: str,
        peer_addr: str,
        area: str,
        root_id: str,
        src_id: str,
        set_child: bool,
        all_roots: bool,
    ) -> None:
        if self._delay:
            await asyncio.sleep(self._delay)
        target = self._target(caller, peer_addr)
        target.handle_flood_topo_set(
            area, root_id, src_id, set_child, all_roots
        )


class BoundTransport(KvStoreTransport):
    """A transport handle bound to one caller's node id."""

    def __init__(self, inner: InProcessTransport, node_id: str) -> None:
        self._inner = inner
        self._node_id = node_id

    async def set_key_vals(
        self,
        peer_addr: str,
        area: str,
        key_vals: KeyVals,
        node_ids: Optional[list] = None,
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        await self._inner.call_set(
            self._node_id, peer_addr, area, key_vals, node_ids, perf_events
        )

    async def dump_key_vals(
        self,
        peer_addr: str,
        area: str,
        key_val_hashes: Optional[KeyVals] = None,
    ) -> Publication:
        return await self._inner.call_dump(
            self._node_id, peer_addr, area, key_val_hashes
        )

    async def dual_messages(self, peer_addr: str, area: str, msgs) -> None:
        await self._inner.call_dual(self._node_id, peer_addr, area, msgs)

    async def flood_topo_set(
        self,
        peer_addr: str,
        area: str,
        root_id: str,
        src_id: str,
        set_child: bool,
        all_roots: bool = False,
    ) -> None:
        await self._inner.call_flood_topo_set(
            self._node_id,
            peer_addr,
            area,
            root_id,
            src_id,
            set_child,
            all_roots,
        )
