"""JSON wire codecs for the KvStore peer protocol.

The reference ships thrift-serialized structs between stores
(openr/if/KvStore.thrift: Value:20, Publication:228, openr/if/Dual.thrift
DualMessages); the TCP peer transport here (openr_tpu.kvstore.tcp) carries
the same fields as newline-delimited JSON, with value bytes base64-encoded.
Full fidelity matters: node_ids (flood loop prevention) and
tobe_updated_keys (3-way sync) must round-trip exactly.
"""

from __future__ import annotations

import base64
from typing import Any, Dict, List, Optional

from openr_tpu.dual.dual import DualMessage, DualMessages, DualMessageType
from openr_tpu.types import (
    TTL_INFINITY,
    KeyVals,
    PerfEvent,
    PerfEvents,
    Publication,
    Value,
    generate_hash,
)


# hard ceilings on decoded frames: a hostile or corrupted peer must not
# be able to balloon memory (or smuggle garbage into the CRDT) through a
# single decoded field
MAX_VALUE_BYTES = 16 * 1024 * 1024
MAX_KEY_CHARS = 8192


class WireDecodeError(ValueError):
    """Typed rejection of a hostile/corrupt wire frame.

    kind ∈ {"oversized", "truncated", "malformed", "hash_mismatch"} — the
    transport layer maps it onto `kvstore.wire.rejected.{kind}` counters
    (KvStore.note_wire_reject) and never lets it crash the store loop."""

    def __init__(self, kind: str, detail: str = "") -> None:
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind


def _b64(data: Optional[bytes]) -> Optional[str]:
    return None if data is None else base64.b64encode(data).decode()


def _unb64(text: Optional[str]) -> Optional[bytes]:
    return None if text is None else base64.b64decode(text)


def value_to_json(v: Value) -> Dict[str, Any]:
    return {
        "version": v.version,
        "originator_id": v.originator_id,
        "value": _b64(v.value),
        "ttl": v.ttl,
        "ttl_version": v.ttl_version,
        "hash": v.hash,
    }


def _int_field(d: Dict[str, Any], name: str, default: int) -> int:
    got = d.get(name, default)
    # bool is an int subclass; a corrupted frame decoding `true` must not
    # masquerade as a version/ttl
    if not isinstance(got, int) or isinstance(got, bool):
        raise WireDecodeError("malformed", f"{name} must be an int")
    return got


def value_from_json(d: Dict[str, Any]) -> Value:
    if not isinstance(d, dict):
        raise WireDecodeError("malformed", "value frame is not an object")
    if "version" not in d or "originator_id" not in d:
        raise WireDecodeError(
            "truncated", "value frame missing version/originator_id"
        )
    version = _int_field(d, "version", 0)
    originator_id = d["originator_id"]
    if not isinstance(originator_id, str):
        raise WireDecodeError("malformed", "originator_id must be a str")
    ttl = _int_field(d, "ttl", TTL_INFINITY)
    ttl_version = _int_field(d, "ttl_version", 0)
    vhash = d.get("hash")
    if vhash is not None and (
        not isinstance(vhash, int) or isinstance(vhash, bool)
    ):
        raise WireDecodeError("malformed", "hash must be an int")
    raw = d.get("value")
    if raw is not None and not isinstance(raw, str):
        raise WireDecodeError("malformed", "value must be base64 text")
    try:
        value = _unb64(raw)
    except (ValueError, TypeError) as exc:  # binascii.Error is a ValueError
        raise WireDecodeError("malformed", "bad base64 value body") from exc
    if value is not None and len(value) > MAX_VALUE_BYTES:
        raise WireDecodeError(
            "oversized", f"value body {len(value)}B > {MAX_VALUE_BYTES}B"
        )
    if value is not None and vhash is not None:
        # end-to-end integrity: the advertised hash must match the body
        # (a bit-flipped frame that still base64-decodes lands here)
        if generate_hash(version, originator_id, value) != vhash:
            raise WireDecodeError(
                "hash_mismatch", "value bytes do not match advertised hash"
            )
    return Value(
        version=version,
        originator_id=originator_id,
        value=value,
        ttl=ttl,
        ttl_version=ttl_version,
        hash=vhash,
    )


def key_vals_to_json(kv: KeyVals) -> Dict[str, Any]:
    return {k: value_to_json(v) for k, v in kv.items()}


def key_vals_from_json(d: Optional[Dict[str, Any]]) -> KeyVals:
    if not d:
        return {}
    if not isinstance(d, dict):
        raise WireDecodeError("malformed", "key_vals is not an object")
    out: KeyVals = {}
    for k, v in d.items():
        if not isinstance(k, str):
            raise WireDecodeError("malformed", "key must be a str")
        if len(k) > MAX_KEY_CHARS:
            raise WireDecodeError(
                "oversized", f"key {len(k)} chars > {MAX_KEY_CHARS}"
            )
        out[k] = value_from_json(v)
    return out


def perf_events_to_json(
    perf_events: Optional[PerfEvents],
) -> Optional[List[List[Any]]]:
    """Flood-hop trace as [node, event, unix_ts_ms] triples (ts may be a
    float — sub-ms hop latencies matter inside one emulator host)."""
    if perf_events is None:
        return None
    return [
        [e.node_name, e.event_descr, e.unix_ts] for e in perf_events.events
    ]


def perf_events_from_json(
    data: Optional[List[List[Any]]],
) -> Optional[PerfEvents]:
    if data is None:
        return None
    try:
        return PerfEvents(
            [PerfEvent(str(n), str(d), float(ts)) for n, d, ts in data]
        )
    except (TypeError, ValueError) as exc:
        raise WireDecodeError(
            "malformed", "perf_events must be [node, event, ts] triples"
        ) from exc


def publication_to_json(pub: Publication) -> Dict[str, Any]:
    return {
        "key_vals": key_vals_to_json(pub.key_vals),
        "expired_keys": list(pub.expired_keys),
        "node_ids": pub.node_ids,
        "tobe_updated_keys": pub.tobe_updated_keys,
        "area": pub.area,
        # the wall-clock flood-hop trace crosses nodes (unlike the
        # monotonic ts_monotonic/span_stages fields, which stay host-local)
        "perf_events": perf_events_to_json(pub.perf_events),
    }


def _str_list(d: Dict[str, Any], name: str) -> Optional[List[str]]:
    got = d.get(name)
    if got is None:
        return None
    if not isinstance(got, list) or not all(
        isinstance(item, str) for item in got
    ):
        raise WireDecodeError("malformed", f"{name} must be a list of str")
    return got


def publication_from_json(d: Dict[str, Any]) -> Publication:
    if not isinstance(d, dict):
        raise WireDecodeError("malformed", "publication is not an object")
    return Publication(
        key_vals=key_vals_from_json(d.get("key_vals")),
        expired_keys=list(_str_list(d, "expired_keys") or []),
        node_ids=_str_list(d, "node_ids"),
        tobe_updated_keys=_str_list(d, "tobe_updated_keys"),
        area=d.get("area", "0"),
        perf_events=perf_events_from_json(d.get("perf_events")),
    )


def dual_messages_to_json(msgs: DualMessages) -> Dict[str, Any]:
    return {
        "src_id": msgs.src_id,
        "messages": [
            {"dst_id": m.dst_id, "distance": m.distance, "type": m.type.name}
            for m in msgs.messages
        ],
    }


def dual_messages_from_json(d: Dict[str, Any]) -> DualMessages:
    if not isinstance(d, dict):
        raise WireDecodeError("malformed", "dual_messages is not an object")
    try:
        return DualMessages(
            src_id=d.get("src_id", ""),
            messages=[
                DualMessage(
                    dst_id=m["dst_id"],
                    distance=m["distance"],
                    type=DualMessageType[m["type"]],
                )
                for m in d.get("messages") or []
            ],
        )
    except (KeyError, TypeError) as exc:
        raise WireDecodeError("malformed", "bad dual message") from exc
