"""JSON wire codecs for the KvStore peer protocol.

The reference ships thrift-serialized structs between stores
(openr/if/KvStore.thrift: Value:20, Publication:228, openr/if/Dual.thrift
DualMessages); the TCP peer transport here (openr_tpu.kvstore.tcp) carries
the same fields as newline-delimited JSON, with value bytes base64-encoded.
Full fidelity matters: node_ids (flood loop prevention) and
tobe_updated_keys (3-way sync) must round-trip exactly.
"""

from __future__ import annotations

import base64
from typing import Any, Dict, List, Optional

from openr_tpu.dual.dual import DualMessage, DualMessages, DualMessageType
from openr_tpu.types import (
    TTL_INFINITY,
    KeyVals,
    PerfEvent,
    PerfEvents,
    Publication,
    Value,
)


def _b64(data: Optional[bytes]) -> Optional[str]:
    return None if data is None else base64.b64encode(data).decode()


def _unb64(text: Optional[str]) -> Optional[bytes]:
    return None if text is None else base64.b64decode(text)


def value_to_json(v: Value) -> Dict[str, Any]:
    return {
        "version": v.version,
        "originator_id": v.originator_id,
        "value": _b64(v.value),
        "ttl": v.ttl,
        "ttl_version": v.ttl_version,
        "hash": v.hash,
    }


def value_from_json(d: Dict[str, Any]) -> Value:
    return Value(
        version=d["version"],
        originator_id=d["originator_id"],
        value=_unb64(d.get("value")),
        ttl=d.get("ttl", TTL_INFINITY),
        ttl_version=d.get("ttl_version", 0),
        hash=d.get("hash"),
    )


def key_vals_to_json(kv: KeyVals) -> Dict[str, Any]:
    return {k: value_to_json(v) for k, v in kv.items()}


def key_vals_from_json(d: Optional[Dict[str, Any]]) -> KeyVals:
    if not d:
        return {}
    return {k: value_from_json(v) for k, v in d.items()}


def perf_events_to_json(
    perf_events: Optional[PerfEvents],
) -> Optional[List[List[Any]]]:
    """Flood-hop trace as [node, event, unix_ts_ms] triples (ts may be a
    float — sub-ms hop latencies matter inside one emulator host)."""
    if perf_events is None:
        return None
    return [
        [e.node_name, e.event_descr, e.unix_ts] for e in perf_events.events
    ]


def perf_events_from_json(
    data: Optional[List[List[Any]]],
) -> Optional[PerfEvents]:
    if data is None:
        return None
    return PerfEvents(
        [PerfEvent(str(n), str(d), ts) for n, d, ts in data]
    )


def publication_to_json(pub: Publication) -> Dict[str, Any]:
    return {
        "key_vals": key_vals_to_json(pub.key_vals),
        "expired_keys": list(pub.expired_keys),
        "node_ids": pub.node_ids,
        "tobe_updated_keys": pub.tobe_updated_keys,
        "area": pub.area,
        # the wall-clock flood-hop trace crosses nodes (unlike the
        # monotonic ts_monotonic/span_stages fields, which stay host-local)
        "perf_events": perf_events_to_json(pub.perf_events),
    }


def publication_from_json(d: Dict[str, Any]) -> Publication:
    return Publication(
        key_vals=key_vals_from_json(d.get("key_vals")),
        expired_keys=list(d.get("expired_keys") or []),
        node_ids=d.get("node_ids"),
        tobe_updated_keys=d.get("tobe_updated_keys"),
        area=d.get("area", "0"),
        perf_events=perf_events_from_json(d.get("perf_events")),
    )


def dual_messages_to_json(msgs: DualMessages) -> Dict[str, Any]:
    return {
        "src_id": msgs.src_id,
        "messages": [
            {"dst_id": m.dst_id, "distance": m.distance, "type": m.type.name}
            for m in msgs.messages
        ],
    }


def dual_messages_from_json(d: Dict[str, Any]) -> DualMessages:
    return DualMessages(
        src_id=d.get("src_id", ""),
        messages=[
            DualMessage(
                dst_id=m["dst_id"],
                distance=m["distance"],
                type=DualMessageType[m["type"]],
            )
            for m in d.get("messages") or []
        ],
    )
