"""Replicated eventually-consistent key-value store — the LSDB bus.

Equivalent of openr/kvstore/: versioned CRDT merge (version > originatorId >
value bytes, ttlVersion refresh), TTL expiry, prefix/originator filters,
3-way full sync, incremental flooding with path-vector loop prevention, flood
rate limiting with buffering, per-area instances, and a peer FSM
(IDLE → SYNCING → INITIALIZED). The network transport is a seam: tests use the
in-process transport (the KvStoreWrapper trick), production uses TCP.
"""

from openr_tpu.kvstore.store import (
    KvStore,
    KvStoreDb,
    KvStoreFilters,
    KvStoreParams,
    PeerHealth,
    PeerSpec,
    PeerState,
    compare_values,
    merge_key_values,
)
from openr_tpu.kvstore.transport import InProcessTransport, KvStoreTransport
from openr_tpu.kvstore.tcp import KvStoreTcpServer, TcpTransport
from openr_tpu.kvstore.wire import WireDecodeError
from openr_tpu.kvstore.client import KvStoreClient

__all__ = [
    "KvStoreClient",
    "KvStore",
    "KvStoreDb",
    "KvStoreFilters",
    "KvStoreParams",
    "PeerHealth",
    "PeerSpec",
    "PeerState",
    "compare_values",
    "merge_key_values",
    "InProcessTransport",
    "KvStoreTransport",
    "KvStoreTcpServer",
    "TcpTransport",
    "WireDecodeError",
]
