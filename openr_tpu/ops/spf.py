"""Batched multi-source shortest paths as min-plus relaxation on TPU.

Replaces the reference's per-source Dijkstra hot loop
(openr/decision/LinkState.cpp:806-880) with Bellman-Ford relaxation rounds
over the whole source batch at once:

    D[s, v] <- min(D[s, v], min over edges (u->v): Dt[s, u] + w(u, v))

where Dt masks transit through overloaded nodes per source (a source's own
row keeps its outgoing edges — LinkState.cpp:829-836 semantics). Each round is
a gather + add + segment-min, entirely fusible by XLA; rounds run under
lax.while_loop until the distance matrix reaches its fixpoint (≤ diameter
rounds, bounded by n for safety).

The ECMP first-hop DAG falls out of the triangle condition
    w(u, v) + D[v, t] == D[u, t]
which reproduces exactly the Dijkstra nexthop-union semantics of
LinkState.cpp:855-871 (proof: a pruned shortest path with first hop v exists
iff v is non-overloaded-or-destination and the triangle holds).

Sharding: all arrays are batched on the sources axis; `sharded_batched_spf`
in openr_tpu.parallel shards that axis over the device mesh so each chip
relaxes its slice of sources with the (small) edge list replicated — no
cross-chip traffic inside a round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.ops.graph import INF, CompiledGraph


@jax.jit
def _bf_fixpoint_vw(
    sources: jnp.ndarray,  # int32 [S]
    src_e: jnp.ndarray,  # int32 [E]
    dst_e: jnp.ndarray,  # int32 [E]
    w_rows: jnp.ndarray,  # int32 [S, E] or [1, E] (broadcast) edge weights
    overloaded: jnp.ndarray,  # bool [N]
) -> jnp.ndarray:
    """Distance matrix D [S, N]; each batch row may solve with its own
    edge-weight vector. Per-row weights are the device form of the
    reference's penalized re-solves: KSP's link-ignore runSpf
    (LinkState.cpp:760-789, ignore set ≙ INF weights) and
    multi-metric/multi-topology SPF become extra batch rows of one solve
    instead of sequential Dijkstra runs."""
    n = overloaded.shape[0]
    s = sources.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32)

    d0 = jnp.full((s, n), INF, dtype=jnp.int32)
    d0 = d0.at[jnp.arange(s), sources].set(0)

    # transit allowed through u for source row i unless u is overloaded and
    # u is not the source itself
    allow = (~overloaded)[None, :] | (node_ids[None, :] == sources[:, None])

    def body(state):
        d, _, it = state
        dt = jnp.where(allow, d, INF)
        contrib = jnp.minimum(dt[:, src_e] + w_rows, INF)  # [S, E]
        upd = jax.vmap(
            lambda row: jax.ops.segment_min(
                row, dst_e, num_segments=n, indices_are_sorted=True
            )
        )(contrib)
        new_d = jnp.minimum(d, upd)
        return new_d, jnp.any(new_d != d), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d


@jax.jit
def _bf_fixpoint(
    sources: jnp.ndarray,  # int32 [S]
    src_e: jnp.ndarray,  # int32 [E]
    dst_e: jnp.ndarray,  # int32 [E]
    w_e: jnp.ndarray,  # int32 [E]
    overloaded: jnp.ndarray,  # bool [N]
) -> jnp.ndarray:
    """Shared-weights solve: one kernel, weights broadcast across the batch."""
    return _bf_fixpoint_vw(sources, src_e, dst_e, w_e[None, :], overloaded)


@jax.jit
def _bf_fixpoint_ell(
    sources: jnp.ndarray,  # int32 [S]
    nbr: jnp.ndarray,  # int32 [N, md] in-neighbor ids (ELL layout)
    wg: jnp.ndarray,  # int32 [N, md]; INF for padding/down links
    overloaded: jnp.ndarray,  # bool [N]
) -> jnp.ndarray:
    """Distance matrix D [S, N] via the "pull" relaxation: each round is
    max-in-degree row-gathers + vector mins over a destination-major [N, S]
    matrix — no scatter, all accesses row-contiguous. Measured ~6x faster
    per round than the edge-list gather/segment-min form on TPU for
    degree-4 grids; selected automatically for bounded-degree graphs."""
    n, md = wg.shape
    s = sources.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32)

    d0 = jnp.full((n, s), INF, dtype=jnp.int32)  # dest-major
    d0 = d0.at[sources, jnp.arange(s)].set(0)
    # transit allowed through u for source column j unless u is overloaded
    # and u is not the source itself
    allow = (~overloaded)[:, None] | (node_ids[:, None] == sources[None, :])

    def body(state):
        d, _, it = state
        dt = jnp.where(allow, d, INF)

        def k_step(k, acc):
            relaxed = jnp.minimum(dt[nbr[:, k]] + wg[:, k][:, None], INF)
            return jnp.minimum(acc, relaxed)

        new_d = jax.lax.fori_loop(0, md, k_step, d)
        return new_d, jnp.any(new_d != d), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    d, _, _ = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d.T


def batched_spf(graph: CompiledGraph, source_rows: np.ndarray) -> jnp.ndarray:
    """Run the batched solve for the given source node indices.

    Dispatches to the ELL pull kernel when the graph's degree profile
    qualifies (ops.graph._build_ell), else the edge-list segment-min form.
    """
    if graph.nbr is not None:
        return _bf_fixpoint_ell(
            jnp.asarray(source_rows, dtype=jnp.int32),
            jnp.asarray(graph.nbr),
            jnp.asarray(graph.wg),
            jnp.asarray(graph.overloaded),
        )
    return _bf_fixpoint(
        jnp.asarray(source_rows, dtype=jnp.int32),
        jnp.asarray(graph.src),
        jnp.asarray(graph.dst),
        jnp.asarray(graph.w),
        jnp.asarray(graph.overloaded),
    )


def batched_spf_vw(
    graph: CompiledGraph, source_rows: np.ndarray, w_rows: np.ndarray
) -> jnp.ndarray:
    """Batched solve with per-row weight vectors (shape [S, e_pad])."""
    return _bf_fixpoint_vw(
        jnp.asarray(source_rows, dtype=jnp.int32),
        jnp.asarray(graph.src),
        jnp.asarray(graph.dst),
        jnp.asarray(w_rows, dtype=jnp.int32),
        jnp.asarray(graph.overloaded),
    )


@jax.jit
def _ecmp_dag(
    d: jnp.ndarray,  # int32 [N, N] all-pairs distances (row = source)
    src_e: jnp.ndarray,
    dst_e: jnp.ndarray,
    w_e: jnp.ndarray,
    overloaded: jnp.ndarray,
) -> jnp.ndarray:
    """Per-edge shortest-DAG membership: out[e, t] == True iff directed edge
    e = (u -> v) is the first hop of some shortest path u -> t."""
    n = overloaded.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32)
    du = d[src_e]  # [E, N] distances from each edge's source
    dv = d[dst_e]  # [E, N] distances from each edge's destination
    triangle = jnp.minimum(w_e[:, None] + dv, INF) == du
    # v may not relay traffic when overloaded, unless v is the destination
    transit_ok = (~overloaded[dst_e])[:, None] | (
        node_ids[None, :] == dst_e[:, None]
    )
    reachable = du < INF
    return triangle & transit_ok & reachable


def ecmp_dag(graph: CompiledGraph, d: jnp.ndarray) -> jnp.ndarray:
    """First-hop DAG for all-pairs distance matrix d (rows must be indexed by
    node id, i.e. computed with source_rows = arange(n_pad))."""
    return _ecmp_dag(
        d,
        jnp.asarray(graph.src),
        jnp.asarray(graph.dst),
        jnp.asarray(graph.w),
        jnp.asarray(graph.overloaded),
    )


