"""Batched multi-source shortest paths as min-plus relaxation on TPU.

Replaces the reference's per-source Dijkstra hot loop
(openr/decision/LinkState.cpp:806-880) with Bellman-Ford relaxation rounds
over the whole source batch at once:

    D[s, v] <- min(D[s, v], min over edges (u->v): Dt[s, u] + w(u, v))

where Dt masks transit through overloaded nodes per source (a source's own
row keeps its outgoing edges — LinkState.cpp:829-836 semantics). Each round is
a gather + add + segment-min, entirely fusible by XLA; rounds run under
lax.while_loop until the distance matrix reaches its fixpoint (≤ diameter
rounds, bounded by n for safety).

The ECMP first-hop DAG falls out of the triangle condition
    w(u, v) + D[v, t] == D[u, t]
which reproduces exactly the Dijkstra nexthop-union semantics of
LinkState.cpp:855-871 (proof: a pruned shortest path with first hop v exists
iff v is non-overloaded-or-destination and the triangle holds).

Sharding: all arrays are batched on the sources axis; `sharded_batched_spf`
in openr_tpu.parallel shards that axis over the device mesh so each chip
relaxes its slice of sources with the (small) edge list replicated — no
cross-chip traffic inside a round.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.ops.graph import INF, CompiledGraph, _next_bucket
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils.shape_contract import shape_contract


def _bf_allow(sources: jnp.ndarray, overloaded: jnp.ndarray) -> jnp.ndarray:
    """Row-major [S, N] transit mask: transit allowed through u for source
    row i unless u is overloaded and u is not the source itself."""
    n = overloaded.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32)
    return (~overloaded)[None, :] | (node_ids[None, :] == sources[:, None])


@shape_contract(
    "d0:[S,n_pad]:int32:inf",
    "allow:[S,n_pad]:bool",
    "src_e:[E]:int32",
    "dst_e:[E]:int32",
)
def _bf_relax(d0, allow, src_e, dst_e, w_rows):
    """Edge-list min-plus relaxation from row-major initial state d0 to the
    fixpoint; returns (d [S, N], rounds). Like _sell_relax, any entrywise
    upper bound of the true distances with the source diagonal pinned to 0
    is a valid d0, which is what makes the edge-list warm path sound."""
    n = d0.shape[1]

    def body(state):
        d, _, it = state
        dt = jnp.where(allow, d, INF)
        contrib = jnp.minimum(dt[:, src_e] + w_rows, INF)  # [S, E]
        upd = jax.vmap(
            lambda row: jax.ops.segment_min(
                row, dst_e, num_segments=n, indices_are_sorted=True
            )
        )(contrib)
        new_d = jnp.minimum(d, upd)
        return new_d, jnp.any(new_d != d), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    d, _, rounds = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d, rounds


def _bf_fixpoint_vw_core(
    sources: jnp.ndarray,  # int32 [S]
    src_e: jnp.ndarray,  # int32 [E]
    dst_e: jnp.ndarray,  # int32 [E]
    w_rows: jnp.ndarray,  # int32 [S, E] or [1, E] (broadcast) edge weights
    overloaded: jnp.ndarray,  # bool [N]
) -> jnp.ndarray:
    """Distance matrix D [S, N]; each batch row may solve with its own
    edge-weight vector. Per-row weights are the device form of the
    reference's penalized re-solves: KSP's link-ignore runSpf
    (LinkState.cpp:760-789, ignore set ≙ INF weights) and
    multi-metric/multi-topology SPF become extra batch rows of one solve
    instead of sequential Dijkstra runs."""
    n = overloaded.shape[0]
    s = sources.shape[0]
    d0 = jnp.full((s, n), INF, dtype=jnp.int32)
    d0 = d0.at[jnp.arange(s), sources].set(0)
    allow = _bf_allow(sources, overloaded)
    d, _ = _bf_relax(d0, allow, src_e, dst_e, w_rows)
    return d


_bf_fixpoint_vw = jax.jit(_bf_fixpoint_vw_core)


@functools.lru_cache(maxsize=8)
def _bf_vw_solver(mesh=None):
    """Jitted per-row-weights edge-list solve, optionally mesh-sharded
    (sources and weight rows over 'batch'). The non-sliced analog of
    _sell_solver_vw(key, mesh) so KSP prefetch honors solver_mesh on
    graphs that disqualify the sliced-ELL layout."""
    if mesh is None:
        return _bf_fixpoint_vw
    row, repl, row2 = _mesh_shardings(mesh)
    return jax.jit(
        _bf_fixpoint_vw_core,
        in_shardings=(row, repl, repl, row2, repl),
        out_shardings=row2,
    )


@jax.jit
def _bf_fixpoint(
    sources: jnp.ndarray,  # int32 [S]
    src_e: jnp.ndarray,  # int32 [E]
    dst_e: jnp.ndarray,  # int32 [E]
    w_e: jnp.ndarray,  # int32 [E]
    overloaded: jnp.ndarray,  # bool [N]
) -> jnp.ndarray:
    """Shared-weights solve: one kernel, weights broadcast across the batch."""
    return _bf_fixpoint_vw(sources, src_e, dst_e, w_e[None, :], overloaded)


@functools.lru_cache(maxsize=64)
def _sell_solver_raw(key: Tuple):
    """Unjitted sliced-ELL fixpoint for one bucket structure (SlicedEll
    .shape_key()) — callers jit it themselves (with shardings for the mesh
    path). Weight patches keep the structure, so per-structure executables
    are reused across LSDB events; lru_cache bounds the executable
    population the way the size-bucket padding does.

    Each round processes the destination-major [N, S] distance matrix in
    contiguous equal-degree row slices: slice k relaxes via dk row-gathers
    + fused vector mins, writing only the [nk, S] slice — no scatter and no
    [E, S] contribution materialization, which is what makes this ~1.7x
    faster than the edge-list segment-min form at 100k nodes."""

    zero_end, starts, shapes = key

    def solve(sources, nbrs, wgs, overloaded):
        return _sell_fixpoint_core(
            sources, nbrs, wgs, overloaded, zero_end, starts, shapes
        )

    return solve


# bound trace-time unrolling for fat buckets (Clos spines etc.); the
# fori_loop body indexes nbr/wg columns dynamically instead
_UNROLL_MAX = 32


def _sell_d0_allow(sources, overloaded):
    """Cold-start dest-major initial state [N, S] plus the per-source
    transit mask (overloaded nodes relay nothing unless they are the
    source itself)."""
    (n,) = overloaded.shape
    s = sources.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32)
    d0 = jnp.full((n, s), INF, dtype=jnp.int32)  # dest-major
    d0 = d0.at[sources, jnp.arange(s)].set(0)
    allow = (~overloaded)[:, None] | (node_ids[:, None] == sources[None, :])
    return d0, allow


def _sell_relax(d0, allow, nbrs, wgs, zero_end, starts, shapes):
    """Min-plus relaxation from dest-major initial state d0 to the fixpoint.

    Returns (d [N, S], rounds). Valid for ANY d0 that is an entrywise upper
    bound of the true distances with the source diagonal pinned to 0: the
    iteration map F(D) = min(D, relax(D)) is monotone, keeps D >= D*, and
    its only fixed point with D[s, s] = 0 at or above D* is D* itself —
    which is what makes warm-starting from a previous event's distances
    sound (cold start D0 = INF is just the trivial upper bound).

    wgs leaves are [nk, dk] (shared across the batch) or [nk, dk, S]
    (per-batch-row weights, the penalized-re-solve form); broadcasting
    handles both in one implementation so the two paths cannot diverge."""
    n = d0.shape[0]

    def body(state):
        d, _, it = state
        dt = jnp.where(allow, d, INF)
        parts = [d[:zero_end]] if zero_end else []
        end = zero_end
        for k, (nbr_k, wg_k) in enumerate(zip(nbrs, wgs)):
            nk, dk = shapes[k]
            bs = starts[k]
            acc = d[bs : bs + nk]
            if dk <= _UNROLL_MAX:
                for j in range(dk):
                    wj = (
                        wg_k[:, j][:, None]
                        if wg_k.ndim == 2
                        else wg_k[:, j, :]
                    )
                    acc = jnp.minimum(
                        acc, jnp.minimum(dt[nbr_k[:, j]] + wj, INF)
                    )
            else:

                def j_step(j, a, nbr_k=nbr_k, wg_k=wg_k):
                    ids = jax.lax.dynamic_index_in_dim(
                        nbr_k, j, axis=1, keepdims=False
                    )
                    wj = jax.lax.dynamic_index_in_dim(
                        wg_k, j, axis=1, keepdims=False
                    )
                    if wg_k.ndim == 2:
                        wj = wj[:, None]
                    return jnp.minimum(
                        a, jnp.minimum(dt[ids] + wj, INF)
                    )

                acc = jax.lax.fori_loop(0, dk, j_step, acc)
            parts.append(acc)
            end = bs + nk
        if end < n:
            parts.append(d[end:])  # array-padding rows never change
        new_d = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
        return new_d, jnp.any(new_d != d), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    d, _, rounds = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d, rounds


def _sell_fixpoint_core(
    sources, nbrs, wgs, overloaded, zero_end, starts, shapes
):
    """Cold-start fixpoint (distances only), row-major [S, N]."""
    d0, allow = _sell_d0_allow(sources, overloaded)
    d, _ = _sell_relax(d0, allow, nbrs, wgs, zero_end, starts, shapes)
    return d.T


def _mesh_shardings(mesh):
    """(row-sharded over 'batch', replicated) NamedShardings for a solver
    mesh. The sliced-ELL solve shards only its source batch; the layout
    leaves are replicated so each relaxation round stays collective-free
    (openr_tpu/parallel/mesh.py design)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return (
        NamedSharding(mesh, P("batch")),
        NamedSharding(mesh, P()),
        NamedSharding(mesh, P("batch", None)),
    )


@functools.lru_cache(maxsize=64)
def _sell_solver(key: Tuple, mesh=None):
    """Jitted form of _sell_solver_raw. With a mesh, the source batch is
    sharded over the 'batch' axis and D comes back row-sharded — the
    production multi-chip path (DecisionConfig.solver_mesh)."""
    if mesh is None:
        return jax.jit(_sell_solver_raw(key))
    row, repl, out = _mesh_shardings(mesh)
    return jax.jit(
        _sell_solver_raw(key),
        in_shardings=(row, repl, repl, repl),
        out_shardings=out,
    )


@functools.lru_cache(maxsize=64)
def _sell_solver_counted(key: Tuple, mesh=None):
    """Like _sell_solver, but also returns the relaxation round count:
    (D [S, N], rounds). The device-resident event path uses this for its
    cold solves so `decision.spf.rounds_last` covers every solve, warm or
    cold, and warm-start wins are observable as a round-count drop."""
    zero_end, starts, shapes = key

    def solve(sources, nbrs, wgs, overloaded):
        d0, allow = _sell_d0_allow(sources, overloaded)
        d, rounds = _sell_relax(d0, allow, nbrs, wgs, zero_end, starts, shapes)
        return d.T, rounds

    if mesh is None:
        return jax.jit(solve)
    row, repl, out = _mesh_shardings(mesh)
    return jax.jit(
        solve,
        in_shardings=(row, repl, repl, repl),
        out_shardings=(out, repl),
    )


def _sell_apply_patches(wgs, patch_idx, patch_vals):
    """Scatter the fixed-width per-bucket weight patches into the bucket
    arrays; padding rows carry out-of-range indices and are dropped."""
    return tuple(
        wg_k.at[patch_idx[k, :, 0], patch_idx[k, :, 1]].set(
            patch_vals[k], mode="drop"
        )
        for k, wg_k in enumerate(wgs)
    )


@functools.lru_cache(maxsize=64)
def _sell_solver_patched(key: Tuple, mesh=None):
    """Patch-and-solve in one dispatch: applies per-bucket weight patches
    (idx [Pk, 2] of (row, slot), vals [Pk]; out-of-range rows dropped) to
    the persistent wg buffers, solves cold, and returns (D, new_wgs,
    rounds) so the caller can keep the patched buffers device-resident.
    One device dispatch per LSDB event instead of scatter + solve — the
    host-side share of a flap event is mostly dispatch latency."""
    zero_end, starts, shapes = key

    def solve(sources, nbrs, wgs, overloaded, patch_idx, patch_vals):
        # patch_idx [B, P, 2] / patch_vals [B, P]: one upload each, sliced
        # per bucket at trace time (B is fixed by the shape key)
        new_wgs = _sell_apply_patches(wgs, patch_idx, patch_vals)
        d0, allow = _sell_d0_allow(sources, overloaded)
        d, rounds = _sell_relax(
            d0, allow, nbrs, new_wgs, zero_end, starts, shapes
        )
        return d.T, new_wgs, rounds

    # donate the replaced weight buffers: the caller always overwrites its
    # handle with new_wgs, so XLA may update in place instead of allocating
    # a second full set of buckets per event
    if mesh is None:
        return jax.jit(solve, donate_argnums=(2,))
    row, repl, out = _mesh_shardings(mesh)
    return jax.jit(
        solve,
        donate_argnums=(2,),
        in_shardings=(row, repl, repl, repl, repl, repl),
        out_shardings=(out, repl, repl),
    )


def _sell_invalidate(dp, nbrs, wgs, inc_idx, zero_end, starts, shapes):
    """Ramalingam–Reps-style invalidation, vectorized on the sliced layout.

    dp is the dest-major [N, S] OLD distance fixpoint and wgs the OLD
    bucket weights. inc_idx [B, P, 2] names the (row, slot) positions whose
    weight is about to increase (padding rows carry out-of-range indices).
    Returns (marks, rounds): marks is a bool [N, S] mask of entries whose
    old shortest-path witness may traverse an increased edge, rounds the
    boolean fixpoint's iteration count (the ROADMAP rounds-accounting gap:
    mark propagation is cheap per round but unbounded in principle on deep
    DAGs, so it is surfaced as decision.spf.invalidation_rounds_last).
    Seed marks where an increased edge sits on the old shortest-path DAG
    (triangle condition against the old weights), then propagate marks down
    the old DAG with a boolean fixpoint (`_sell_mark_fixpoint`, shared with
    the per-row KSP warm seed). Over-marking is safe (marked entries are
    recomputed from INF); under-marking is impossible because every true
    DAG edge passes the unmasked triangle test."""
    n, s = dp.shape
    marks = jnp.zeros((n, s), dtype=jnp.bool_)
    for k, (nbr_k, wg_k) in enumerate(zip(nbrs, wgs)):
        nk, dk = shapes[k]
        rows = inc_idx[k, :, 0]
        slots = inc_idx[k, :, 1]
        valid = rows < (1 << 29)  # padding rows are 1 << 30
        r = jnp.clip(rows, 0, nk - 1)
        j = jnp.clip(slots, 0, dk - 1)
        u = nbr_k[r, j]  # [P] in-neighbor of each increased edge
        w_old = wg_k[r, j]  # [P]
        v = starts[k] + r  # [P] global node row of each edge head
        dv = dp[v]  # [P, S]
        cond = (
            valid[:, None]
            & (dv < INF)
            & (jnp.minimum(dp[u] + w_old[:, None], INF) == dv)
        )
        marks = marks.at[v].max(cond)
    return _sell_mark_fixpoint(dp, marks, nbrs, wgs, zero_end, starts, shapes)


def _sell_mark_fixpoint(dp, marks, nbrs, wgs, zero_end, starts, shapes):
    """Propagate invalidation marks down the old shortest-path DAG (a
    boolean fixpoint over the sliced layout): an entry marks when any of
    its old-DAG in-edges carries a marked tail. Shared by the shared-
    weights warm path (_sell_invalidate seeds) and the per-row KSP warm
    seed (_sell_solver_vw_warm seeds). Returns (marks, rounds)."""
    n, _ = dp.shape

    def body(state):
        m, _, it = state
        parts = [m[:zero_end]] if zero_end else []
        end = zero_end
        for k, (nbr_k, wg_k) in enumerate(zip(nbrs, wgs)):
            nk, dk = shapes[k]
            bs = starts[k]
            dv = dp[bs : bs + nk]
            reach = dv < INF
            acc = m[bs : bs + nk]
            if dk <= _UNROLL_MAX:
                for j in range(dk):
                    ids = nbr_k[:, j]
                    wj = wg_k[:, j][:, None]
                    on_dag = jnp.minimum(dp[ids] + wj, INF) == dv
                    acc = acc | (m[ids] & on_dag & reach)
            else:

                def j_step(j, a, nbr_k=nbr_k, wg_k=wg_k, dv=dv, reach=reach):
                    ids = jax.lax.dynamic_index_in_dim(
                        nbr_k, j, axis=1, keepdims=False
                    )
                    wj = jax.lax.dynamic_index_in_dim(
                        wg_k, j, axis=1, keepdims=False
                    )[:, None]
                    on_dag = jnp.minimum(dp[ids] + wj, INF) == dv
                    return a | (m[ids] & on_dag & reach)

                acc = jax.lax.fori_loop(0, dk, j_step, acc)
            parts.append(acc)
            end = bs + nk
        if end < n:
            parts.append(m[end:])
        new_m = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
        return new_m, jnp.any(new_m != m), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    # zero increased edges -> zero seed marks -> the loop is skipped whole,
    # so decrease-only events pay nothing for sharing this executable
    marks, _, rounds = jax.lax.while_loop(
        cond, body, (marks, jnp.any(marks), 0)
    )
    return marks, rounds


@functools.lru_cache(maxsize=64)
def _sell_solver_warm(key: Tuple, mesh=None):
    """Warm-start incremental patch-and-solve, one dispatch per LSDB event.

    (sources, nbrs, wgs, overloaded, patch_idx, patch_vals, inc_idx,
    d_prev) -> (D, new_wgs, rounds, inv_rounds, col_changed, num_changed):
    invalidates the entries of d_prev [S, N] whose old shortest path may
    witness an increased edge (_sell_invalidate, against the OLD weights),
    applies the weight patches, and relaxes from the repaired state instead
    of from INF — rounds scale with the affected radius of the event, not
    the graph diameter. inv_rounds is the invalidation mark fixpoint's own
    round count (0 for decrease-only events, whose empty inc_idx skips the
    loop and warm-starts directly).

    col_changed is a DEVICE-resident bool [N]: destination columns whose
    distance row moved vs d_prev — the DeltaPath seed. num_changed is its
    scalar popcount; the host reads only that int (4 bytes) and then sizes
    a compacted `_delta_extract` dispatch, so the per-event copy-back is
    O(changes), never the [S, N] mirror. All patch shapes are fixed
    (_PATCH_SLOTS per bucket) so one executable serves every event; d_prev
    and the weight buffers are donated since the caller always replaces
    its handles."""
    zero_end, starts, shapes = key

    def solve(
        sources, nbrs, wgs, overloaded, patch_idx, patch_vals, inc_idx, d_prev
    ):
        s = sources.shape[0]
        dp = d_prev.T  # dest-major [N, S], like the relaxation state
        marks, inv_rounds = _sell_invalidate(
            dp, nbrs, wgs, inc_idx, zero_end, starts, shapes
        )
        new_wgs = _sell_apply_patches(wgs, patch_idx, patch_vals)
        d0 = jnp.where(marks, INF, dp)
        d0 = d0.at[sources, jnp.arange(s)].set(0)  # re-pin marked sources
        _, allow = _sell_d0_allow(sources, overloaded)
        d, rounds = _sell_relax(
            d0, allow, nbrs, new_wgs, zero_end, starts, shapes
        )
        col_changed = jnp.any(d != dp, axis=1)  # dest-major: [N]
        num_changed = jnp.sum(col_changed, dtype=jnp.int32)
        return d.T, new_wgs, rounds, inv_rounds, col_changed, num_changed

    if mesh is None:
        return jax.jit(solve, donate_argnums=(2, 7))
    row, repl, out = _mesh_shardings(mesh)
    return jax.jit(
        solve,
        donate_argnums=(2, 7),
        in_shardings=(row, repl, repl, repl, repl, repl, repl, out),
        out_shardings=(out, repl, repl, repl, repl, repl),
    )


def _bf_warm_core(
    sources: jnp.ndarray,  # int32 [S]
    src_e: jnp.ndarray,  # int32 [E]
    dst_e: jnp.ndarray,  # int32 [E] (sorted ascending)
    w_new: jnp.ndarray,  # int32 [E] weights after the event
    w_old: jnp.ndarray,  # int32 [E] weights that produced d_prev
    overloaded: jnp.ndarray,  # bool [N]
    d_prev: jnp.ndarray,  # int32 [S, N] previous fixpoint (donated)
):
    """Warm-start solve on the edge-list (non sliced-ELL) layout: the same
    Ramalingam–Reps-style recipe as _sell_solver_warm, but with the
    increased-edge set derived on device from w_new > w_old instead of a
    host-built index patch (the edge-list form has no fixed-width slot
    structure to patch into; uploading the [E] weight vector per event is
    the layout's native cost anyway).

    Seed marks where an increased edge sits on the old shortest-path DAG
    (triangle condition against w_old), propagate marks down the old DAG
    with a boolean segment-max fixpoint, reset marked entries to INF, then
    relax from the repaired state with the new weights. Returns
    (d, rounds, inv_rounds, col_changed [N] bool, num_changed) — the same
    delta outputs as the sliced path, so `_delta_extract` serves both."""
    n = overloaded.shape[0]
    s = sources.shape[0]
    dp = d_prev
    du = dp[:, src_e]  # [S, E]
    dv = dp[:, dst_e]
    on_old = (jnp.minimum(du + w_old[None, :], INF) == dv) & (dv < INF)
    seeds = on_old & (w_new > w_old)[None, :]

    def seg_any(rows):  # bool [S, E] -> bool [S, N] (OR per destination)
        return (
            jax.vmap(
                lambda row: jax.ops.segment_max(
                    row.astype(jnp.int32),
                    dst_e,
                    num_segments=n,
                    indices_are_sorted=True,
                )
            )(rows)
            > 0
        )

    marks0 = seg_any(seeds)

    def body(state):
        m, _, it = state
        new_m = m | seg_any(m[:, src_e] & on_old)
        return new_m, jnp.any(new_m != m), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    # zero increased edges -> zero seed marks -> the loop is skipped whole
    marks, _, inv_rounds = jax.lax.while_loop(
        cond, body, (marks0, jnp.any(marks0), 0)
    )
    d0 = jnp.where(marks, INF, dp)
    d0 = d0.at[jnp.arange(s), sources].set(0)  # re-pin marked sources
    allow = _bf_allow(sources, overloaded)
    d, rounds = _bf_relax(d0, allow, src_e, dst_e, w_new[None, :])
    col_changed = jnp.any(d != dp, axis=0)  # row-major: reduce sources
    num_changed = jnp.sum(col_changed, dtype=jnp.int32)
    return d, rounds, inv_rounds, col_changed, num_changed


_bf_solver_warm = jax.jit(_bf_warm_core, donate_argnums=(6,))


def _bf_warm_vw_core(
    sources: jnp.ndarray,  # int32 [S]
    src_e: jnp.ndarray,  # int32 [E]
    dst_e: jnp.ndarray,  # int32 [E] (sorted ascending)
    w_rows: jnp.ndarray,  # int32 [S, E] per-row weights after the event
    w_base: jnp.ndarray,  # int32 [E] shared weights that produced d_prev
    overloaded: jnp.ndarray,  # bool [N]
    d_prev: jnp.ndarray,  # int32 [S, N] base fixpoint (NOT donated)
):
    """Per-row-weights warm solve on the edge-list layout: the KSP
    layer-seeding form of _bf_warm_core. Every per-row weight change is an
    INCREASE (link-ignore masks pin base weights to INF), so each batch
    row warm-starts from the shared unpenalized base fixpoint: seed marks
    where a row's masked edge sits on the base DAG, propagate down the
    base DAG, reset, and relax with the per-row weights. d_prev is a
    broadcast view of the resident base row, so it is not donated."""
    n = overloaded.shape[0]
    s = sources.shape[0]
    dp = d_prev
    du = dp[:, src_e]  # [S, E]
    dv = dp[:, dst_e]
    on_old = (jnp.minimum(du + w_base[None, :], INF) == dv) & (dv < INF)
    seeds = on_old & (w_rows > w_base[None, :])

    def seg_any(rows):  # bool [S, E] -> bool [S, N] (OR per destination)
        return (
            jax.vmap(
                lambda row: jax.ops.segment_max(
                    row.astype(jnp.int32),
                    dst_e,
                    num_segments=n,
                    indices_are_sorted=True,
                )
            )(rows)
            > 0
        )

    marks0 = seg_any(seeds)

    def body(state):
        m, _, it = state
        new_m = m | seg_any(m[:, src_e] & on_old)
        return new_m, jnp.any(new_m != m), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    marks, _, inv_rounds = jax.lax.while_loop(
        cond, body, (marks0, jnp.any(marks0), 0)
    )
    d0 = jnp.where(marks, INF, dp)
    d0 = d0.at[jnp.arange(s), sources].set(0)  # re-pin marked sources
    allow = _bf_allow(sources, overloaded)
    d, rounds = _bf_relax(d0, allow, src_e, dst_e, w_rows)
    return d, rounds, inv_rounds


_bf_solver_warm_vw = jax.jit(_bf_warm_vw_core)


# -- destination-tiled 2-D P('batch', 'graph') kernels ----------------------
#
# The row-sharded layouts above keep a full [S, n_pad] distance replica per
# chip; the tiled kernels below keep only a [S/batch, n_pad/graph] tile and
# run under shard_map over both mesh axes. Edges are regrouped by SOURCE
# tile (openr_tpu/parallel/mesh.py:GraphTiling), so every tail read in a
# relaxation round is tile-local; the per-round cross-chip traffic is the
# halo exchange: each device's compact per-destination frontier minima
# (ctr [S_l, h] plus the slot->column map) travel one hop at a time around
# a lax.ppermute ring along 'graph', and every device scatter-mins the
# passing frontier into the columns it owns, dropping the rest. Nothing the
# size of a distance row ever moves.


@shape_contract(
    "tile:[S_l,n_tile]:int32:inf",
    "ctr:[S_l,h]:int32:inf",
    "cols:[h]:int32",
    returns="[S_l,n_tile]:int32:inf",
)
def _tile_fold_min(tile, ctr, cols, me, n_tile):
    """Fold a frontier into the columns this device owns: cols outside
    [me*n_tile, (me+1)*n_tile) map to the out-of-range sentinel and are
    dropped by the scatter (sentinel 1<<30 padding slots included)."""
    local = cols - me * n_tile
    local = jnp.where((local >= 0) & (local < n_tile), local, n_tile)
    return tile.at[:, local].min(ctr, mode="drop")


def _tile_halo_min(ctr, cols, base, me, n_tile, g):
    """The halo exchange: fold every partition's frontier (ctr [S_l, h],
    cols [h]) into `base` [S_l, n_tile], rotating the frontier g-1 hops
    around the 'graph' ring. Returns the folded tile; per hop each device
    forwards only its compact frontier — O(h) per device, never O(n_pad)."""
    perm = [(i, (i + 1) % g) for i in range(g)]
    out = _tile_fold_min(base, ctr, cols, me, n_tile)
    for _ in range(g - 1):
        ctr = jax.lax.ppermute(ctr, "graph", perm)
        cols = jax.lax.ppermute(cols, "graph", perm)
        out = _tile_fold_min(out, ctr, cols, me, n_tile)
    return out


@shape_contract(
    "vals:[S_l,e_tile]:int32:inf",
    "hseg:[e_tile]:int32",
    returns="[S_l,h]:int32:inf",
)
def _tile_seg_min(vals, hseg, h):
    """Per-frontier-slot minima of per-edge values [S_l, e_tile] -> [S_l, h]
    (empty slots clamp to INF; hseg is per-tile dst-sorted, so the sorted
    fast path holds)."""
    out = jax.vmap(
        lambda row: jax.ops.segment_min(
            row, hseg, num_segments=h, indices_are_sorted=True
        )
    )(vals)
    return jnp.minimum(out, INF)


def _tile_d0_allow(sources, overloaded, me, n_tile):
    """Cold initial tile [S_l, n_tile] + the per-source transit mask for
    the columns this device owns (overloaded nodes relay nothing unless
    they are the source itself — same semantics as _bf_allow)."""
    s_l = sources.shape[0]
    offset = me * n_tile
    ov_t = jax.lax.dynamic_slice(overloaded, (offset,), (n_tile,))
    ids = offset + jnp.arange(n_tile, dtype=jnp.int32)
    allow = (~ov_t)[None, :] | (ids[None, :] == sources[:, None])
    local = sources - offset
    local = jnp.where((local >= 0) & (local < n_tile), local, n_tile)
    d0 = jnp.full((s_l, n_tile), INF, dtype=jnp.int32)
    d0 = d0.at[jnp.arange(s_l), local].set(0, mode="drop")
    return d0, allow


@shape_contract(
    "d0:[S_l,n_tile]:int32:inf",
    "allow:[S_l,n_tile]:bool",
    "src_l:[e_tile]:int32",
    "hseg:[e_tile]:int32",
    "w2:[e_tile]:int32:inf",
    "hcols:[h]:int32",
)
def _tile_relax(d0, allow, src_l, hseg, w2, hcols, me, *, g, n_tile, n_pad):
    """Min-plus relaxation of the local tile to the GLOBAL fixpoint.

    Each round relaxes the locally-tailed edges (src_l is tile-local by
    construction) into compact frontier minima and halo-exchanges them;
    convergence is the psum of per-device change flags over both mesh
    axes, so every device leaves the loop in lockstep. Same warm-start
    contract as _sell_relax/_bf_relax: any entrywise upper bound of the
    true distances with the source diagonal pinned to 0 is a valid d0."""
    h = hcols.shape[0]

    def body(state):
        d, _, it = state
        dt = jnp.where(allow, d, INF)
        contrib = jnp.minimum(dt[:, src_l] + w2, INF)  # [S_l, e_tile]
        ctr = _tile_seg_min(contrib, hseg, h)
        new_d = _tile_halo_min(ctr, hcols, d, me, n_tile, g)
        changed = (
            jax.lax.psum(
                jnp.any(new_d != d).astype(jnp.int32), ("batch", "graph")
            )
            > 0
        )
        return new_d, changed, it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < n_pad)

    d, _, rounds = jax.lax.while_loop(cond, body, (d0, jnp.bool_(True), 0))
    return d, rounds


@functools.lru_cache(maxsize=64)
def _tile_solver(key: Tuple, mesh):
    """Cold destination-tiled solve: key = GraphTiling.shape_key() +
    (n_pad,). (sources, src_l, hseg, w2, hcols, overloaded) -> (D, rounds)
    with D sharded P('batch', 'graph') — each device keeps only its
    [S/batch, n_pad/graph] tile."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    g, n_tile, e_tile, h, n_pad = key
    assert mesh.shape["graph"] == g, (dict(mesh.shape), g)

    def solve(sources, src_l, hseg, w2, hcols, overloaded):
        me = jax.lax.axis_index("graph")
        d0, allow = _tile_d0_allow(sources, overloaded, me, n_tile)
        d, rounds = _tile_relax(
            d0, allow, src_l[0], hseg[0], w2[0], hcols[0], me,
            g=g, n_tile=n_tile, n_pad=n_pad,
        )
        return d, rounds

    fn = shard_map(
        solve,
        mesh=mesh,
        in_specs=(
            P("batch"),
            P("graph", None),
            P("graph", None),
            P("graph", None),
            P("graph", None),
            P(),
        ),
        out_specs=(P("batch", "graph"), P()),
        check_rep=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _tile_solver_warm(key: Tuple, mesh):
    """Warm-start incremental solve on the tiled layout, one dispatch per
    LSDB event: (sources, src_l, hseg, w2_new, w2_old, hcols, ov_new,
    ov_old, d_prev) -> (D, rounds, inv_rounds, col_changed, num_changed).

    The invalidation fixpoint is halo-aware: marks cannot be pushed along
    edges directly (a tail's owner does not hold the head's column), so
    the old-DAG membership test runs RECEIVER-side on the same frontier
    machinery as the relaxation. At the old fixpoint every masked tail
    value satisfies dt_old[u] + w_old >= dp[v], so the min over any edge
    subset's candidates equals dp[v] exactly when the subset contains an
    old-DAG edge: each round the devices exchange per-destination minima
    of dt_old[u] + w_old over marked-tail edges and a device marks the
    columns where the received min matches its resident dp. Seeds use the
    same test over the increased-edge set — weight increases derived on
    device from w2_new > w2_old, plus the out-edges of newly-overloaded
    nodes, which is how an overload toggle rides the warm path here too
    (the repair relax then uses the NEW transit mask). Un-overloading
    only adds paths, so the old tile stays a valid upper bound as-is.

    col_changed comes back sharded P('graph') (each device reports its
    own columns, reduced over 'batch'); num_changed is the replicated
    scalar popcount the host reads to size the compacted _delta_extract
    dispatch — the DeltaPath handshake is unchanged by the resharding."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    g, n_tile, e_tile, h, n_pad = key
    assert mesh.shape["graph"] == g, (dict(mesh.shape), g)

    def solve(
        sources, src_l, hseg, w2_new, w2_old, hcols, ov_new, ov_old, d_prev
    ):
        me = jax.lax.axis_index("graph")
        src = src_l[0]
        seg = hseg[0]
        wn = w2_new[0]
        wo = w2_old[0]
        cols = hcols[0]
        s_l = sources.shape[0]
        offset = me * n_tile
        _, allow_old = _tile_d0_allow(sources, ov_old, me, n_tile)
        _, allow_new = _tile_d0_allow(sources, ov_new, me, n_tile)
        dp = d_prev
        dt_old = jnp.where(allow_old, dp, INF)
        # per-edge old-DAG candidates; down edges (w_old == INF) clamp to
        # INF and can never match a finite dp[v]
        cand = jnp.minimum(dt_old[:, src] + wo, INF)  # [S_l, e_tile]
        newly_on = ov_new & ~ov_old  # [n_pad] replicated
        seed_edge = (wn > wo) | newly_on[offset + src]
        inf_tile = jnp.full((s_l, n_tile), INF, dtype=jnp.int32)
        ctr0 = _tile_seg_min(jnp.where(seed_edge[None, :], cand, INF), seg, h)
        recv0 = _tile_halo_min(ctr0, cols, inf_tile, me, n_tile, g)
        marks0 = (recv0 == dp) & (dp < INF)

        def body(state):
            m, _, it = state
            vals = jnp.where(m[:, src], cand, INF)
            ctr = _tile_seg_min(vals, seg, h)
            recv = _tile_halo_min(ctr, cols, inf_tile, me, n_tile, g)
            new_m = m | ((recv == dp) & (dp < INF))
            changed = (
                jax.lax.psum(
                    jnp.any(new_m != m).astype(jnp.int32),
                    ("batch", "graph"),
                )
                > 0
            )
            return new_m, changed, it + 1

        def cond(state):
            _, changed, it = state
            return changed & (it < n_pad)

        # zero seed marks everywhere -> the loop is skipped whole, so
        # decrease-only events pay one seed exchange and nothing more
        any_seed = (
            jax.lax.psum(
                jnp.any(marks0).astype(jnp.int32), ("batch", "graph")
            )
            > 0
        )
        marks, _, inv_rounds = jax.lax.while_loop(
            cond, body, (marks0, any_seed, 0)
        )
        d0 = jnp.where(marks, INF, dp)
        local = sources - offset
        local = jnp.where((local >= 0) & (local < n_tile), local, n_tile)
        d0 = d0.at[jnp.arange(s_l), local].set(0, mode="drop")
        d, rounds = _tile_relax(
            d0, allow_new, src, seg, wn, cols, me,
            g=g, n_tile=n_tile, n_pad=n_pad,
        )
        col_changed = jnp.any(d != dp, axis=0)  # [n_tile] this shard
        col_changed = jax.lax.pmax(col_changed.astype(jnp.int32), "batch") > 0
        num_changed = jax.lax.psum(
            jnp.sum(col_changed.astype(jnp.int32)), "graph"
        )
        return d, rounds, inv_rounds, col_changed, num_changed

    fn = shard_map(
        solve,
        mesh=mesh,
        in_specs=(
            P("batch"),
            P("graph", None),
            P("graph", None),
            P("graph", None),
            P("graph", None),
            P("graph", None),
            P(),
            P(),
            P("batch", "graph"),
        ),
        out_specs=(P("batch", "graph"), P(), P(), P("graph"), P()),
        check_rep=False,
    )
    # d_prev is donated: the caller always replaces its resident handle
    # and the output tile matches its shape and sharding exactly
    return jax.jit(fn, donate_argnums=(8,))


@functools.partial(jax.jit, static_argnames=("cap",))
def _delta_extract(
    col_changed: jnp.ndarray,  # bool [N] device-resident changed-dest mask
    d: jnp.ndarray,  # int32 [S, N] device-resident distance matrix
    nh_rows: jnp.ndarray,  # int32 [L] batch row of each up-link neighbor
    nh_ws: jnp.ndarray,  # int32 [L] metric of each up-link from me
    cap: int,  # static: compacted column capacity (power-of-two bucket)
):
    """Compact the changed destinations and recompute the triangle-condition
    nexthop memberships for just those columns — the O(changes) copy-back
    that replaces the full [S, N] mirror fetch on the warm event path.

    Returns (cols [cap] int32 changed-destination indices, fill = N for
    padding; dcols [S, cap] their distance columns; nh [L, cap] bool: link
    l is an ECMP first hop toward cols[c], the exact _AreaSolve.nh_mask
    formula w(me, n) + D[n, t] == D[me, t]). The caller picks cap =
    _next_bucket(num_changed) so a handful of executables (one per
    power-of-two bucket) serve every event size."""
    n = col_changed.shape[0]
    (cols,) = jnp.nonzero(col_changed, size=cap, fill_value=n)
    safe = jnp.clip(cols, 0, n - 1)
    dcols = d[:, safe]  # [S, cap]
    nh = (nh_ws[:, None] + dcols[nh_rows, :]) == dcols[0][None, :]
    return cols, dcols, nh


@functools.lru_cache(maxsize=64)
def _sell_solver_vw(key: Tuple, mesh=None):
    """Per-row-weights sliced-ELL fixpoint (jitted): the device form of the
    reference's penalized re-solves — KSP's link-ignore runSpf
    (LinkState.cpp:760-789) — on the sliced layout.

    Instead of materializing per-row edge weights host-side ([S, E] ints
    uploaded per call), callers pass the shared bucket weights plus per-
    bucket mask index arrays [Mk, 3] of (row-in-bucket, slot, batch-col)
    positions to pin to INF; out-of-range rows (padding) are dropped. The
    [nk, dk, S] expanded weights are built on device.
    """
    zero_end, starts, shapes = key

    def solve(sources, nbrs, wgs, masks, overloaded):
        s = sources.shape[0]
        wgv = []
        for k, wg_k in enumerate(wgs):
            nk, dk = shapes[k]
            full = jnp.broadcast_to(wg_k[:, :, None], (nk, dk, s))
            m = masks[k]
            full = full.at[m[:, 0], m[:, 1], m[:, 2]].set(INF, mode="drop")
            wgv.append(full)
        return _sell_fixpoint_core(
            sources, nbrs, tuple(wgv), overloaded, zero_end, starts, shapes
        )

    if mesh is None:
        return jax.jit(solve)
    row, repl, out = _mesh_shardings(mesh)
    return jax.jit(
        solve,
        in_shardings=(row, repl, repl, repl, repl),
        out_shardings=out,
    )


@functools.lru_cache(maxsize=64)
def _sell_solver_vw_warm(key: Tuple, mesh=None):
    """Warm per-row-weights sliced-ELL solve: the KSP layer-seeding form.

    (sources, nbrs, wgs, masks, overloaded, d_prev [S, N]) -> D [S, N].
    The mask positions ARE the increased edges (base weight -> INF), so
    the penalized layer-k solve warm-starts from the unpenalized base
    fixpoint d_prev instead of cold-starting from INF: seed invalidation
    marks where a masked edge sits on the base shortest-path DAG (per
    batch column, since each row ignores its own link set), propagate
    them down the base DAG (`_sell_mark_fixpoint`), reset marked entries
    to INF, and relax with the masked per-row weights. Rounds scale with
    the penalized detour radius, not the graph diameter — the KSP
    warm-start carry-over (ROADMAP FatPaths item)."""
    zero_end, starts, shapes = key

    def solve(sources, nbrs, wgs, masks, overloaded, d_prev):
        s = sources.shape[0]
        dp = d_prev.T  # dest-major [N, S]
        marks = jnp.zeros(dp.shape, dtype=jnp.bool_)
        wgv = []
        for k, (nbr_k, wg_k) in enumerate(zip(nbrs, wgs)):
            nk, dk = shapes[k]
            m = masks[k]
            valid = m[:, 0] < (1 << 29)  # padding rows are 1 << 30
            r = jnp.clip(m[:, 0], 0, nk - 1)
            j = jnp.clip(m[:, 1], 0, dk - 1)
            c = jnp.clip(m[:, 2], 0, s - 1)
            u = nbr_k[r, j]  # [M] in-neighbor of each masked edge
            w_old = wg_k[r, j]  # [M] base weight (pre-mask)
            v = starts[k] + r  # [M] global node row of each edge head
            dv = dp[v, c]  # [M]
            cond = (
                valid
                & (dv < INF)
                & (jnp.minimum(dp[u, c] + w_old, INF) == dv)
            )
            marks = marks.at[v, c].max(cond)
            # the masked per-row weights, as in _sell_solver_vw
            full = jnp.broadcast_to(wg_k[:, :, None], (nk, dk, s))
            full = full.at[m[:, 0], m[:, 1], m[:, 2]].set(INF, mode="drop")
            wgv.append(full)
        marks, _ = _sell_mark_fixpoint(
            dp, marks, nbrs, wgs, zero_end, starts, shapes
        )
        d0 = jnp.where(marks, INF, dp)
        d0 = d0.at[sources, jnp.arange(s)].set(0)  # re-pin marked sources
        _, allow = _sell_d0_allow(sources, overloaded)
        d, _ = _sell_relax(
            d0, allow, nbrs, tuple(wgv), zero_end, starts, shapes
        )
        return d.T

    if mesh is None:
        return jax.jit(solve)
    row, repl, out = _mesh_shardings(mesh)
    return jax.jit(
        solve,
        in_shardings=(row, repl, repl, repl, repl, out),
        out_shardings=out,
    )


def sell_fixpoint_masked(
    sell,  # ops.graph.SlicedEll
    sources,  # int32 [S]
    overloaded,  # bool [n_pad]
    mask_positions,  # per batch row: list of edge positions to pin to INF
    device_arrays=None,  # optional (nbrs, wgs, ov) already on device
    mesh=None,  # optional solver mesh: sources sharded over 'batch'
    d_prev=None,  # optional [S, N] base fixpoint: warm-start the solve
) -> jnp.ndarray:
    """Per-row link-ignore solve on the sliced layout.

    mask_positions[i] is an iterable of edge positions (dst-sorted edge
    array indices, e.g. from CompiledGraph.link_edges) whose weight becomes
    INF for batch row i only. Mask arrays are bucket-padded so repeated
    calls with similar mask counts share jitted executables. Pass
    device_arrays (e.g. an _AreaSolve's persistent buffers) to avoid
    re-uploading the layout per call. With d_prev — the UNPENALIZED base
    distance rows for the same sources and weights — the penalized solve
    warm-starts via increase-invalidation instead of relaxing from INF
    (`_sell_solver_vw_warm`): sound because masking only raises weights,
    so the base fixpoint plus mark-reset is a valid upper-bound seed.
    """
    nb = len(sell.nbr)
    per_bucket: list = [[] for _ in range(nb)]
    for col, positions in enumerate(mask_positions):
        for p in positions:
            per_bucket[sell.edge_bucket[p]].append(
                (sell.edge_row[p], sell.edge_slot[p], col)
            )
    masks = []
    for k in range(nb):
        entries = per_bucket[k]
        m_pad = _next_bucket(max(len(entries), 1))
        arr = np.full((m_pad, 3), 1 << 30, dtype=np.int32)  # dropped rows
        if entries:
            arr[: len(entries)] = np.asarray(entries, dtype=np.int32)
        masks.append(jnp.asarray(arr))
    if device_arrays is not None:
        nbrs, wgs, ov = device_arrays
    else:
        nbrs = tuple(jnp.asarray(a) for a in sell.nbr)
        wgs = tuple(jnp.asarray(a) for a in sell.wg)
        ov = jnp.asarray(overloaded)
    if d_prev is not None:
        fn = _sell_solver_vw_warm(sell.shape_key(), mesh)
        with profile_span("spf.ksp_masked_warm"):
            return fn(
                jnp.asarray(sources, dtype=jnp.int32),
                nbrs,
                wgs,
                tuple(masks),
                ov,
                d_prev,
            )
    fn = _sell_solver_vw(sell.shape_key(), mesh)
    with profile_span("spf.ksp_masked"):
        return fn(
            jnp.asarray(sources, dtype=jnp.int32), nbrs, wgs, tuple(masks), ov
        )



def profile_span(name: str):
    """Named `jax.profiler.TraceAnnotation` around a kernel dispatch seam:
    inside an on-demand profiling window (monitor/profiling.py) the
    captured TensorBoard trace shows the dispatch under this label; with
    no profiler active the annotation is a C++-side no-op, cheap enough
    for the serving path."""
    from jax.profiler import TraceAnnotation

    return TraceAnnotation(name)


def sell_fixpoint(
    sell,  # ops.graph.SlicedEll
    sources,  # int32 [S] device or host
    wgs,  # tuple of [nk, dk] weight arrays (device or host)
    overloaded,  # bool [n_pad]
) -> jnp.ndarray:
    """Distance matrix D [S, N] via the sliced-ELL pull relaxation."""
    fn = _sell_solver(sell.shape_key(), None)
    with profile_span("spf.sell_fixpoint"):
        return fn(
            jnp.asarray(sources, dtype=jnp.int32),
            tuple(jnp.asarray(a) for a in sell.nbr),
            tuple(jnp.asarray(a) for a in wgs),
            jnp.asarray(overloaded),
        )


def batched_spf(graph: CompiledGraph, source_rows: np.ndarray) -> jnp.ndarray:
    """Run the batched solve for the given source node indices.

    Dispatches to the sliced-ELL pull kernel when the graph's degree
    profile qualifies (ops.graph._build_sell), else the edge-list
    segment-min form.
    """
    # named fault seam for injected dispatch failures (docs/Robustness.md)
    fault_point("ops.spf.batched_spf", graph)
    if graph.sell is not None:
        return sell_fixpoint(
            graph.sell, source_rows, graph.sell.wg, graph.overloaded
        )
    with profile_span("spf.batched_cold"):
        return _bf_fixpoint(
            jnp.asarray(source_rows, dtype=jnp.int32),
            jnp.asarray(graph.src),
            jnp.asarray(graph.dst),
            jnp.asarray(graph.w),
            jnp.asarray(graph.overloaded),
        )


def batched_spf_vw(
    graph: CompiledGraph, source_rows: np.ndarray, w_rows: np.ndarray,
    mesh=None,
) -> jnp.ndarray:
    """Batched solve with per-row weight vectors (shape [S, e_pad]).

    With a mesh, sources and weight rows shard over 'batch' (S must be a
    multiple of the batch-axis size)."""
    fault_point("ops.spf.batched_spf_vw", graph)
    with profile_span("spf.batched_vw"):
        return _bf_vw_solver(mesh)(
            jnp.asarray(source_rows, dtype=jnp.int32),
            jnp.asarray(graph.src),
            jnp.asarray(graph.dst),
            jnp.asarray(w_rows, dtype=jnp.int32),
            jnp.asarray(graph.overloaded),
        )


@jax.jit
def _ecmp_dag(
    d: jnp.ndarray,  # int32 [N, N] all-pairs distances (row = source)
    src_e: jnp.ndarray,
    dst_e: jnp.ndarray,
    w_e: jnp.ndarray,
    overloaded: jnp.ndarray,
) -> jnp.ndarray:
    """Per-edge shortest-DAG membership: out[e, t] == True iff directed edge
    e = (u -> v) is the first hop of some shortest path u -> t."""
    n = overloaded.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32)
    du = d[src_e]  # [E, N] distances from each edge's source
    dv = d[dst_e]  # [E, N] distances from each edge's destination
    triangle = jnp.minimum(w_e[:, None] + dv, INF) == du
    # v may not relay traffic when overloaded, unless v is the destination
    transit_ok = (~overloaded[dst_e])[:, None] | (
        node_ids[None, :] == dst_e[:, None]
    )
    reachable = du < INF
    return triangle & transit_ok & reachable


def ecmp_dag(graph: CompiledGraph, d: jnp.ndarray) -> jnp.ndarray:
    """First-hop DAG for all-pairs distance matrix d (rows must be indexed by
    node id, i.e. computed with source_rows = arange(n_pad))."""
    return _ecmp_dag(
        d,
        jnp.asarray(graph.src),
        jnp.asarray(graph.dst),
        jnp.asarray(graph.w),
        jnp.asarray(graph.overloaded),
    )


def compile_cache_stats() -> dict:
    """Aggregate executable-cache totals across the jitted solver factories.

    Each factory's lru_cache is keyed by (SlicedEll.shape_key(), mesh), so a
    miss is one new trace+XLA compile for a new bucket structure and a hit
    is an executable reused across LSDB events — the shape-bucketing design
    working as intended. TpuSpfSolver surfaces these as the
    decision.spf.compile_cache_{hits,misses} gauges (process-wide: the
    caches are module-level, shared by every solver instance)."""
    hits = misses = entries = 0
    for fn in (
        _sell_solver_raw,
        _sell_solver,
        _sell_solver_counted,
        _sell_solver_patched,
        _sell_solver_warm,
        _sell_solver_vw,
        _sell_solver_vw_warm,
        _bf_vw_solver,
        _tile_solver,
        _tile_solver_warm,
    ):
        info = fn.cache_info()
        hits += info.hits
        misses += info.misses
        entries += info.currsize
    return {"hits": hits, "misses": misses, "entries": entries}


def compile_cache_memory() -> dict:
    """Device-memory ledger external source (monitor/memledger.py): the
    compiled executables are device-resident state too, but they live
    behind module-level lru_caches the ledger does not allocate — so they
    ride snapshots as an informational row (entry counts per family +
    the APSP closer's caches) outside the exact-accounting invariant."""
    from openr_tpu.apsp import apsp_compile_cache_stats

    stats = compile_cache_stats()
    fw = apsp_compile_cache_stats()
    return {
        "structure": "compile_cache",
        "spf_entries": stats["entries"],
        "apsp_entries": fw["entries"],
        "entries": stats["entries"] + fw["entries"],
    }


