"""JAX/XLA ops for the batched min-plus SPF solver.

The LSDB graph is compiled to padded edge-list arrays (graph.py); shortest
paths for a batch of sources run as Bellman-Ford relaxation rounds with
segment-min scatter (spf.py), converging in at most graph-diameter rounds; the
ECMP first-hop DAG falls out of the triangle condition on the distance matrix.
This replaces the reference's per-source serial Dijkstra
(openr/decision/LinkState.cpp:806-880) with one data-parallel computation.
"""

from openr_tpu.ops.graph import INF, CompiledGraph, compile_graph
from openr_tpu.ops.spf import batched_spf, batched_spf_vw, ecmp_dag

__all__ = [
    "INF",
    "CompiledGraph",
    "compile_graph",
    "batched_spf",
    "batched_spf_vw",
    "ecmp_dag",
]
