"""LSDB graph → padded device arrays.

The dynamic string-keyed LinkState graph becomes static-shaped int32 arrays:
directed edge list (src, dst, w) sorted by destination for sorted segment-min,
plus a per-node overload mask. Node and edge counts are padded to power-of-two
buckets so that incremental topology changes (single link flap) reuse the same
jit-compiled executable instead of recompiling (SURVEY.md §7 "dynamic graph,
static shapes").

Reference semantics compiled in:
  - only up links participate (LinkState.cpp:844 skips !link->isUp())
  - per-direction metrics (Link::getMetricFromNode)
  - overloaded nodes carry no transit traffic (LinkState.cpp:829-836); the
    mask is applied per-source inside the solver since a source's own edges
    remain usable
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from openr_tpu.lsdb.link_state import LinkState
from openr_tpu.lsdb.link_state import Link

# int32-safe infinity: INF + max edge weight must not overflow int32
INF = 1 << 29


def _next_bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class CompiledGraph:
    """Static-shaped arrays for one LinkState snapshot."""

    names: List[str]  # index -> node name (real nodes only)
    node_index: Dict[str, int]
    n: int  # real node count
    e: int  # real directed edge count
    n_pad: int
    e_pad: int
    src: np.ndarray  # int32 [e_pad], padded entries point at 0 with INF w
    dst: np.ndarray  # int32 [e_pad], sorted ascending (real entries)
    w: np.ndarray  # int32 [e_pad]
    overloaded: np.ndarray  # bool [n_pad]
    # Link object -> its two directed-edge positions in the padded arrays
    # (forward = n1->n2, reverse = n2->n1); lets callers mask individual
    # links out of a solve (KSP link-ignore semantics, LinkState.cpp:760-789)
    link_edges: Dict[Link, Tuple[int, int]] = field(default_factory=dict)


def compile_graph(link_state: LinkState) -> CompiledGraph:
    names = sorted(
        set(link_state.get_adjacency_databases().keys())
        | {n for link in link_state.all_links for n in (link.n1, link.n2)}
    )
    node_index = {name: i for i, name in enumerate(names)}
    n = len(names)

    srcs: List[int] = []
    dsts: List[int] = []
    ws: List[int] = []
    up_links: List[Link] = []
    for link in sorted(link_state.all_links):
        if not link.is_up():
            continue
        up_links.append(link)
        i1, i2 = node_index[link.n1], node_index[link.n2]
        srcs.append(i1)
        dsts.append(i2)
        ws.append(link.metric_from_node(link.n1))
        srcs.append(i2)
        dsts.append(i1)
        ws.append(link.metric_from_node(link.n2))
    e = len(srcs)

    n_pad = _next_bucket(max(n, 1))
    e_pad = _next_bucket(max(e, 1))

    src = np.zeros(e_pad, dtype=np.int32)
    dst = np.zeros(e_pad, dtype=np.int32)
    w = np.full(e_pad, INF, dtype=np.int32)
    link_edges: Dict[Link, Tuple[int, int]] = {}
    if e:
        order = np.argsort(np.asarray(dsts, dtype=np.int32), kind="stable")
        src[:e] = np.asarray(srcs, dtype=np.int32)[order]
        dst[:e] = np.asarray(dsts, dtype=np.int32)[order]
        w[:e] = np.asarray(ws, dtype=np.int32)[order]
        # padded edges must not break sorted-segment assumptions: point them
        # at the last real destination
        dst[e:] = dst[e - 1]
        # pre-sort edge index -> post-sort position
        pos = np.empty(e, dtype=np.int64)
        pos[order] = np.arange(e)
        for i, link in enumerate(up_links):
            link_edges[link] = (int(pos[2 * i]), int(pos[2 * i + 1]))

    overloaded = np.zeros(n_pad, dtype=bool)
    for i, name in enumerate(names):
        overloaded[i] = link_state.is_node_overloaded(name)

    return CompiledGraph(
        names=names,
        node_index=node_index,
        n=n,
        e=e,
        n_pad=n_pad,
        e_pad=e_pad,
        src=src,
        dst=dst,
        w=w,
        overloaded=overloaded,
        link_edges=link_edges,
    )
