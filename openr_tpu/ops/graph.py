"""LSDB graph → padded device arrays.

The dynamic string-keyed LinkState graph becomes static-shaped int32 arrays:
directed edge list (src, dst, w) sorted by destination for sorted segment-min,
plus a per-node overload mask. Node and edge counts are padded to power-of-two
buckets so that incremental topology changes (single link flap) reuse the same
jit-compiled executable instead of recompiling (SURVEY.md §7 "dynamic graph,
static shapes").

Reference semantics compiled in:
  - only up links participate (LinkState.cpp:844 skips !link->isUp())
  - per-direction metrics (Link::getMetricFromNode)
  - overloaded nodes carry no transit traffic (LinkState.cpp:829-836); the
    mask is applied per-source inside the solver since a source's own edges
    remain usable
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from openr_tpu.lsdb.link_state import LinkState
from openr_tpu.lsdb.link_state import Link

# int32-safe infinity: INF + max edge weight must not overflow int32
INF = 1 << 29


def _next_bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class CompiledGraph:
    """Static-shaped arrays for one LinkState snapshot.

    Down links are present in the arrays with INF weight (they never relax),
    so link flaps and metric changes are pure weight patches — the arrays
    keep their shape and identity and the jitted solver never recompiles.
    """

    names: List[str]  # index -> node name (real nodes only)
    node_index: Dict[str, int]
    n: int  # real node count
    e: int  # real directed edge count (up and down links)
    n_pad: int
    e_pad: int
    src: np.ndarray  # int32 [e_pad], padded entries point at 0 with INF w
    dst: np.ndarray  # int32 [e_pad], sorted ascending (real entries)
    w: np.ndarray  # int32 [e_pad]; INF for down links and padding
    overloaded: np.ndarray  # bool [n_pad]
    # Link object -> its two directed-edge positions in the padded arrays
    # (forward = n1->n2, reverse = n2->n1); lets callers mask individual
    # links out of a solve (KSP link-ignore semantics, LinkState.cpp:760-789)
    link_edges: Dict[Link, Tuple[int, int]] = field(default_factory=dict)
    # snapshot markers for incremental refresh (refresh_graph)
    version: int = -1  # LinkState.version at compile time
    log_pos: int = 0  # LinkState.graph_log_pos at compile time
    # ELL (padded per-destination in-neighbor lists) "pull" layout — the
    # fast path for bounded-degree graphs: relaxation becomes max_in_degree
    # row-gathers + mins instead of a gather/scatter over the edge list
    # (measured ~6x faster per round on TPU for degree-4 grids). None when
    # the degree spread makes ELL wasteful (e.g. Clos spines).
    nbr: Optional[np.ndarray] = None  # int32 [n_pad, md] in-neighbor ids
    wg: Optional[np.ndarray] = None  # int32 [n_pad, md]; INF padding
    # edge position i in src/dst/w -> its (row, slot) in nbr/wg, for
    # incremental weight patches
    ell_row: Optional[np.ndarray] = None  # int32 [e_pad]
    ell_slot: Optional[np.ndarray] = None  # int32 [e_pad]


def compile_graph(link_state: LinkState) -> CompiledGraph:
    names = sorted(
        set(link_state.get_adjacency_databases().keys())
        | {n for link in link_state.all_links for n in (link.n1, link.n2)}
    )
    node_index = {name: i for i, name in enumerate(names)}
    n = len(names)

    srcs: List[int] = []
    dsts: List[int] = []
    ws: List[int] = []
    links: List[Link] = []
    for link in sorted(link_state.all_links):
        # down links stay in the arrays at INF weight (LinkState.cpp:844
        # semantics — they never relax) so a flap is a weight patch, not a
        # structural rebuild
        up = link.is_up()
        links.append(link)
        i1, i2 = node_index[link.n1], node_index[link.n2]
        srcs.append(i1)
        dsts.append(i2)
        ws.append(link.metric_from_node(link.n1) if up else INF)
        srcs.append(i2)
        dsts.append(i1)
        ws.append(link.metric_from_node(link.n2) if up else INF)
    e = len(srcs)

    n_pad = _next_bucket(max(n, 1))
    e_pad = _next_bucket(max(e, 1))

    src = np.zeros(e_pad, dtype=np.int32)
    dst = np.zeros(e_pad, dtype=np.int32)
    w = np.full(e_pad, INF, dtype=np.int32)
    link_edges: Dict[Link, Tuple[int, int]] = {}
    if e:
        order = np.argsort(np.asarray(dsts, dtype=np.int32), kind="stable")
        src[:e] = np.asarray(srcs, dtype=np.int32)[order]
        dst[:e] = np.asarray(dsts, dtype=np.int32)[order]
        w[:e] = np.asarray(ws, dtype=np.int32)[order]
        # padded edges must not break sorted-segment assumptions: point them
        # at the last real destination
        dst[e:] = dst[e - 1]
        # pre-sort edge index -> post-sort position
        pos = np.empty(e, dtype=np.int64)
        pos[order] = np.arange(e)
        for i, link in enumerate(links):
            link_edges[link] = (int(pos[2 * i]), int(pos[2 * i + 1]))

    overloaded = np.zeros(n_pad, dtype=bool)
    for i, name in enumerate(names):
        overloaded[i] = link_state.is_node_overloaded(name)

    graph = CompiledGraph(
        names=names,
        node_index=node_index,
        n=n,
        e=e,
        n_pad=n_pad,
        e_pad=e_pad,
        src=src,
        dst=dst,
        w=w,
        overloaded=overloaded,
        link_edges=link_edges,
        version=link_state.version,
        log_pos=link_state.graph_log_pos,
    )
    _build_ell(graph)
    return graph


# ELL is only worthwhile while md gathers of the full distance matrix beat
# one edge-list gather+scatter; cap the wasted work at 4x and bound md
_ELL_WASTE_CAP = 4
_ELL_MAX_DEGREE = 128


def _build_ell(graph: CompiledGraph) -> None:
    """Derive the padded in-neighbor (ELL) layout from the edge arrays.

    Only real edges participate (array-padding edges are permanently INF and
    never patched); down links carry INF in wg and never relax, keeping
    slots stable across flaps."""
    n_pad, e = graph.n_pad, graph.e
    if e == 0:
        graph.nbr = graph.wg = graph.ell_row = graph.ell_slot = None
        return
    dst = graph.dst[:e]
    # per-destination slot index: dst is sorted, so slot = i - segment_start
    counts = np.bincount(dst, minlength=n_pad)
    md = int(counts.max())
    if md > _ELL_MAX_DEGREE or md * n_pad > _ELL_WASTE_CAP * graph.e_pad:
        graph.nbr = graph.wg = graph.ell_row = graph.ell_slot = None
        return
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot = np.arange(e, dtype=np.int64) - starts[dst]
    nbr = np.zeros((n_pad, md), dtype=np.int32)
    wg = np.full((n_pad, md), INF, dtype=np.int32)
    nbr[dst, slot] = graph.src[:e]
    wg[dst, slot] = graph.w[:e]
    graph.nbr = nbr
    graph.wg = wg
    graph.ell_row = dst.astype(np.int32)
    graph.ell_slot = slot.astype(np.int32)


def refresh_graph(graph: CompiledGraph, link_state: LinkState) -> CompiledGraph:
    """Bring a compiled snapshot up to date with its LinkState.

    Replays the LinkState graph changelog since the snapshot: pure
    weight/overload changes (link flap, metric change, drain) patch copies of
    the w/overloaded arrays in place — same shapes, no recompilation and no
    O(E) Python rebuild; structural changes (link/node add/remove) or a
    dropped changelog fall back to a full compile_graph. This is the
    single-link-flap incremental event path (BASELINE.md config 2)."""
    if graph.version == link_state.version:
        return graph
    changes = link_state.graph_changes_since(graph.log_pos)
    if changes is None or any(kind == "structure" for kind, _ in changes):
        return compile_graph(link_state)

    w = graph.w.copy()
    wg = graph.wg.copy() if graph.wg is not None else None
    overloaded = graph.overloaded.copy()
    for kind, obj in changes:
        if kind == "link":
            pos = graph.link_edges.get(obj)
            if pos is None:  # changelog raced a structural entry we missed
                return compile_graph(link_state)
            up = obj.is_up()
            for p, metric in (
                (pos[0], obj.metric_from_node(obj.n1)),
                (pos[1], obj.metric_from_node(obj.n2)),
            ):
                w[p] = metric if up else INF
                if wg is not None:
                    wg[graph.ell_row[p], graph.ell_slot[p]] = w[p]
        else:  # "node"
            i = graph.node_index.get(obj)
            if i is None:
                return compile_graph(link_state)
            overloaded[i] = link_state.is_node_overloaded(obj)

    return CompiledGraph(
        names=graph.names,
        node_index=graph.node_index,
        n=graph.n,
        e=graph.e,
        n_pad=graph.n_pad,
        e_pad=graph.e_pad,
        src=graph.src,
        dst=graph.dst,
        w=w,
        overloaded=overloaded,
        link_edges=graph.link_edges,
        version=link_state.version,
        log_pos=link_state.graph_log_pos,
        nbr=graph.nbr,
        wg=wg,
        ell_row=graph.ell_row,
        ell_slot=graph.ell_slot,
    )
