"""LSDB graph → padded device arrays.

The dynamic string-keyed LinkState graph becomes static-shaped int32 arrays:
directed edge list (src, dst, w) sorted by destination, plus a per-node
overload mask. Node and edge counts are padded to power-of-two buckets so
that incremental topology changes (single link flap) reuse the same
jit-compiled executable instead of recompiling (SURVEY.md §7 "dynamic graph,
static shapes").

Node ids are assigned by ascending in-degree ("sliced-ELL" renumbering): the
relaxation kernel can then process nodes in contiguous equal-degree slices,
each slice being pure row-gathers + fused vector mins with zero scatter and
near-zero slot padding (openr_tpu/ops/spf.py:_bf_fixpoint_sell). Measured
~1.7x faster than the edge-list gather/segment-min form on a 100k-node WAN
and strictly generalizes the uniform-degree ELL layout it replaces.

Reference semantics compiled in:
  - only up links participate (LinkState.cpp:844 skips !link->isUp())
  - per-direction metrics (Link::getMetricFromNode)
  - overloaded nodes carry no transit traffic (LinkState.cpp:829-836); the
    mask is applied per-source inside the solver since a source's own edges
    remain usable
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from openr_tpu.lsdb.link_state import LinkState
from openr_tpu.lsdb.link_state import Link

# int32-safe infinity: INF + max edge weight must not overflow int32
INF = 1 << 29


def _next_bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class SlicedEll:
    """Degree-bucketed pull layout (node ids pre-sorted by in-degree).

    Rows [0, zero_end) have no in-edges (isolated nodes); row range k is
    [starts[k], starts[k] + nbr[k].shape[0]) and relaxes via dk =
    nbr[k].shape[1] row-gathers; rows [starts[-1] + nbr[-1].shape[0], n_pad)
    are array padding. Degree classes merge adjacent in-degrees when the
    slot padding stays under _SELL_WASTE_FRAC of the edge count.
    """

    zero_end: int
    starts: Tuple[int, ...]
    nbr: Tuple[np.ndarray, ...]  # int32 [nk, dk] in-neighbor ids
    wg: Tuple[np.ndarray, ...]  # int32 [nk, dk]; INF for slot padding
    # edge position p in the dst-sorted arrays -> its (bucket, row-within-
    # bucket, slot) for incremental weight patches
    edge_bucket: np.ndarray  # int32 [e]
    edge_row: np.ndarray  # int32 [e]
    edge_slot: np.ndarray  # int32 [e]

    def shape_key(self) -> Tuple:
        """Static structure key: two graphs with equal keys share jitted
        solver executables (weight patches never change it)."""
        return (
            self.zero_end,
            self.starts,
            tuple(a.shape for a in self.nbr),
        )

    def patched_wg(self, w_edges: np.ndarray) -> Tuple[np.ndarray, ...]:
        """Fresh wg bucket arrays carrying `w_edges` (length e, dst-sorted
        edge order) — the weight-variant path for benches/KSP rows."""
        out = [a.copy() for a in self.wg]
        for k in range(len(out)):
            sel = self.edge_bucket == k
            out[k][self.edge_row[sel], self.edge_slot[sel]] = w_edges[sel]
        return tuple(out)


@dataclass
class CompiledGraph:
    """Static-shaped arrays for one LinkState snapshot.

    Down links are present in the arrays with INF weight (they never relax),
    so link flaps and metric changes are pure weight patches — the arrays
    keep their shape and identity and the jitted solver never recompiles.
    """

    names: List[str]  # index -> node name (real nodes only)
    node_index: Dict[str, int]
    n: int  # real node count
    e: int  # real directed edge count (up and down links)
    n_pad: int
    e_pad: int
    src: np.ndarray  # int32 [e_pad], padded entries point at 0 with INF w
    dst: np.ndarray  # int32 [e_pad], sorted ascending (real entries)
    w: np.ndarray  # int32 [e_pad]; INF for down links and padding
    overloaded: np.ndarray  # bool [n_pad]
    # Link object -> its two directed-edge positions in the padded arrays
    # (forward = n1->n2, reverse = n2->n1); lets callers mask individual
    # links out of a solve (KSP link-ignore semantics, LinkState.cpp:760-789)
    link_edges: Dict[Link, Tuple[int, int]] = field(default_factory=dict)
    # snapshot markers for incremental refresh (refresh_graph)
    version: int = -1  # LinkState.version at compile time
    log_pos: int = 0  # LinkState.graph_log_pos at compile time
    # sliced-ELL pull layout; None when the degree profile disqualifies it
    # (_SELL_UNROLL_CAP) and the edge-list segment-min form is used instead
    sell: Optional[SlicedEll] = None
    # provenance of a weight-patch refresh: the version this graph was
    # patched FROM and the edge positions whose weights differ — lets the
    # device-buffer layer skip its O(E) diff when its snapshot matches
    # parent_version. None/-2 for full builds.
    parent_version: int = -2
    changed_edges: Optional[np.ndarray] = None


# Degree-class merging: adjacent in-degrees merge while the extra padded
# slots stay under this fraction of the real edge count; the unroll cap
# bounds trace/compile cost (sum of class degrees = relaxation ops per
# round), beyond it the edge-list form wins anyway.
_SELL_WASTE_FRAC = 0.25
_SELL_UNROLL_CAP = 1024


def _build_sell(
    dst_sorted: np.ndarray,  # int32 [e] (real edges, ids ascending by degree)
    src_sorted: np.ndarray,
    w_sorted: np.ndarray,
    n: int,
    indeg: np.ndarray,  # int32 [n] in-degree per (renumbered) node id
) -> Optional[SlicedEll]:
    e = len(dst_sorted)
    if e == 0:
        return None
    zero_end = int(np.searchsorted(indeg, 1))
    # unique degrees ascending + node counts (ids are degree-sorted)
    degs, counts = np.unique(indeg[zero_end:], return_counts=True)

    # merge adjacent degrees into classes under the waste budget
    classes: List[Tuple[int, int]] = []  # (class_degree, node_count)
    waste_budget = _SELL_WASTE_FRAC * e
    cum_nodes = cum_edges = 0
    start_i = 0
    for i, (d, c) in enumerate(zip(degs, counts)):
        if i > start_i and cum_nodes * int(d) - cum_edges > waste_budget:
            classes.append((int(degs[i - 1]), cum_nodes))
            start_i = i
            cum_nodes = cum_edges = 0
        cum_nodes += int(c)
        cum_edges += int(c) * int(d)
    classes.append((int(degs[-1]), cum_nodes))
    if sum(d for d, _ in classes) > _SELL_UNROLL_CAP:
        return None

    starts: List[int] = []
    nbrs: List[np.ndarray] = []
    wgs: List[np.ndarray] = []
    edge_bucket = np.empty(e, dtype=np.int32)
    edge_row = np.empty(e, dtype=np.int32)
    edge_slot = np.empty(e, dtype=np.int32)

    csr_starts = np.concatenate([[0], np.cumsum(indeg)])
    row_lo = zero_end
    for k, (dk, nk) in enumerate(classes):
        row_hi = row_lo + nk
        lo_e, hi_e = int(csr_starts[row_lo]), int(csr_starts[row_hi])
        nbr_k = np.zeros((nk, dk), dtype=np.int32)
        wg_k = np.full((nk, dk), INF, dtype=np.int32)
        rows = dst_sorted[lo_e:hi_e] - row_lo
        slots = np.arange(lo_e, hi_e) - csr_starts[dst_sorted[lo_e:hi_e]]
        nbr_k[rows, slots] = src_sorted[lo_e:hi_e]
        wg_k[rows, slots] = w_sorted[lo_e:hi_e]
        edge_bucket[lo_e:hi_e] = k
        edge_row[lo_e:hi_e] = rows
        edge_slot[lo_e:hi_e] = slots
        starts.append(row_lo)
        nbrs.append(nbr_k)
        wgs.append(wg_k)
        row_lo = row_hi

    return SlicedEll(
        zero_end=zero_end,
        starts=tuple(starts),
        nbr=tuple(nbrs),
        wg=tuple(wgs),
        edge_bucket=edge_bucket,
        edge_row=edge_row,
        edge_slot=edge_slot,
    )


def _compile_arrays(
    names_sorted: List[str],
    srcs: np.ndarray,  # int32 [e] preliminary ids (sorted-name order)
    dsts: np.ndarray,
    ws: np.ndarray,
    overloaded_by_prelim: np.ndarray,  # bool [n]
    version: int = -1,
    log_pos: int = 0,
) -> Tuple[CompiledGraph, np.ndarray]:
    """Shared core: renumber nodes by in-degree, sort edges by destination,
    build the sliced-ELL layout. Returns (graph, pos) where pos[i] is the
    final array position of input edge i."""
    n = len(names_sorted)
    e = len(srcs)
    n_pad = _next_bucket(max(n, 1))
    e_pad = _next_bucket(max(e, 1))

    indeg_prelim = np.bincount(dsts, minlength=n) if e else np.zeros(n, int)
    order_nodes = np.argsort(indeg_prelim, kind="stable")
    perm = np.empty(n, dtype=np.int32)
    perm[order_nodes] = np.arange(n, dtype=np.int32)
    names = [names_sorted[i] for i in order_nodes]
    node_index = {name: i for i, name in enumerate(names)}
    indeg = indeg_prelim[order_nodes].astype(np.int32)

    src = np.zeros(e_pad, dtype=np.int32)
    dst = np.zeros(e_pad, dtype=np.int32)
    w = np.full(e_pad, INF, dtype=np.int32)
    pos = np.empty(e, dtype=np.int64)
    sell = None
    if e:
        psrc = perm[srcs]
        pdst = perm[dsts]
        order = np.argsort(pdst, kind="stable")
        src[:e] = psrc[order]
        dst[:e] = pdst[order]
        w[:e] = np.asarray(ws, dtype=np.int32)[order]
        # padded edges must not break sorted-segment assumptions: point them
        # at the last real destination
        dst[e:] = dst[e - 1]
        pos[order] = np.arange(e)
        sell = _build_sell(dst[:e], src[:e], w[:e], n, indeg)

    overloaded = np.zeros(n_pad, dtype=bool)
    overloaded[:n] = overloaded_by_prelim[order_nodes]

    graph = CompiledGraph(
        names=names,
        node_index=node_index,
        n=n,
        e=e,
        n_pad=n_pad,
        e_pad=e_pad,
        src=src,
        dst=dst,
        w=w,
        overloaded=overloaded,
        version=version,
        log_pos=log_pos,
        sell=sell,
    )
    return graph, pos


def compile_graph(link_state: LinkState) -> CompiledGraph:
    names_sorted = sorted(
        set(link_state.get_adjacency_databases().keys())
        | {n for link in link_state.all_links for n in (link.n1, link.n2)}
    )
    prelim_index = {name: i for i, name in enumerate(names_sorted)}

    srcs: List[int] = []
    dsts: List[int] = []
    ws: List[int] = []
    links: List[Link] = []
    for link in sorted(link_state.all_links):
        # down links stay in the arrays at INF weight (LinkState.cpp:844
        # semantics — they never relax) so a flap is a weight patch, not a
        # structural rebuild
        up = link.is_up()
        links.append(link)
        i1, i2 = prelim_index[link.n1], prelim_index[link.n2]
        srcs.append(i1)
        dsts.append(i2)
        ws.append(link.metric_from_node(link.n1) if up else INF)
        srcs.append(i2)
        dsts.append(i1)
        ws.append(link.metric_from_node(link.n2) if up else INF)

    overloaded = np.array(
        [link_state.is_node_overloaded(name) for name in names_sorted],
        dtype=bool,
    )
    graph, pos = _compile_arrays(
        names_sorted,
        np.asarray(srcs, dtype=np.int32),
        np.asarray(dsts, dtype=np.int32),
        np.asarray(ws, dtype=np.int32),
        overloaded,
        version=link_state.version,
        log_pos=link_state.graph_log_pos,
    )
    for i, link in enumerate(links):
        graph.link_edges[link] = (int(pos[2 * i]), int(pos[2 * i + 1]))
    return graph


def compile_edges(
    edges: Sequence[Tuple[str, str, int]],
    overloaded_nodes: Optional[set] = None,
) -> CompiledGraph:
    """Edge list -> CompiledGraph, numpy-vectorized: the fast path for
    synthetic benchmark topologies where building a LinkState (a python
    object graph) would dominate setup time at 100k+ nodes. No link_edges
    mapping and no refresh support (version stays -1)."""
    names_sorted = sorted({n for a, b, _ in edges for n in (a, b)})
    prelim_index = {name: i for i, name in enumerate(names_sorted)}
    a = np.fromiter((prelim_index[x] for x, _, _ in edges), np.int32)
    b = np.fromiter((prelim_index[y] for _, y, _ in edges), np.int32)
    m = np.fromiter((wt for _, _, wt in edges), np.int32)
    overloaded = np.zeros(len(names_sorted), dtype=bool)
    for name in overloaded_nodes or ():
        overloaded[prelim_index[name]] = True
    graph, _ = _compile_arrays(
        names_sorted,
        np.concatenate([a, b]),
        np.concatenate([b, a]),
        np.concatenate([m, m]),
        overloaded,
    )
    return graph


def refresh_graph(graph: CompiledGraph, link_state: LinkState) -> CompiledGraph:
    """Bring a compiled snapshot up to date with its LinkState.

    Replays the LinkState graph changelog since the snapshot: pure
    weight/overload changes (link flap, metric change, drain) patch copies of
    the w/overloaded arrays in place — same shapes, no recompilation and no
    O(E) Python rebuild; structural changes (link/node add/remove) or a
    dropped changelog fall back to a full compile_graph. This is the
    single-link-flap incremental event path (BASELINE.md config 2)."""
    if graph.version == link_state.version:
        return graph
    changes = link_state.graph_changes_since(graph.log_pos)
    if changes is None or any(kind == "structure" for kind, _ in changes):
        return compile_graph(link_state)

    w = graph.w.copy()
    sell = graph.sell
    wgs = [a.copy() for a in sell.wg] if sell is not None else None
    overloaded = graph.overloaded.copy()
    touched: List[int] = []
    for kind, obj in changes:
        if kind == "link":
            pos = graph.link_edges.get(obj)
            if pos is None:  # changelog raced a structural entry we missed
                return compile_graph(link_state)
            up = obj.is_up()
            for p, metric in (
                (pos[0], obj.metric_from_node(obj.n1)),
                (pos[1], obj.metric_from_node(obj.n2)),
            ):
                w[p] = metric if up else INF
                touched.append(p)
                if wgs is not None:
                    wgs[sell.edge_bucket[p]][
                        sell.edge_row[p], sell.edge_slot[p]
                    ] = w[p]
        else:  # "node"
            i = graph.node_index.get(obj)
            if i is None:
                return compile_graph(link_state)
            overloaded[i] = link_state.is_node_overloaded(obj)

    new_sell = None
    if sell is not None:
        new_sell = SlicedEll(
            zero_end=sell.zero_end,
            starts=sell.starts,
            nbr=sell.nbr,
            wg=tuple(wgs),
            edge_bucket=sell.edge_bucket,
            edge_row=sell.edge_row,
            edge_slot=sell.edge_slot,
        )
    return CompiledGraph(
        names=graph.names,
        node_index=graph.node_index,
        n=graph.n,
        e=graph.e,
        n_pad=graph.n_pad,
        e_pad=graph.e_pad,
        src=graph.src,
        dst=graph.dst,
        w=w,
        overloaded=overloaded,
        link_edges=graph.link_edges,
        version=link_state.version,
        log_pos=link_state.graph_log_pos,
        sell=new_sell,
        parent_version=graph.version,
        changed_edges=np.unique(np.asarray(touched, dtype=np.int64)),
    )
