"""Differentiable traffic engineering over the live LSDB.

A new Decision workload (ROADMAP "differentiable TE"): softmin-relaxed
shortest paths turn link weights into optimizable parameters, a manual
Adam loop with temperature annealing descends the softmax-relaxed
max-link-utilization over a batch of demand scenarios, and the TE service
reports proposed integer weight changes scored under exact hard-SPF ECMP
routing — supervised by the solver fault domain, surfaced via ctrl
`runTeOptimize` / `breeze decision te-optimize`.
"""

from openr_tpu.te.objective import (
    hard_distances,
    hard_max_util,
    hard_utilization,
    soft_mlu,
    soft_utilization,
    softmin_distances,
    te_edge_arrays,
)
from openr_tpu.te.optimizer import TeOptConfig, TeOptResult, optimize_weights
from openr_tpu.te.scenarios import (
    build_demand_scenarios,
    congested_clos_fixture,
    uniform_demand_spec,
)
from openr_tpu.te.service import TeService

__all__ = [
    "TeOptConfig",
    "TeOptResult",
    "TeService",
    "build_demand_scenarios",
    "congested_clos_fixture",
    "hard_distances",
    "hard_max_util",
    "hard_utilization",
    "optimize_weights",
    "soft_mlu",
    "soft_utilization",
    "softmin_distances",
    "te_edge_arrays",
    "uniform_demand_spec",
]
