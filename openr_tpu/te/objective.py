"""Differentiable routing core: softmin-relaxed SPF over the edge list.

The solver stack computes hard shortest paths as a min-plus fixpoint
(ops/spf.py). This module relaxes the same recursion with a temperature
parameter so the whole routing function becomes differentiable in the edge
weights — the gradient-descent traffic-engineering formulation of "Fast
Traffic Engineering by Gradient Descent with Learned Differentiable
Routing" (PAPERS.md, arXiv:2209.10380), grafted onto this repo's
compiled-graph arrays instead of a learned GNN:

  - **softmin distances** (`softmin_distances`): replace the inner `min`
    of the Bellman-Ford recursion D[v, t] <- min(D[v, t], min over edges
    (v->u): w + D[u, t]) with softmin_tau(x) = -tau * log(sum exp(-x /
    tau)) across v's out-edges (the incumbent folds in with a hard min —
    see `_softmin_fixpoint_core`). As tau -> 0 this converges to the hard
    SPF distances (the annealing
    differential suite in tests/test_te_objective.py pins this against the
    solver/cpu.py oracle); at tau > 0 every candidate path contributes,
    which is exactly what gives the objective a nonzero gradient through
    alternative paths a hard argmin would ignore.
  - **soft traffic splitting** (`soft_utilization`): at each node, traffic
    toward destination t splits over out-edges by a softmax of the negated
    triangle gap (w(u,v) + D[v, t] - D[u, t]) / tau — the relaxation of the
    ECMP first-hop DAG membership test (`ops/spf.py:_ecmp_dag`). Flows
    propagate for a fixed number of rounds (paths are <= n-1 hops), giving
    per-link utilizations against per-edge capacities.
  - **soft max-link-utilization**: tau_obj * logsumexp(util / tau_obj), the
    softmax relaxation of the TE objective max_e util[e].

The hard counterparts (`hard_distances`, `hard_utilization`,
`hard_max_util`) evaluate candidate integer weight vectors under exact SPF
+ fractional ECMP splitting — the acceptance metric the optimizer's
rounded iterates are scored with. They run host-side in numpy and are
never traced.

Relaxation rounds are a static argument (scan of fixed length): reverse-
mode autodiff cannot differentiate through `lax.while_loop`, so unlike the
hard solver the soft fixpoint runs a bounded unroll instead of iterating
to convergence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.ops.graph import INF, CompiledGraph
from openr_tpu.utils.shape_contract import shape_contract

# float-domain "unreachable": softmin arithmetic needs a finite sentinel
# (exp(-INF/tau) underflows fine, but INF - INF poisons gradients)
F_INF = 1.0e9


def te_edge_arrays(graph: CompiledGraph):
    """(src, dst, w0, up) real-edge arrays for the TE relaxation.

    Down links (weight INF in the compiled arrays) stay in the edge list
    with up=False so the optimizer's weight vector keeps the compiled
    graph's edge positions — proposed changes map back to Link objects via
    CompiledGraph.link_edges without index translation."""
    e = graph.e
    src = graph.src[:e].astype(np.int32)
    dst = graph.dst[:e].astype(np.int32)
    up = graph.w[:e] < INF
    w0 = np.where(up, graph.w[:e], 1).astype(np.float32)
    return src, dst, w0, up


@shape_contract("seg:[E]:int32")
def _segment_softmin(x, seg, n, tau):
    """Softmin over segments of x's leading axis (empty segments -> F_INF).

    Stabilized by the segment min: softmin = m - tau * log(sum exp(-(x -
    m[seg]) / tau)); entries at F_INF contribute exp(0)=1 only when the
    whole segment is unreachable, in which case the result clips back to
    F_INF."""
    x = jnp.minimum(x, F_INF)
    m = jnp.minimum(jax.ops.segment_min(x, seg, num_segments=n), F_INF)
    z = jnp.exp(-(x - m[seg]) / tau)
    s = jax.ops.segment_sum(z, seg, num_segments=n)
    out = m - tau * jnp.log(jnp.maximum(s, 1e-30))
    return jnp.where(s > 0, jnp.minimum(out, F_INF), F_INF)


@shape_contract(
    "w:[E]:float32",
    "src_e:[E]:int32",
    "dst_e:[E]:int32",
    "up:[E]:bool",
    returns="[N,N]:float32:inf",
)
def _softmin_fixpoint_core(w, src_e, dst_e, up, tau, n, rounds):
    """Softmin distance-to-destination matrix D [N, N]: D[v, t] is the
    relaxed distance from v to t after `rounds` relaxations.

    Edge e = (src_e[e] -> dst_e[e]) relaxes its source row: candidates for
    D[u, t] are w[e] + D[dst_e[e], t] over u's out-edges, softmin-combined
    ACROSS EDGES only — the incumbent is folded in with a hard `minimum`.
    Softmin against the incumbent would re-count the same paths every
    round (the incumbent already is last round's softmin of them),
    accumulating an O(rounds * tau * log 2) undershoot; the hard fold
    keeps the per-entry error at O(hops * tau * log degree) while
    gradients still flow through whichever side wins (and through every
    edge of the segment softmin, which is where multi-path gradient
    signal comes from). Down edges are pinned to F_INF (they never relax,
    matching the hard solver's INF-weight convention)."""
    we = jnp.where(up, w, F_INF)
    d0 = jnp.full((n, n), F_INF, dtype=jnp.float32)
    d0 = d0.at[jnp.arange(n), jnp.arange(n)].set(0.0)

    def body(d, _):
        cand = jnp.minimum(we[:, None] + d[dst_e], F_INF)  # [E, N]
        relaxed = _segment_softmin(cand, src_e, n, tau)
        new_d = jnp.minimum(d, relaxed)
        new_d = new_d.at[jnp.arange(n), jnp.arange(n)].set(0.0)
        return new_d, None

    d, _ = jax.lax.scan(body, d0, None, length=rounds)
    return d


softmin_distances = jax.jit(
    _softmin_fixpoint_core, static_argnames=("n", "rounds")
)


@shape_contract(
    "w:[E]:float32",
    "demands:[N,N]:float32",
    "caps:[E]:float32",
    "src_e:[E]:int32",
    "dst_e:[E]:int32",
    "up:[E]:bool",
    returns="[E]:float32",
)
def _soft_utilization_core(
    w, demands, caps, src_e, dst_e, up, tau, n, rounds
):
    """Per-link utilization [E] of one demand matrix under soft routing.

    demands [N, N]: row = origin node, column = destination node (diagonal
    ignored). Splitting gates are the softmax relaxation of the ECMP
    triangle condition; flows propagate for `rounds` hops and are absorbed
    at their destination (a destination node forwards nothing toward
    itself). caps [E] are per-directed-edge capacities."""
    d = _softmin_fixpoint_core(w, src_e, dst_e, up, tau, n, rounds)
    we = jnp.where(up, w, F_INF)
    # triangle gap of edge e toward each destination; >= 0 near the
    # shortest DAG, large on detours — the softmax temperature decides how
    # much traffic detours carry
    gap = we[:, None] + d[dst_e] - d[src_e]  # [E, N]
    node_t = jnp.arange(n, dtype=jnp.int32)
    score = jnp.exp(-jnp.maximum(gap, 0.0) / tau)
    # explicit mask casts: both gates are bools, and a silent bool->float
    # promotion is exactly what the dtype-promotion lint exists to catch
    score = score * up[:, None].astype(score.dtype)
    absorb = (src_e[:, None] != node_t[None, :]).astype(score.dtype)
    score = score * absorb  # a destination node forwards nothing to itself
    score = jnp.where(d[dst_e] >= F_INF / 2, 0.0, score)  # dead ends
    denom = jax.ops.segment_sum(score, src_e, num_segments=n)  # [N, N]
    # double-where: the masked branch must be NaN-free in the BACKWARD
    # pass too (reverse-mode differentiates both branches; a zero-denom
    # division poisons the weight gradient with NaN even though the
    # forward value is discarded)
    safe_denom = jnp.where(denom[src_e] > 1e-20, denom[src_e], 1.0)
    p = jnp.where(denom[src_e] > 1e-20, score / safe_denom, 0.0)

    x0 = demands * (1.0 - jnp.eye(n, dtype=demands.dtype))
    flow0 = jnp.zeros((src_e.shape[0], n), dtype=jnp.float32)

    def body(carry, _):
        x, flow = carry
        ef = p * x[src_e]  # [E, N] flow pushed over each edge this hop
        new_x = jax.ops.segment_sum(ef, dst_e, num_segments=n)
        return (new_x, flow + ef), None

    (_, flow), _ = jax.lax.scan(body, (x0, flow0), None, length=rounds)
    util = flow.sum(axis=1) / jnp.maximum(caps, 1e-9)
    return util


soft_utilization = jax.jit(
    _soft_utilization_core, static_argnames=("n", "rounds")
)


def _soft_mlu_core(
    w, demands, caps, src_e, dst_e, up, tau, tau_obj, n, rounds
):
    """Softmax-relaxed max link utilization of one demand scenario."""
    util = _soft_utilization_core(
        w, demands, caps, src_e, dst_e, up, tau, n, rounds
    )
    return tau_obj * jax.scipy.special.logsumexp(util / tau_obj)


soft_mlu = jax.jit(_soft_mlu_core, static_argnames=("n", "rounds"))


# ---------------------------------------------------------------------------
# hard counterparts (numpy, host-side): the acceptance metric the rounded
# candidate weights are scored with — exact SPF + fractional ECMP splits
# ---------------------------------------------------------------------------


def hard_distances(w, src_e, dst_e, up, n) -> np.ndarray:
    """Integer distance-to-destination matrix D [N, N] by Bellman-Ford.

    Matches the hard SPF semantics the solvers share: down edges never
    relax, unreachable stays at INF. (No overload/transit pruning: the TE
    service excludes overloaded nodes' transit by pinning their out-edge
    weights, same as the compiled-graph convention.)"""
    big = np.int64(INF)
    we = np.where(up, w.astype(np.int64), big)
    d = np.full((n, n), big, dtype=np.int64)
    np.fill_diagonal(d, 0)
    for _ in range(n):
        cand = np.minimum(we[:, None] + d[dst_e], big)  # [E, N]
        upd = np.full((n, n), big, dtype=np.int64)
        np.minimum.at(upd, src_e, cand)
        new_d = np.minimum(d, upd)
        if np.array_equal(new_d, d):
            break
        d = new_d
    return d


def hard_utilization(w, demands, caps, src_e, dst_e, up, n, d=None) -> np.ndarray:
    """Per-link utilization [E] under exact SPF + fractional ECMP.

    At every node, traffic toward t splits equally over the out-edges on
    the shortest-path DAG (the triangle condition of ops/spf.py:_ecmp_dag),
    the idealized ECMP model TE optimizes for. Pass `d` to skip the BF
    re-derivation with a precomputed exact distance matrix for `w` — the
    solver's resident APSP matrix serves the live-weight scoring
    (docs/Apsp.md TE consumer)."""
    if d is None:
        d = hard_distances(w, src_e, dst_e, up, n)
    else:
        d = d.astype(np.int64)
    big = np.int64(INF)
    we = np.where(up, w.astype(np.int64), big)
    node_t = np.arange(n)
    on_dag = (
        (we[:, None] + d[dst_e] == d[src_e])
        & (d[src_e] < big)
        & up[:, None]
        & (src_e[:, None] != node_t[None, :])
    )
    deg = np.zeros((n, n), dtype=np.int64)
    np.add.at(deg, src_e, on_dag.astype(np.int64))
    with np.errstate(divide="ignore", invalid="ignore"):
        p = np.where(deg[src_e] > 0, on_dag / np.maximum(deg[src_e], 1), 0.0)

    x = demands * (1.0 - np.eye(n))
    flow = np.zeros((len(src_e), n), dtype=np.float64)
    for _ in range(n):
        ef = p * x[src_e]
        if not ef.any():
            break
        flow += ef
        x = np.zeros((n, n), dtype=np.float64)
        np.add.at(x, dst_e, ef)
    return flow.sum(axis=1) / np.maximum(caps, 1e-9)


def hard_max_util(w, demands, caps, src_e, dst_e, up, n, d=None) -> float:
    """Max link utilization of one demand matrix under hard SPF routing."""
    util = hard_utilization(w, demands, caps, src_e, dst_e, up, n, d=d)
    return float(util.max()) if len(util) else 0.0
