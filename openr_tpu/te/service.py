"""TE engine: what-if gradient-descent weight optimization over the live
LSDB.

`TeService` snapshots a Decision area's `LinkState` into the compiled
graph arrays (ops/graph.py — the same snapshot the SPF backend solves),
builds the demand-scenario batch (te/scenarios.py), and runs the annealed
GD loop (te/optimizer.py) inside the solver fault domain: the optimization
dispatch is a supervised call on the `SolverSupervisor` (classified
errors, bounded retry, per-call deadline, breaker accounting), and a
failing or degraded device path re-runs the identical optimization pinned
to the CPU backend — a dead accelerator makes TE slower, never a crashed
ctrl request (docs/Robustness.md posture).

This is a REPORTING service: it proposes per-link metric changes plus the
predicted hard-SPF max-link-utilization delta; nothing is programmed. The
operator applies accepted changes through the existing drain/metric
controls (`breeze lm set-link-metric`). Surfaced via ctrl `runTeOptimize`
and `breeze decision te-optimize` (docs/TrafficEngineering.md).

First workload where this reproduction does something the C++ Open/R
reference structurally cannot: the reference's Dijkstra is not
differentiable, so "which weights would decongest this demand matrix" has
no gradient signal to follow there.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import numpy as np

from openr_tpu.ops.graph import compile_graph
from openr_tpu.te.objective import hard_utilization, te_edge_arrays
from openr_tpu.te.optimizer import TeOptConfig, optimize_weights
from openr_tpu.te.scenarios import build_demand_scenarios
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin

log = logging.getLogger(__name__)

# report at most this many hottest links per utilization table
_TOP_LINKS = 8


class TeService(CountersMixin, HistogramsMixin):
    """Differentiable-TE optimization over Decision's LSDB snapshot."""

    def __init__(
        self,
        my_node_name: str,
        area_link_states: Dict,
        solver=None,
        mesh=None,
        log_sample_fn=None,
    ) -> None:
        self.my_node_name = my_node_name
        self.area_link_states = area_link_states
        # the Decision solver facade; when it is a SolverSupervisor the
        # optimization runs as a supervised call and shares the breaker
        self.solver = solver
        self.mesh = mesh if mesh is not None else getattr(solver, "mesh", None)
        self._log_sample_fn = log_sample_fn
        self.counters: Dict[str, int] = {}
        self.histograms: Dict = {}

    # ------------------------------------------------------------------

    def optimize(self, params: Optional[Dict] = None) -> Dict:
        """One what-if optimization; returns the JSON-shaped report served
        by ctrl `runTeOptimize`. Raises ValueError on an empty topology
        (per-request ctrl error, not a degraded run)."""
        params = dict(params or {})
        t0 = time.perf_counter()
        self._bump("decision.te.optimize_runs")
        try:
            report = self._optimize(params, t0)
        except Exception:
            self._bump("decision.te.optimize_errors")
            raise
        self._observe("decision.te.solve_ms", report["solve_ms"])
        return report

    def _optimize(self, params: Dict, t0: float) -> Dict:
        area, link_state = self._pick_area(params.get("area"))
        graph = compile_graph(link_state)
        if graph.n < 2 or graph.e == 0:
            raise ValueError(f"area {area}: no usable topology to optimize")
        src_e, dst_e, w0, up = te_edge_arrays(graph)
        # overloaded (drained) nodes carry no transit traffic: their
        # out-edges leave the optimization and their originating demands
        # are zeroed (a drained node is not a TE source either)
        drained = graph.overloaded[src_e]
        up = up & ~drained
        demands, caps, scenarios = build_demand_scenarios(
            graph,
            params.get("demands"),
            scenarios=params.get("scenarios"),
            seed=int(params.get("seed", 0)),
        )
        drained_rows = np.flatnonzero(graph.overloaded[: graph.n])
        if len(drained_rows):
            demands[:, drained_rows, :] = 0.0
            demands[:, :, drained_rows] = 0.0
        # device-memory ledger seam (monitor/memledger.py): the [B, N, N]
        # scenario batch + capacity vector are the TE run's device-resident
        # working set — registered for the optimization's duration,
        # released with the report build below
        from openr_tpu.monitor.memledger import get_ledger

        ledger = get_ledger()
        mem_handle = ledger.register(
            f"{area}/te",
            "te",
            layout="te",
            arrays=(demands, caps),
        )

        cfg = TeOptConfig(
            steps=int(params.get("steps", TeOptConfig.steps)),
            lr=float(params.get("lr", TeOptConfig.lr)),
            tau0=float(params.get("tau0", TeOptConfig.tau0)),
            tau_min=float(params.get("tau_min", TeOptConfig.tau_min)),
            tau_obj=float(params.get("tau_obj", TeOptConfig.tau_obj)),
            w_min=float(params.get("w_min", TeOptConfig.w_min)),
            w_max=float(params.get("w_max", TeOptConfig.w_max)),
            rounds=params.get("rounds"),
        )
        initial_d = self._borrow_initial_distances(
            area, link_state, graph, w0, up, cfg
        )

        def primary():
            # named fault seam: the supervisor's TE fault-injection tests
            # raise here, exactly where a real device dispatch would
            fault_point("te.optimize", self)
            return optimize_weights(
                src_e, dst_e, up, w0, demands, caps, graph.n,
                config=cfg, mesh=self.mesh, initial_d=initial_d,
            )

        def fallback():
            self._bump("decision.te.fallback_runs")
            return self._cpu_optimize(
                src_e, dst_e, up, w0, demands, caps, graph.n, cfg,
                initial_d=initial_d,
            )

        supervised = getattr(self.solver, "supervised_call", None)
        try:
            if supervised is not None:
                result, degraded = supervised(
                    "te.optimize", primary, fallback
                )
            else:
                try:
                    result, degraded = primary(), False
                except Exception as exc:
                    log.warning("TE device optimization failed: %s", exc)
                    result, degraded = fallback(), True
        finally:
            ledger.release(mem_handle)
        if degraded:
            self._emit_degraded(area)

        self._bump("decision.te.steps", result.steps)
        self._bump("decision.te.d2h_bytes", result.d2h_bytes)
        self.counters["decision.te.steps_last"] = result.steps
        self.counters["decision.te.scenarios_last"] = scenarios
        improved = result.best_max_util < result.initial_max_util
        self.counters["decision.te.improved_last"] = int(improved)
        solve_ms = (time.perf_counter() - t0) * 1e3
        return self._build_report(
            area, graph, src_e, dst_e, up, demands, caps, result,
            scenarios, degraded, improved, solve_ms, initial_d=initial_d,
        )

    # ------------------------------------------------------------------

    def _pick_area(self, area: Optional[str]):
        if area is not None:
            link_state = self.area_link_states.get(area)
            if link_state is None:
                raise ValueError(f"unknown area {area!r}")
            return area, link_state
        for name, link_state in sorted(self.area_link_states.items()):
            if link_state.num_links():
                return name, link_state
        raise ValueError("no area holds any links")

    def _borrow_initial_distances(
        self, area, link_state, graph, w0, up, cfg
    ):
        """Borrow the solver's resident APSP matrix for the live weights
        (docs/Apsp.md TE consumer): the exact [n, n] distances the initial
        hard-scoring would otherwise re-derive by Bellman-Ford. Only valid
        when the scored integer weights are EXACTLY the live graph weights
        (the [w_min, w_max] projection can clip extreme metrics) and the
        solver holds a fresh matrix for this snapshot — anything else
        returns None and the optimizer derives distances itself."""
        borrow = getattr(self.solver, "borrow_apsp", None)
        if borrow is None:
            return None
        w0_int = np.clip(np.rint(w0), cfg.w_min, cfg.w_max).astype(np.int64)
        live = graph.w[: graph.e].astype(np.int64)
        if not np.array_equal(w0_int[up], live[up]):
            return None
        d = borrow(area, link_state.version)
        if d is None or d.shape[0] < graph.n:
            return None
        self._bump("decision.te.apsp_borrows")
        return np.asarray(d[: graph.n, : graph.n])

    def _cpu_optimize(
        self, src_e, dst_e, up, w0, demands, caps, n, cfg, initial_d=None
    ):
        """The identical optimization pinned to the CPU backend (the
        degraded path). Falls back to the default device set when the
        process has no distinct CPU backend to pin to."""
        import jax

        try:
            cpu = jax.devices("cpu")[0]
        except RuntimeError:
            cpu = None
        if cpu is None:
            return optimize_weights(
                src_e, dst_e, up, w0, demands, caps, n, config=cfg,
                initial_d=initial_d,
            )
        with jax.default_device(cpu):
            return optimize_weights(
                src_e, dst_e, up, w0, demands, caps, n, config=cfg,
                initial_d=initial_d,
            )

    def _build_report(
        self,
        area,
        graph,
        src_e,
        dst_e,
        up,
        demands,
        caps,
        result,
        scenarios,
        degraded,
        improved,
        solve_ms,
        initial_d=None,
    ) -> Dict:
        names = graph.names

        def top_links(w_int, d=None) -> List[Dict]:
            worst = np.zeros(len(src_e))
            for k in range(demands.shape[0]):
                worst = np.maximum(
                    worst,
                    hard_utilization(
                        w_int, demands[k], caps, src_e, dst_e, up, graph.n,
                        d=d,
                    ),
                )
            order = np.argsort(-worst)[:_TOP_LINKS]
            return [
                {
                    "src": names[int(src_e[e])],
                    "dst": names[int(dst_e[e])],
                    "util": round(float(worst[e]), 4),
                }
                for e in order
                if worst[e] > 0
            ]

        w0_int = np.rint(result.w0).astype(np.int64)
        changes: List[Dict] = []
        for link, (fwd, rev) in sorted(
            graph.link_edges.items(), key=lambda kv: kv[0].key
        ):
            for pos, node in ((fwd, link.n1), (rev, link.n2)):
                if pos >= len(w0_int) or not up[pos]:
                    continue
                before = int(w0_int[pos])
                after = int(result.w_best[pos])
                if before != after:
                    changes.append(
                        {
                            "node": node,
                            "neighbor": link.other_node_name(node),
                            "iface": link.iface_from_node(node),
                            "metric_before": before,
                            "metric_after": after,
                        }
                    )

        return {
            "node": self.my_node_name,
            "area": area,
            "nodes": graph.n,
            "links": int(np.count_nonzero(up)),
            "scenarios": scenarios,
            "steps": result.steps,
            "best_step": result.best_step,
            "backend": "cpu-fallback" if degraded else "primary",
            "degraded": bool(degraded),
            "improved": bool(improved),
            "initial_max_util": round(float(result.initial_max_util), 6),
            "optimized_max_util": round(float(result.best_max_util), 6),
            "max_util_delta": round(
                float(result.best_max_util - result.initial_max_util), 6
            ),
            "weight_changes": changes if improved else [],
            "top_links": {
                "initial": top_links(w0_int, d=initial_d),
                "optimized": top_links(
                    result.w_best if improved else w0_int,
                    d=None if improved else initial_d,
                ),
            },
            "loss_first": round(float(result.losses[0]), 6)
            if len(result.losses)
            else None,
            "loss_last": round(float(result.losses[-1]), 6)
            if len(result.losses)
            else None,
            "solve_ms": round(solve_ms, 3),
        }

    # ------------------------------------------------------------------

    def _emit_degraded(self, area: str) -> None:
        if self._log_sample_fn is None:
            return
        from openr_tpu.monitor.monitor import LogSample

        sample = LogSample()
        sample.add_string("event", "TE_OPTIMIZE_DEGRADED")
        sample.add_string("area", area)
        try:
            self._log_sample_fn(sample)
        except Exception:  # a closed monitor queue must not fail the run
            log.exception("failed to emit TE degraded log sample")
