"""Demand-matrix and fixture builders for the TE service, bench and tests.

Demand specs are plain JSON (the `breeze decision te-optimize --demands
file.json` format):

    {
      "demands": [["src", "dst", 6.0], ...],
      "capacities": {"default": 1.0, "links": [["a", "b", 4.0], ...]},
      "scenarios": 4,
      "scenario_spread": 0.5
    }

`demands` rows are directed node-to-node offered loads; `capacities.links`
set both directions of a link. Scenario k > 0 scales each origin row by a
deterministic factor drawn from [1 - spread, 1 + spread] (seeded rng), so
the optimizer sees a batch of candidate load patterns around the operator's
estimate instead of overfitting weights to a single matrix.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from openr_tpu.ops.graph import CompiledGraph
from openr_tpu.topology import Edge


def congested_clos_fixture() -> Tuple[List[Edge], Dict]:
    """Deterministic 2-pod Clos with an express link and a skewed demand
    matrix — the acceptance fixture (tests/test_te_service.py) and the
    bench topology (bench.py te_optimize_ms).

    Two spines, two leaves per pod, every leaf dual-homed at metric 1,
    plus a direct l0_0—l1_0 express link. Under uniform weights the big
    l0_0→l1_0 demand rides the 1-hop express link alone (util 6.0) while
    both spine paths idle; weighting the express link up to 2 makes all
    three paths equal cost, ECMP 3-way-splits the elephant and the max
    link utilization drops to 2.0 — a strict improvement hard SPF can
    verify, reachable by integer weights."""
    leaves = ["l0_0", "l0_1", "l1_0", "l1_1"]
    edges: List[Edge] = [
        (leaf, spine, 1) for leaf in leaves for spine in ("s0", "s1")
    ]
    edges.append(("l0_0", "l1_0", 1))  # the express link the elephant rides
    spec = {
        "demands": [
            ["l0_0", "l1_0", 6.0],
            ["l0_1", "l1_1", 1.0],
        ],
        "scenarios": 1,
    }
    return edges, spec


def uniform_demand_spec(names: List[str], load: float = 1.0) -> Dict:
    """All-pairs uniform demands — the synthetic default when the operator
    supplies no matrix (what-if sweep over an unweighted traffic prior)."""
    return {
        "demands": [
            [a, b, load] for a in names for b in names if a != b
        ],
        "scenarios": 1,
    }


def build_demand_scenarios(
    graph: CompiledGraph,
    spec: Optional[Dict],
    scenarios: Optional[int] = None,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """(demands [B, n, n], caps [E], scenario count) from a spec (n = real
    node count: TE solves run on the real-edge arrays, unpadded).

    Unknown node names are ignored (the LSDB may have moved since the
    operator wrote the file); capacities default to 1.0 per directed edge.
    """
    spec = dict(spec or {})
    if not spec.get("demands"):
        spec.update(uniform_demand_spec(list(graph.names)))
    n = graph.n
    base = np.zeros((n, n), dtype=np.float32)
    for row in spec["demands"]:
        a, b, load = row[0], row[1], float(row[2])
        ia = graph.node_index.get(a)
        ib = graph.node_index.get(b)
        if ia is None or ib is None or ia == ib:
            continue
        base[ia, ib] += load

    caps = np.ones(graph.e, dtype=np.float32)
    cap_spec = spec.get("capacities") or {}
    default_cap = float(cap_spec.get("default", 1.0))
    caps[:] = default_cap
    by_pair: Dict[Tuple[int, int], float] = {}
    for row in cap_spec.get("links", ()):
        a, b, cap = row[0], row[1], float(row[2])
        ia = graph.node_index.get(a)
        ib = graph.node_index.get(b)
        if ia is None or ib is None:
            continue
        by_pair[(ia, ib)] = cap
        by_pair[(ib, ia)] = cap
    if by_pair:
        for e in range(graph.e):
            cap = by_pair.get((int(graph.src[e]), int(graph.dst[e])))
            if cap is not None:
                caps[e] = cap

    b_count = int(scenarios or spec.get("scenarios") or 1)
    b_count = max(1, min(b_count, 64))
    spread = float(spec.get("scenario_spread", 0.5))
    mats = [base]
    rng = np.random.default_rng(seed)
    for _ in range(b_count - 1):
        row_scale = rng.uniform(
            max(0.0, 1.0 - spread), 1.0 + spread, size=(n, 1)
        ).astype(np.float32)
        mats.append(base * row_scale)
    return np.stack(mats), caps, b_count
