"""Gradient-descent TE loop: manual Adam over link weights, annealed.

One jitted `lax.scan` runs the whole optimization — per step: anneal the
softmin temperature toward hard SPF, differentiate the mean soft
max-link-utilization over the demand-scenario batch (`jax.value_and_grad`
of the objective in te/objective.py), apply a hand-rolled Adam update (no
optax in the image; the four-line recurrence is not worth a dependency),
and project back into the bounded weight box. The scan emits the full
weight trajectory so the host can score every *rounded integer* iterate
under exact hard-SPF routing and keep the best one — gradient descent
explores in the relaxation, but the weights a TE service proposes must win
under the routing the network actually runs.

Scenario batching rides the existing source-batch conventions: the demand
tensor is [B, N, N] with a scenario validity mask (padding scenarios are
zero-demand and masked out of the objective), and with a solver mesh the
batch axis is sharded over the mesh's 'batch' axis exactly like SPF source
batches (openr_tpu/parallel/mesh.py) — scenario sweeps run data-parallel
with the topology arrays replicated.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.te.objective import (
    _soft_utilization_core,
    hard_max_util,
)
from openr_tpu.utils.shape_contract import shape_contract


@dataclass(frozen=True)
class TeOptConfig:
    """Knobs of the gradient-descent TE loop (docs/TrafficEngineering.md)."""

    steps: int = 80  # Adam steps
    lr: float = 0.4
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    # softmin/softmax temperature annealing: geometric tau0 -> tau_min
    # across the step budget; small tau -> the relaxation approaches the
    # hard SPF objective it is scored under
    tau0: float = 2.0
    tau_min: float = 0.05
    # smooth-max temperature of the max-link-utilization objective
    tau_obj: float = 0.25
    # bounded-weight projection box (integer metrics after rounding)
    w_min: float = 1.0
    w_max: float = 64.0
    # soft relaxation rounds; None -> n (graph node count)
    rounds: Optional[int] = None


@dataclass
class TeOptResult:
    """Outcome of one optimization run, hard-scored."""

    w0: np.ndarray  # initial float weights [E]
    w_best: np.ndarray  # best rounded integer weights [E]
    best_step: int  # scan step the winner came from (-1 = initial)
    initial_max_util: float  # worst-scenario hard MLU at w0
    best_max_util: float  # worst-scenario hard MLU at w_best
    losses: np.ndarray  # soft objective per step [steps]
    steps: int
    # device->host bytes of the trajectory copy-back (one per run); the
    # TE service folds this into decision.te.d2h_bytes so the TE share of
    # transfer traffic is observable next to decision.spf.*
    d2h_bytes: int = 0


@shape_contract(
    "w:[E]:float32",
    "demands:[B,N,N]:float32",
    "scen_mask:[B]:float32",
    "caps:[E]:float32",
    "src_e:[E]:int32",
    "dst_e:[E]:int32",
    "up:[E]:bool",
)
def _loss_core(
    w, demands, scen_mask, caps, src_e, dst_e, up, tau, tau_obj, n, rounds
):
    """Scenario-averaged soft max-link-utilization (the objective).

    demands [B, N, N]; scen_mask [B] zeroes padded scenarios out of the
    mean (padding exists so the batch axis divides a mesh's batch size)."""
    utils = jax.vmap(
        lambda dm: _soft_utilization_core(
            w, dm, caps, src_e, dst_e, up, tau, n, rounds
        )
    )(demands)  # [B, E]
    mlu = tau_obj * jax.scipy.special.logsumexp(utils / tau_obj, axis=1)
    return jnp.sum(mlu * scen_mask) / jnp.maximum(jnp.sum(scen_mask), 1.0)


def _adam_scan_core(
    w0,
    demands,
    scen_mask,
    caps,
    src_e,
    dst_e,
    up,
    lr,
    beta1,
    beta2,
    eps,
    tau0,
    tau_min,
    tau_obj,
    w_min,
    w_max,
    n,
    rounds,
    steps,
):
    """(final w, weight trajectory [steps, E], losses [steps])."""
    grad_fn = jax.value_and_grad(_loss_core)
    m0 = jnp.zeros_like(w0)
    v0 = jnp.zeros_like(w0)

    def step(carry, i):
        w, m, v = carry
        frac = i.astype(jnp.float32) / jnp.maximum(steps - 1, 1)
        tau = tau0 * (tau_min / tau0) ** frac
        loss, g = grad_fn(
            w, demands, scen_mask, caps, src_e, dst_e, up, tau, tau_obj,
            n, rounds,
        )
        g = jnp.where(up, g, 0.0)  # down links are not optimizable
        m = beta1 * m + (1.0 - beta1) * g
        v = beta2 * v + (1.0 - beta2) * g * g
        mh = m / (1.0 - beta1 ** (i.astype(jnp.float32) + 1.0))
        vh = v / (1.0 - beta2 ** (i.astype(jnp.float32) + 1.0))
        w = w - lr * mh / (jnp.sqrt(vh) + eps)
        w = jnp.clip(w, w_min, w_max)  # bounded projection
        return (w, m, v), (w, loss)

    (w_final, _, _), (w_hist, losses) = jax.lax.scan(
        step, (w0, m0, v0), jnp.arange(steps, dtype=jnp.int32)
    )
    return w_final, w_hist, losses


_adam_solver = jax.jit(
    _adam_scan_core, static_argnames=("n", "rounds", "steps")
)


def _shard_scenarios(demands, scen_mask, mesh):
    """Pad the scenario axis to the mesh batch size and commit the demand
    tensor row-sharded over 'batch' (topology arrays stay replicated by
    default) — the SPF source-batch sharding scheme applied to scenarios."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    b = mesh.shape["batch"]
    pad = (-demands.shape[0]) % b
    if pad:
        demands = np.concatenate(
            [demands, np.zeros((pad,) + demands.shape[1:], demands.dtype)]
        )
        scen_mask = np.concatenate(
            [scen_mask, np.zeros(pad, scen_mask.dtype)]
        )
    demands = jax.device_put(
        jnp.asarray(demands), NamedSharding(mesh, P("batch", None, None))
    )
    scen_mask = jax.device_put(
        jnp.asarray(scen_mask), NamedSharding(mesh, P("batch"))
    )
    return demands, scen_mask


def optimize_weights(
    src_e: np.ndarray,
    dst_e: np.ndarray,
    up: np.ndarray,
    w0: np.ndarray,  # float initial weights [E]
    demands: np.ndarray,  # [B, N, N] candidate demand scenarios
    caps: np.ndarray,  # [E] per-directed-edge capacities
    n: int,
    config: Optional[TeOptConfig] = None,
    mesh=None,
    initial_d: Optional[np.ndarray] = None,
) -> TeOptResult:
    """Run the annealed GD loop and hard-score the rounded iterates.

    The winner is the rounded integer weight vector minimizing the WORST
    scenario's hard max link utilization; the initial weights are scored
    too, so a run that finds nothing better reports itself unimproved
    instead of proposing noise. `initial_d`, when given, is an exact
    distance matrix for the INITIAL integer weights (the solver's resident
    APSP matrix, docs/Apsp.md): the w0 score reuses it instead of
    re-deriving [N, N] distances by Bellman-Ford."""
    cfg = config or TeOptConfig()
    rounds = cfg.rounds if cfg.rounds is not None else int(n)
    rounds = max(2, min(int(rounds), 128))

    b = demands.shape[0]
    scen_mask = np.ones(b, dtype=np.float32)
    dem = demands.astype(np.float32)
    if mesh is not None:
        dem, scen_mask = _shard_scenarios(dem, scen_mask, mesh)

    _, w_hist, losses = _adam_solver(
        jnp.asarray(w0, dtype=jnp.float32),
        jnp.asarray(dem),
        jnp.asarray(scen_mask),
        jnp.asarray(caps, dtype=jnp.float32),
        jnp.asarray(src_e),
        jnp.asarray(dst_e),
        jnp.asarray(up),
        cfg.lr,
        cfg.beta1,
        cfg.beta2,
        cfg.eps,
        cfg.tau0,
        cfg.tau_min,
        cfg.tau_obj,
        cfg.w_min,
        cfg.w_max,
        n=int(n),
        rounds=rounds,
        steps=int(cfg.steps),
    )
    # the whole optimization is one dispatch; this is its single
    # copy-back (trajectory + losses), accounted like every other d2h
    w_hist = np.asarray(w_hist)
    losses = np.asarray(losses)
    d2h_bytes = int(w_hist.nbytes + losses.nbytes)

    def worst_hard(w_int: np.ndarray, d=None) -> float:
        return max(
            hard_max_util(w_int, demands[k], caps, src_e, dst_e, up, n, d=d)
            for k in range(b)
        )

    w0_int = np.clip(np.rint(w0), cfg.w_min, cfg.w_max).astype(np.int64)
    best_w, best_step = w0_int, -1
    best_util = initial_util = worst_hard(w0_int, d=initial_d)
    seen = {w0_int.tobytes()}
    for i in range(w_hist.shape[0]):
        w_int = np.clip(np.rint(w_hist[i]), cfg.w_min, cfg.w_max).astype(
            np.int64
        )
        key = w_int.tobytes()
        if key in seen:
            continue  # rounded trajectory revisits few distinct vectors
        seen.add(key)
        util = worst_hard(w_int)
        if util < best_util:
            best_util, best_w, best_step = util, w_int, i

    if best_step >= 0:
        # minimal-change prune: GD wanders many weights on its way to the
        # optimum; revert every changed edge that does not pay for itself
        # so operators see the smallest equivalent proposal
        best_w = best_w.copy()
        for pos in np.flatnonzero(best_w != w0_int):
            trial = best_w.copy()
            trial[pos] = w0_int[pos]
            if worst_hard(trial) <= best_util:
                best_w = trial

    return TeOptResult(
        w0=np.asarray(w0),
        w_best=best_w,
        best_step=best_step,
        initial_max_util=initial_util,
        best_max_util=best_util,
        losses=losses,
        steps=int(cfg.steps),
        d2h_bytes=d2h_bytes,
    )
