"""Device-memory observatory: resident-state ledger + predictive capacity.

The repo's other observability layers (spans, exporter, flight recorder,
fleet observer, journal) watch *time* and *bytes moved* — h2d/d2h/halo
transfer counters, phase clocks, convergence spans. None of them watch
*bytes resident*: an area that does not fit device memory simply dies in
RESOURCE_EXHAUSTED with no forecast, no attribution, and no forensics.
This module closes that gap with three cooperating pieces:

  1. **The ledger** (`MemLedger`): every device-resident structure
     registers at allocation and releases at teardown — `_AreaSolve`'s
     distance matrix and sliced-ELL / bf / tile2d layout buffers, the
     `_PATCH_SLOTS` weight-patch slots, the lazy D host mirrors,
     `ApspState`'s [n_pad, n_pad] matrices, TE scenario tensors, KSP
     layer rows — tagged by (area, structure, layout, dtype, shape).
     Accounting is EXACT, and pinned by test:

         registered_bytes == live_bytes + freed_bytes

     always, across solve / teardown / degrade cycles. The release seam
     carries the `solver.mem.retain` fault point: an armed injector can
     pin entries live (skip the free) to simulate the buffer-leak bug
     class the ledger exists to see — the leak shows up as monotonic
     `live_bytes` growth and a widening live-vs-freed gap, never as an
     accounting violation.

  2. **Watermark reconciliation** (`reconcile()`): where the backend
     exposes `device.memory_stats()` the ledger's live_bytes is compared
     against the allocator's `bytes_in_use`; on backends that don't (the
     CPU backend used by tier-1), `jax.live_arrays()` is the secondary
     source, and when neither is available the `drift_events` counter
     records the unreconcilable check instead of guessing.

  3. **Predictive capacity** (`predict_fit()`): a forward model of
     resident bytes derived from the SAME padding/bucketing arithmetic
     the solvers use (`_next_bucket` buckets, mesh batch-axis rounding,
     `GraphTiling` tile/halo shapes, FW block shapes) — so admission
     decisions (`ApspState.enabled_for`, tile2d layout selection) become
     measured, headroom-gated verdicts that refuse or degrade BEFORE the
     allocator raises, not after. `solver_apsp_max_nodes` demotes to the
     fallback gate used only when no capacity source exists.

Surfaces (docs/Monitoring.md "Device-memory observatory"): the
`decision.mem.*` counters/gauges folded into the solver facade by
`fold_counters()`, ctrl `getDeviceMemory` / `breeze decision memory`,
ledger rows in `getSolverHealth`, the snapshot embedded in every
flight-recorder forensics dump, and the fleet observer's `device_memory`
SLO rule (headroom budget + leak trend over the live-bytes series).

A process-global default ledger (`get_ledger()`) mirrors the process-
wide compile caches: bench's raw-jit paths and the module-level solver
factories share one accounting domain. Tests that need isolation
construct their own `MemLedger` and pass it down.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from openr_tpu.testing.faults import fault_point

# fixed structure vocabulary: per-structure gauge names must be string
# literals (registry-drift resolves docs/Monitoring.md rows against the
# code's string universe), so unknown structures fold into "other"
STRUCT_GAUGES = {
    "dist": "decision.mem.dist_bytes_last",
    "sell": "decision.mem.sell_bytes_last",
    "bf": "decision.mem.bf_bytes_last",
    "tile": "decision.mem.tile_bytes_last",
    "halo": "decision.mem.halo_bytes_last",
    "patch": "decision.mem.patch_bytes_last",
    "mirror": "decision.mem.mirror_bytes_last",
    "apsp": "decision.mem.apsp_bytes_last",
    "te": "decision.mem.te_bytes_last",
    "ksp": "decision.mem.ksp_bytes_last",
    "other": "decision.mem.other_bytes_last",
}

_INT32 = 4
_BOOL = 1


@dataclass
class MemEntry:
    """One registered device-resident (or accounted host-mirror)
    structure. `retained` marks entries pinned live by the
    `solver.mem.retain` fault — released by the caller but never freed,
    the exact signature of a real buffer leak."""

    handle: int
    area: str
    structure: str
    layout: str
    dtype: str
    shape: Tuple[int, ...]
    nbytes: int
    retained: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "handle": self.handle,
            "area": self.area,
            "structure": self.structure,
            "layout": self.layout,
            "dtype": self.dtype,
            "shape": list(self.shape),
            "nbytes": int(self.nbytes),
            "retained": bool(self.retained),
        }


class _ReleaseCtx:
    """fault_point context for `solver.mem.retain`: an armed action sets
    `retain = True` and the ledger keeps the entry live (leak injection
    for MEM_SMOKE / the fleet `device_memory` rule)."""

    __slots__ = ("ledger", "entry", "retain")

    def __init__(self, ledger: "MemLedger", entry: MemEntry) -> None:
        self.ledger = ledger
        self.entry = entry
        self.retain = False


def _arrays_bytes(arrays: Iterable[Any]) -> int:
    total = 0
    for a in arrays:
        if a is None:
            continue
        nb = getattr(a, "nbytes", None)
        if nb is None:
            continue
        total += int(nb)
    return total


class MemLedger:
    """Exact-accounting resident-bytes ledger (thread-safe; the solver,
    APSP closer and TE optimizer touch it from the decision loop while
    ctrl handlers snapshot it from the server loop)."""

    def __init__(self, capacity_bytes: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[int, MemEntry] = {}
        self._next_handle = 1
        # exact accounting: registered == live + freed, always
        self.registered_bytes = 0  # monotonic: every byte ever registered
        self.freed_bytes = 0  # monotonic: every byte ever freed
        self.live_bytes = 0
        self.peak_bytes = 0
        self.registers = 0
        self.releases = 0
        self.retained = 0  # releases pinned live by solver.mem.retain
        self.drift_events = 0  # reconcile() checks with no backend source
        self.capacity_refusals = 0
        self.last_refusal: Optional[Dict[str, Any]] = None
        self._capacity_override = capacity_bytes
        self._headroom_frac = 0.10
        self._externals: Dict[str, Callable[[], Dict[str, Any]]] = {}
        # per-structure live/peak, folded onto the fixed gauge vocabulary
        # (bench lines report the structure peak next to predict_fit)
        self._struct_live: Dict[str, int] = {}
        self._struct_peak: Dict[str, int] = {}

    @staticmethod
    def _fold_structure(structure: str) -> str:
        key = structure.split(".", 1)[0]
        return key if key in STRUCT_GAUGES else "other"

    def _struct_delta(self, structure: str, delta: int) -> None:
        """Adjust one structure's live bytes (caller holds the lock)."""
        key = self._fold_structure(structure)
        live = self._struct_live.get(key, 0) + delta
        self._struct_live[key] = live
        if live > self._struct_peak.get(key, 0):
            self._struct_peak[key] = live

    # -- registration ---------------------------------------------------

    def register(
        self,
        area: str,
        structure: str,
        *,
        layout: str = "none",
        arrays: Iterable[Any] = (),
        nbytes: Optional[int] = None,
        dtype: str = "int32",
        shape: Tuple[int, ...] = (),
    ) -> int:
        """Register one device-resident structure; returns the handle the
        owner must `release()` at teardown. Bytes come from the actual
        arrays when given (`sum(a.nbytes)` — the logical global size, so
        sharded and replicated placements account identically)."""
        if nbytes is None:
            nbytes = _arrays_bytes(arrays)
            first = next((a for a in arrays if a is not None), None)
            if first is not None:
                dtype = str(getattr(first, "dtype", dtype))
                shape = tuple(int(s) for s in getattr(first, "shape", shape))
        nbytes = int(nbytes)
        with self._lock:
            handle = self._next_handle
            self._next_handle += 1
            self._entries[handle] = MemEntry(
                handle=handle,
                area=area,
                structure=structure,
                layout=layout,
                dtype=dtype,
                shape=tuple(shape),
                nbytes=nbytes,
            )
            self.registers += 1
            self.registered_bytes += nbytes
            self.live_bytes += nbytes
            self._struct_delta(structure, nbytes)
            if self.live_bytes > self.peak_bytes:
                self.peak_bytes = self.live_bytes
        return handle

    def release(self, handle: Optional[int]) -> bool:
        """Release a registered structure. The `solver.mem.retain` fault
        seam sits HERE: an armed action pins the entry live (the free is
        skipped), modeling a teardown path that forgot a buffer — the
        accounting stays exact while live_bytes stops returning to
        baseline, which is what the fleet leak-trend rule watches."""
        if handle is None:
            return False
        with self._lock:
            entry = self._entries.get(handle)
        if entry is None or entry.retained:
            return False
        ctx = _ReleaseCtx(self, entry)
        fault_point("solver.mem.retain", ctx)
        with self._lock:
            if ctx.retain:
                entry.retained = True
                self.retained += 1
                return False
            self._entries.pop(handle, None)
            self.releases += 1
            self.freed_bytes += entry.nbytes
            self.live_bytes -= entry.nbytes
            self._struct_delta(entry.structure, -entry.nbytes)
        return True

    def release_area(self, area: str) -> int:
        """Release every live entry tagged with `area` (area teardown:
        `TpuSpfSolver` dropping a solve, mesh degradation rebuilds)."""
        with self._lock:
            handles = [
                h for h, e in self._entries.items() if e.area == area
            ]
        released = 0
        for handle in handles:
            if self.release(handle):
                released += 1
        return released

    def update(self, handle: Optional[int], arrays: Iterable[Any]) -> None:
        """Re-size an existing entry in place (persistent buffers whose
        contents re-upload without changing identity — e.g. the sell `ov`
        refresh). Byte delta flows through registered/freed so the exact-
        accounting invariant holds through the resize."""
        if handle is None:
            return
        nbytes = _arrays_bytes(arrays)
        with self._lock:
            entry = self._entries.get(handle)
            if entry is None:
                return
            delta = nbytes - entry.nbytes
            entry.nbytes = nbytes
            if delta >= 0:
                self.registered_bytes += delta
                self.live_bytes += delta
            else:
                self.freed_bytes += -delta
                self.live_bytes += delta
            self._struct_delta(entry.structure, delta)
            if self.live_bytes > self.peak_bytes:
                self.peak_bytes = self.live_bytes

    # -- introspection --------------------------------------------------

    def check(self) -> bool:
        """The exact-accounting invariant, pinned by test."""
        with self._lock:
            return self.registered_bytes == self.live_bytes + self.freed_bytes

    def live_entries(
        self, area: Optional[str] = None
    ) -> List[MemEntry]:
        with self._lock:
            entries = list(self._entries.values())
        if area is not None:
            entries = [e for e in entries if e.area == area]
        return sorted(entries, key=lambda e: e.handle)

    def area_bytes(self, area: str) -> int:
        with self._lock:
            return sum(
                e.nbytes for e in self._entries.values() if e.area == area
            )

    def structure_bytes(self) -> Dict[str, int]:
        """Live bytes per structure, folded onto the fixed gauge
        vocabulary (unknown structures roll into `other`)."""
        out = {name: 0 for name in STRUCT_GAUGES}
        with self._lock:
            out.update(self._struct_live)
        return out

    def structure_peak_bytes(self) -> Dict[str, int]:
        """Peak live bytes per structure over the ledger's lifetime (the
        bench lines' mem_peak_bytes source)."""
        out = {name: 0 for name in STRUCT_GAUGES}
        with self._lock:
            out.update(self._struct_peak)
        return out

    def attach_external(
        self, name: str, provider: Callable[[], Dict[str, Any]]
    ) -> None:
        """Attach an informational source folded into snapshots WITHOUT
        entering the exact accounting (the compile caches: entry counts
        and size estimates live behind `lru_cache`, not our allocations)."""
        self._externals[name] = provider

    def fold_counters(self, counters: Dict[str, Any]) -> None:
        """Fold the ledger's counters + gauges into a module counter dict
        (the solver facade's — rides the established decision.spf sync
        into the Monitor and the Prometheus exporter). Counters are
        absolute monotonic totals like every decision.* counter; gauges
        carry the `_last`/`_active` suffixes the exporter types by."""
        with self._lock:
            counters["decision.mem.registers"] = self.registers
            counters["decision.mem.releases"] = self.releases
            counters["decision.mem.registered_bytes"] = self.registered_bytes
            counters["decision.mem.freed_bytes"] = self.freed_bytes
            counters["decision.mem.retained"] = self.retained
            counters["decision.mem.drift_events"] = self.drift_events
            counters["decision.mem.capacity_refusals"] = (
                self.capacity_refusals
            )
            counters["decision.mem.live_bytes_last"] = self.live_bytes
            counters["decision.mem.peak_bytes_last"] = self.peak_bytes
            counters["decision.mem.structures_active"] = len(self._entries)
        headroom = self.headroom_bytes()
        counters["decision.mem.headroom_bytes_last"] = (
            -1 if headroom is None else headroom
        )
        for structure, nbytes in self.structure_bytes().items():
            counters[STRUCT_GAUGES[structure]] = nbytes

    def snapshot(self, area: Optional[str] = None) -> Dict[str, Any]:
        """The full ledger picture: totals, invariant, per-structure and
        per-area live bytes, entry rows, reconciliation, capacity. Served
        by ctrl getDeviceMemory and embedded in every forensics dump."""
        entries = self.live_entries(area)
        per_area: Dict[str, int] = {}
        for e in entries:
            per_area[e.area] = per_area.get(e.area, 0) + e.nbytes
        with self._lock:
            totals = {
                "registered_bytes": self.registered_bytes,
                "live_bytes": self.live_bytes,
                "freed_bytes": self.freed_bytes,
                "peak_bytes": self.peak_bytes,
                "registers": self.registers,
                "releases": self.releases,
                "retained": self.retained,
                "drift_events": self.drift_events,
                "capacity_refusals": self.capacity_refusals,
            }
            last_refusal = dict(self.last_refusal) if self.last_refusal else None
        snap: Dict[str, Any] = {
            "totals": totals,
            "exact": totals["registered_bytes"]
            == totals["live_bytes"] + totals["freed_bytes"],
            "structures": self.structure_bytes(),
            "areas": per_area,
            "entries": [e.to_dict() for e in entries],
            "reconcile": self.reconcile(),
            "capacity": self.capacity(),
            "last_refusal": last_refusal,
        }
        external: Dict[str, Any] = {}
        for name, provider in list(self._externals.items()):
            try:
                external[name] = provider()
            except Exception:
                external[name] = {"error": "provider failed"}
        if external:
            snap["external"] = external
        return snap

    # -- watermark reconciliation --------------------------------------

    def reconcile(self) -> Dict[str, Any]:
        """Compare ledger live bytes against the backend's own view.
        Preference order: allocator `memory_stats()` (real HBM
        accounting, present on accelerator backends) > `jax.live_arrays()`
        (logical live-buffer sum — the CPU-backend tier-1 path) >
        unavailable (bump `drift_events`: the check could not be made,
        which is itself a signal worth counting)."""
        backend_bytes: Optional[int] = None
        peak: Optional[int] = None
        source = "unavailable"
        try:
            import jax

            stats_total = 0
            stats_seen = False
            peak_total = 0
            for dev in jax.devices():
                stats = None
                try:
                    stats = dev.memory_stats()
                except Exception:
                    stats = None
                if stats and "bytes_in_use" in stats:
                    stats_seen = True
                    stats_total += int(stats["bytes_in_use"])
                    peak_total += int(
                        stats.get("peak_bytes_in_use", stats["bytes_in_use"])
                    )
            if stats_seen:
                backend_bytes = stats_total
                peak = peak_total
                source = "memory_stats"
            else:
                backend_bytes = sum(
                    int(getattr(a, "nbytes", 0)) for a in jax.live_arrays()
                )
                source = "live_arrays"
        except Exception:
            source = "unavailable"
        if source == "unavailable":
            with self._lock:
                self.drift_events += 1
        with self._lock:
            ledger_bytes = self.live_bytes
        drift = (
            backend_bytes - ledger_bytes if backend_bytes is not None else None
        )
        return {
            "source": source,
            "backend_bytes": backend_bytes,
            "backend_peak_bytes": peak,
            "ledger_bytes": ledger_bytes,
            "drift_bytes": drift,
        }

    # -- capacity model -------------------------------------------------

    def set_capacity_override(self, capacity_bytes: Optional[int]) -> None:
        self._capacity_override = capacity_bytes

    def set_headroom_frac(self, frac: float) -> None:
        self._headroom_frac = max(0.0, min(float(frac), 1.0))

    def capacity(self) -> Dict[str, Any]:
        """Total device capacity and where the number came from:
        `override` (config / tests) > `memory_stats` bytes_limit >
        `fallback` (no capacity source — admission gates must fall back
        to their static caps, e.g. `solver_apsp_max_nodes`)."""
        if self._capacity_override is not None:
            return {
                "capacity_bytes": int(self._capacity_override),
                "source": "override",
            }
        try:
            import jax

            total = 0
            seen = False
            for dev in jax.devices():
                try:
                    stats = dev.memory_stats()
                except Exception:
                    stats = None
                if stats and "bytes_limit" in stats:
                    seen = True
                    total += int(stats["bytes_limit"])
            if seen:
                return {"capacity_bytes": total, "source": "memory_stats"}
        except Exception:
            pass
        return {"capacity_bytes": None, "source": "fallback"}

    def headroom_bytes(self) -> Optional[int]:
        cap = self.capacity()["capacity_bytes"]
        if cap is None:
            return None
        with self._lock:
            return cap - self.live_bytes

    def predict_fit(
        self,
        n_nodes: int,
        layout: str,
        *,
        n_sources: int = 1,
        graph: Any = None,
        tiling: Any = None,
        mesh_shape: Optional[Tuple[int, int]] = None,
        consumers: Tuple[str, ...] = (),
    ) -> Dict[str, Any]:
        """Forward model of resident bytes for a layout, built from the
        SAME arithmetic the solvers use — `_next_bucket` power-of-two
        buckets, mesh batch-axis rounding, the sliced-ELL bucket sums,
        `GraphTiling` tile/halo shapes, the [n_pad, n_pad] FW triple —
        plus a headroom verdict against current capacity and live bytes.
        Pass the `CompiledGraph` for exact sell/tile components (the
        bucket structure depends on the degree distribution); without it
        the edge-count estimate carries the documented sell waste bound.

        Returns {layout, predicted_bytes, components, capacity_bytes,
        headroom_bytes, fits, source}; `fits is None` means no capacity
        source exists and the caller must use its fallback gate."""
        from openr_tpu.ops.graph import _next_bucket

        n = int(n_nodes)
        n_pad = (
            int(graph.n_pad) if graph is not None else _next_bucket(max(n, 1))
        )
        e = int(graph.e) if graph is not None else 0
        e_pad = (
            int(graph.e_pad)
            if graph is not None
            else _next_bucket(max(e, 1))
        )
        b, g = (1, 1)
        if mesh_shape is not None:
            b, g = int(mesh_shape[0]), int(mesh_shape[1])
        s_pad = _next_bucket(max(int(n_sources), 1), minimum=8)
        s_pad += (-s_pad) % max(b, 1)

        components: Dict[str, int] = {}
        if layout == "apsp":
            # the FW triple: d + w (int32) and allow (bool), all [n_pad,n_pad]
            components["apsp.d"] = n_pad * n_pad * _INT32
            components["apsp.w"] = n_pad * n_pad * _INT32
            components["apsp.allow"] = n_pad * n_pad * _BOOL
        elif layout == "te":
            # TE runs on the REAL node/edge counts (te/scenarios.py builds
            # [B, n, n] float32 demands, unpadded); n_sources carries the
            # scenario batch width B
            batch = max(int(n_sources), 1)
            components["te.demands"] = batch * n * n * 4
            components["te.caps"] = max(e, 1) * 4
        else:
            components["dist"] = s_pad * n_pad * _INT32
            if layout == "sell":
                sell = getattr(graph, "sell", None) if graph is not None else None
                if sell is not None:
                    sell_bytes = sum(
                        int(a.nbytes) for a in (*sell.nbr, *sell.wg)
                    )
                    nb = len(sell.nbr)
                else:
                    # no graph: bound by the sell builder's waste contract
                    # (total slots <= edges * (1 + _SELL_WASTE_FRAC)), two
                    # int32 planes (nbr + wg)
                    sell_bytes = int(e_pad * 2 * _INT32 * 1.25)
                    nb = 4
                components["sell"] = sell_bytes + n_pad * _BOOL
                # fixed-capacity weight-patch slots: rowcol [nb,64,2] +
                # vals [nb,64], int32
                components["patch"] = nb * 64 * 3 * _INT32
            elif layout in ("bf", "replicated"):
                # edge-list planes (src/dst/w int32 [e_pad]) + the
                # overload mask; the mesh-replicated edge-list layout has
                # the same logical footprint
                components["bf"] = 3 * e_pad * _INT32 + n_pad * _BOOL
            elif layout == "tile2d":
                if tiling is None and graph is not None and g > 1:
                    from openr_tpu.parallel.mesh import tile_graph

                    try:
                        tiling = tile_graph(graph, g)
                    except Exception:
                        tiling = None
                if tiling is not None:
                    components["tile"] = (
                        tiling.tile_bytes() + n_pad * _BOOL
                    )
                    components["halo"] = tiling.halo_bytes()
                else:
                    # estimate: 3 int32 planes of [g, e_tile≈e_pad/g] + the
                    # ov mask, halo slots bounded by n_pad
                    components["tile"] = 3 * e_pad * _INT32 + n_pad * _BOOL
                    components["halo"] = g * _next_bucket(n_pad) * _INT32
        for extra in consumers:
            if extra == "mirror":
                components["mirror"] = s_pad * n_pad * _INT32
            elif extra == "ksp":
                components["ksp"] = s_pad * n_pad * _INT32

        predicted = int(sum(components.values()))
        cap = self.capacity()
        capacity_bytes = cap["capacity_bytes"]
        fits: Optional[bool] = None
        headroom: Optional[int] = None
        if capacity_bytes is not None:
            with self._lock:
                live = self.live_bytes
            budget = int(capacity_bytes * (1.0 - self._headroom_frac))
            headroom = budget - live - predicted
            fits = headroom >= 0
        return {
            "layout": layout,
            "n_nodes": n,
            "n_pad": n_pad,
            "predicted_bytes": predicted,
            "components": components,
            "capacity_bytes": capacity_bytes,
            "headroom_bytes": headroom,
            "headroom_frac": self._headroom_frac,
            "fits": fits,
            "source": cap["source"],
        }

    def record_refusal(self, verdict: Dict[str, Any]) -> None:
        """Count + remember a headroom-gated admission refusal (surfaced
        through getSolverHealth and the SOLVER_CAPACITY_REFUSED sample)."""
        with self._lock:
            self.capacity_refusals += 1
            self.last_refusal = {
                "layout": verdict.get("layout"),
                "n_nodes": verdict.get("n_nodes"),
                "predicted_bytes": verdict.get("predicted_bytes"),
                "capacity_bytes": verdict.get("capacity_bytes"),
                "headroom_bytes": verdict.get("headroom_bytes"),
                "source": verdict.get("source"),
            }


# -- process-global default ledger -------------------------------------

_LEDGER = MemLedger()


def get_ledger() -> MemLedger:
    """The process-global ledger (the default accounting domain — the
    compile caches and bench's raw-jit paths are process-wide, so the
    default ledger is too). Tests needing isolation construct their own
    `MemLedger` and pass it to the structures they build."""
    return _LEDGER
