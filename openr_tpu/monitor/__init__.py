"""Observability: structured event logs, counters + histogram aggregation,
convergence spans, watchdog.

Equivalents of openr/monitor/ (MonitorBase, LogSample) and openr/watchdog/,
plus the monotonic span tracing layer (monitor/spans.py) that PerfEvents
ride-alongs feed into.
"""

from openr_tpu.monitor.monitor import (
    LogSample,
    Monitor,
    merge_module_histograms,
)
from openr_tpu.monitor.exporter import (
    MetricsExporter,
    parse_metrics_text,
    render_metrics_text,
)
from openr_tpu.monitor.report import (
    ConvergenceRollup,
    aggregate_convergence_reports,
    merge_rollup_snapshots,
    node_convergence_report,
    percentile_summary,
)
from openr_tpu.monitor.profiling import ProfileController
from openr_tpu.monitor.spans import SPAN_EVENT, Span
from openr_tpu.monitor.watchdog import Watchdog, WatchdogConfig

__all__ = [
    "ConvergenceRollup",
    "LogSample",
    "MetricsExporter",
    "Monitor",
    "ProfileController",
    "Span",
    "SPAN_EVENT",
    "Watchdog",
    "WatchdogConfig",
    "aggregate_convergence_reports",
    "merge_module_histograms",
    "merge_rollup_snapshots",
    "node_convergence_report",
    "parse_metrics_text",
    "percentile_summary",
    "render_metrics_text",
]
