"""Observability: structured event logs, counters aggregation, watchdog.

Equivalents of openr/monitor/ (MonitorBase, LogSample) and openr/watchdog/.
"""

from openr_tpu.monitor.monitor import LogSample, Monitor
from openr_tpu.monitor.watchdog import Watchdog, WatchdogConfig

__all__ = ["LogSample", "Monitor", "Watchdog", "WatchdogConfig"]
