"""Convergence spans: structured stage traces of one LSDB event.

PerfEvents (types.py) ride LSDB values across nodes with wall-clock ms
stamps — right for cross-node convergence reports (`breeze perf view`),
wrong for local latency histograms: an NTP step mid-event skews every
duration derived from them. A Span is the local monotonic-clock sibling of
that trace: created when Decision keeps the oldest event of a debounce
batch (seeded from the KvStore publication stamp when one rode along),
marked at each pipeline stage —

    kvstore publication → decision recv → debounce fire → route build
    → fib recv → fib program

— and finished by Fib once routes are programmed. Stage durations feed the
`*_ms` histograms (decision.debounce_ms, decision.spf.solve_ms,
fib.program_ms, convergence.e2e_ms) and the finished span is emitted as
one CONVERGENCE_TRACE LogSample through the monitor queue.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from openr_tpu.monitor.monitor import LogSample

SPAN_EVENT = "CONVERGENCE_TRACE"


class Span:
    """Ordered (stage, monotonic-ts) marks over one event's pipeline pass.

    Spans never cross a process boundary (monotonic clocks don't compare
    across hosts) — they ride in-process queue payloads only, as the
    `span` attribute next to `perf_events`.
    """

    __slots__ = ("name", "t0", "marks")

    def __init__(self, name: str, t0: Optional[float] = None) -> None:
        self.name = name
        self.t0 = time.monotonic() if t0 is None else t0
        self.marks: List[Tuple[str, float]] = []

    def mark(self, stage: str) -> float:
        """Append a stage boundary; returns the stage's duration in ms
        (time since the previous mark, or since t0 for the first)."""
        now = time.monotonic()
        prev = self.marks[-1][1] if self.marks else self.t0
        self.marks.append((stage, now))
        return (now - prev) * 1e3

    def elapsed_ms(self) -> float:
        """End-to-end ms since the span started (t0 → now)."""
        return (time.monotonic() - self.t0) * 1e3

    def stage_durations_ms(self) -> Dict[str, float]:
        """stage -> ms from the previous mark (t0 for the first)."""
        out: Dict[str, float] = {}
        prev = self.t0
        for stage, ts in self.marks:
            out[stage] = (ts - prev) * 1e3
            prev = ts
        return out

    def to_log_sample(self) -> LogSample:
        sample = LogSample()
        sample.add_string("event", SPAN_EVENT)
        sample.add_string("span", self.name)
        total = 0.0
        for stage, ms in self.stage_durations_ms().items():
            sample.add_double(f"{stage}_ms", ms)
            total += ms
        sample.add_double("total_ms", total)
        return sample
