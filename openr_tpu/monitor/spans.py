"""Convergence spans: structured stage traces of one LSDB event.

PerfEvents (types.py) ride LSDB values across nodes with wall-clock ms
stamps — right for cross-node convergence reports (`breeze perf view`),
wrong for local latency histograms: an NTP step mid-event skews every
duration derived from them. A Span is the local monotonic-clock sibling of
that trace: created when Decision keeps the oldest event of a debounce
batch (seeded from the KvStore publication stamp when one rode along),
marked at each pipeline stage —

    spark.neighbor_event → linkmonitor.adj_advertised
    → [kvstore.flood.origin → kvstore.flood.hop1..k]   (remote events)
    → kvstore.publish → decision recv → debounce fire → route build
    → fib recv → fib program

— and finished by Fib once routes are programmed. The pre-publish stages
arrive either as monotonic `Publication.span_stages` marks (the local
origin chain) or are reconstructed from wall-clock PerfEvents (flood-hop
traces from remote nodes); from kvstore.publish on, every mark is taken
live on this process's monotonic clock. Stage durations feed the `*_ms`
histograms (decision.debounce_ms, decision.spf.solve_ms, fib.program_ms,
convergence.e2e_ms) and the finished span is emitted as one
CONVERGENCE_TRACE LogSample through the monitor queue.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from openr_tpu.monitor.monitor import LogSample

SPAN_EVENT = "CONVERGENCE_TRACE"

# finished-span sample keys that are not per-stage durations ("total_ms"
# is the end-to-end duration, exposed as the "total" pseudo-stage)
_NON_STAGE_KEYS = {"event", "span", "node_name"}


def sample_stage_durations(values: Dict[str, float]) -> Dict[str, float]:
    """stage -> ms from one finished span's LogSample value map (the
    CONVERGENCE_TRACE export shape produced by Span.to_log_sample).
    Shared by the point-in-time convergence report and the windowed
    rollup so both read the same stage vocabulary; the end-to-end
    `total_ms` field maps to the `total` pseudo-stage."""
    out: Dict[str, float] = {}
    for key, value in values.items():
        if (
            key.endswith("_ms")
            and key not in _NON_STAGE_KEYS
            and isinstance(value, (int, float))
        ):
            out[key[: -len("_ms")]] = float(value)
    return out


class Span:
    """Ordered (stage, monotonic-ts) marks over one event's pipeline pass.

    Spans never cross a process boundary (monotonic clocks don't compare
    across hosts) — they ride in-process queue payloads only, as the
    `span` attribute next to `perf_events`.
    """

    __slots__ = ("name", "t0", "marks")

    def __init__(self, name: str, t0: Optional[float] = None) -> None:
        self.name = name
        self.t0 = time.monotonic() if t0 is None else t0
        self.marks: List[Tuple[str, float]] = []

    def mark(self, stage: str, ts: Optional[float] = None) -> float:
        """Append a stage boundary; returns the stage's duration in ms
        (time since the previous mark, or since t0 for the first).

        `ts` replays a mark that already happened at a known monotonic
        time — the span-stage handoff (Publication.span_stages) and the
        reconstructed flood-hop stages use it. Marks are kept monotonic:
        a ts behind the previous mark (reconstruction jitter, cross-host
        wall-clock skew) is clamped to it, yielding a zero-length stage
        rather than a negative one."""
        now = time.monotonic() if ts is None else ts
        prev = self.marks[-1][1] if self.marks else self.t0
        if now < prev:
            now = prev
        self.marks.append((stage, now))
        return (now - prev) * 1e3

    def elapsed_ms(self) -> float:
        """End-to-end ms since the span started (t0 → now)."""
        return (time.monotonic() - self.t0) * 1e3

    def stage_durations_ms(self) -> Dict[str, float]:
        """stage -> ms from the previous mark (t0 for the first)."""
        out: Dict[str, float] = {}
        prev = self.t0
        for stage, ts in self.marks:
            out[stage] = (ts - prev) * 1e3
            prev = ts
        return out

    def to_log_sample(self) -> LogSample:
        sample = LogSample()
        sample.add_string("event", SPAN_EVENT)
        sample.add_string("span", self.name)
        total = 0.0
        for stage, ms in self.stage_durations_ms().items():
            sample.add_double(f"{stage}_ms", ms)
            total += ms
        sample.add_double("total_ms", total)
        return sample
