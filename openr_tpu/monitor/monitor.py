"""Structured event logging + counter aggregation.

Behavioral port of openr/monitor/: LogSample (monitor/LogSample.h) is a
typed key→value event record; Monitor (monitor/MonitorBase.h:26-62) drains
the log-sample queue into a bounded ring (monitor_config.max_event_log) and
aggregates fb303-style counters from every registered module (the
reference's fbData singleton is replaced by each module's CountersMixin
dict, pulled on demand)."""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, Iterable, List, Optional

from openr_tpu.messaging import QueueClosedError, RQueue
from openr_tpu.utils.counters import CountersMixin, Histogram

EVENT_LOG_CATEGORY = "openr.event_logs"  # Constants::kEventLogCategory


def merge_module_histograms(
    modules: Iterable[object], reset: bool = False
) -> Dict[str, Histogram]:
    """Merge the `histograms` dicts of a module set into fresh Histogram
    objects (same-name histograms across modules fold together). Shared by
    Monitor.get_histograms and the ctrl server's monitor-less fallback.

    With `reset=True` (the reset-on-read snapshot mode) every merged
    source histogram is cleared after the copy, so consecutive exports
    describe disjoint windows and dashboards can compute rates from
    otherwise lifetime-cumulative distributions. Objects shared by
    reference across modules (e.g. Decision re-exporting the solver's
    decision.spf.* histograms) are reset exactly once — they were also
    merged from whichever module listed them first, and the id-dedup
    keeps the copy and the clear consistent."""
    merged: Dict[str, Histogram] = {}
    seen_ids = set()
    for module in modules:
        hists = getattr(module, "histograms", None)
        if not isinstance(hists, dict):
            continue
        for name, hist in hists.items():
            if not isinstance(hist, Histogram):
                continue
            if id(hist) in seen_ids:
                continue  # same object re-exported by another module
            seen_ids.add(id(hist))
            if name in merged:
                merged[name].merge(hist)
            else:
                merged[name] = hist.copy()
            if reset:
                hist.reset()
    return merged


class LogSample:
    """monitor/LogSample.h: typed structured event."""

    def __init__(self, timestamp: Optional[float] = None) -> None:
        self.timestamp = timestamp if timestamp is not None else time.time()
        self._values: Dict[str, Any] = {}

    def add_string(self, key: str, value: str) -> "LogSample":
        self._values[key] = value
        return self

    def add_int(self, key: str, value: int) -> "LogSample":
        self._values[key] = int(value)
        return self

    def add_double(self, key: str, value: float) -> "LogSample":
        self._values[key] = float(value)
        return self

    def add_string_vector(self, key: str, values: List[str]) -> "LogSample":
        self._values[key] = list(values)
        return self

    def get(self, key: str) -> Any:
        return self._values.get(key)

    def values(self) -> Dict[str, Any]:
        """Copy of the typed key→value map (the convergence-report
        aggregation reads whole samples, not single keys)."""
        return dict(self._values)

    def to_json(self) -> str:
        return json.dumps(
            {"time": int(self.timestamp), **self._values}, sort_keys=True
        )

    @staticmethod
    def from_json(text: str) -> "LogSample":
        data = json.loads(text)
        sample = LogSample(timestamp=data.pop("time", 0))
        sample._values = data
        return sample


class Monitor(CountersMixin):
    """Counter aggregation + event-log ring (MonitorBase equivalent), plus
    the eviction-proof convergence rollup: finished CONVERGENCE_TRACE spans
    fold into fixed-cost windowed aggregates at record time (monitor/
    report.py:ConvergenceRollup), so convergence reports cover every event
    since start even after the `max_event_log` ring evicts the samples."""

    def __init__(
        self,
        node_name: str,
        log_sample_queue: Optional[RQueue] = None,
        max_event_log: int = 100,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        rollup_window_s: float = 60.0,
        rollup_max_windows: int = 120,
    ) -> None:
        from openr_tpu.monitor.report import ConvergenceRollup

        self.node_name = node_name
        self.log_sample_queue = log_sample_queue
        self.max_event_log = max_event_log
        self._loop = loop
        self.event_logs: List[LogSample] = []
        self.rollup = ConvergenceRollup(
            window_s=rollup_window_s, max_windows=rollup_max_windows
        )
        # name -> module exposing .counters dict (CountersMixin)
        self._modules: Dict[str, object] = {}
        self._task: Optional[asyncio.Task] = None
        self.process_start = time.time()
        self.counters: Dict[str, int] = {}
        # histogram samples cleared by reset-on-read snapshots, preserved
        # for the exporter's non-resetting cumulative view (see
        # get_cumulative_histograms)
        self._reset_accum: Dict[str, Histogram] = {}

    def register_module(self, name: str, module: object) -> None:
        """Modules register so their counters appear in getCounters."""
        self._modules[name] = module

    def start(self) -> None:
        if self.log_sample_queue is not None:
            loop = self._loop or asyncio.get_event_loop()
            self._task = loop.create_task(self._drain())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _drain(self) -> None:
        while True:
            try:
                sample = await self.log_sample_queue.get()
            except (QueueClosedError, asyncio.CancelledError):
                return
            self.add_event_log(sample)

    def add_event_log(self, sample: LogSample) -> None:
        if sample.get("node_name") is None:
            sample.add_string("node_name", self.node_name)
        from openr_tpu.monitor.spans import SPAN_EVENT

        if sample.get("event") == SPAN_EVENT:
            # record-time fold: the rollup sees every span exactly once,
            # before the bounded ring below can evict its sample
            self.rollup.record_span(sample.values(), ts=sample.timestamp)
        self.event_logs.append(sample)
        while len(self.event_logs) > self.max_event_log:
            self.event_logs.pop(0)
            self._bump("monitor.event_log_evictions")

    def get_event_logs(self) -> List[LogSample]:
        return list(self.event_logs)

    def get_counters(self) -> Dict[str, int]:
        """Merged counters of every registered module + process stats
        (the getCounters thrift API surface)."""
        merged: Dict[str, int] = {
            "process.uptime.seconds": int(time.time() - self.process_start),
        }
        merged.update(self.counters)
        for module in self._modules.values():
            counters = getattr(module, "counters", None)
            if isinstance(counters, dict):
                merged.update(counters)
        return merged

    def get_histograms(
        self, reset: bool = False
    ) -> Dict[str, Dict[str, float]]:
        """Merged latency histograms of every registered module (the
        getHistograms ctrl API surface): name -> exported stats dict
        (count/sum/avg/min/max/p50/p95/p99). `reset=True` clears every
        source histogram after export (reset-on-read windowing); the
        cleared samples are preserved in the reset accumulator so the
        exporter's cumulative view (get_cumulative_histograms) never
        loses them to another consumer's snapshot."""
        merged = merge_module_histograms(self._modules.values(), reset=reset)
        if reset:
            for name, hist in merged.items():
                acc = self._reset_accum.get(name)
                if acc is None:
                    self._reset_accum[name] = hist.copy()
                else:
                    acc.merge(hist)
        return {name: h.to_dict() for name, h in sorted(merged.items())}

    def get_cumulative_histograms(self) -> Dict[str, Histogram]:
        """Non-resetting, reset-proof histogram view (live Histogram
        objects): the live module histograms merged with every sample a
        `reset=True` snapshot cleared. A scrape racing a `--reset`
        dashboard therefore still exports lifetime-cumulative
        distributions — the exporter contract (docs/Monitoring.md)."""
        merged = merge_module_histograms(self._modules.values(), reset=False)
        for name, acc in self._reset_accum.items():
            if name in merged:
                merged[name].merge(acc)
            else:
                merged[name] = acc.copy()
        return merged
