"""Liveness watchdog.

Behavioral port of openr/watchdog/Watchdog.{h,cpp}: every module's event
loop stamps a heartbeat; a periodic checker fires a crash action when any
module stalls past thread_timeout_s or process RSS exceeds max_memory_mb
(OpenrConfig.thrift:65-69). The reference aborts the process (fireCrash,
Watchdog.h:42); here the action is injectable so tests (and supervisors
that prefer restart-on-unhealthy) can observe it.
"""

from __future__ import annotations

import asyncio
import logging
import os
import resource
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

log = logging.getLogger(__name__)


@dataclass
class WatchdogConfig:
    """OpenrConfig.thrift WatchdogConfig:65."""

    interval_s: float = 20.0
    thread_timeout_s: float = 300.0
    max_memory_mb: int = 800


def _default_fire(reason: str) -> None:
    log.critical("watchdog firing: %s", reason)
    os.abort()


class Watchdog:
    def __init__(
        self,
        config: Optional[WatchdogConfig] = None,
        fire: Callable[[str], None] = _default_fire,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.config = config or WatchdogConfig()
        self.fire = fire
        self._loop = loop
        self._heartbeats: Dict[str, float] = {}
        self._tasks: Dict[str, asyncio.Task] = {}
        self._checker: Optional[asyncio.Task] = None
        self.monitored_modules: list = []
        # module -> count of budget-overrun sections (note_slow)
        self.slow_sections: Dict[str, int] = {}

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    # ------------------------------------------------------------------

    def add_module(self, name: str) -> None:
        """addEvb equivalent: spawn a heartbeat task on the (shared) loop.

        The reference stamps per-thread event loops; the rebuild runs all
        modules on one asyncio loop, so one heartbeat task per registered
        module detects loop starvation (a stuck module blocks them all) and
        keeps per-module attribution for the report."""
        self.monitored_modules.append(name)
        self._heartbeats[name] = time.monotonic()
        self._tasks[name] = self.loop().create_task(self._beat(name))

    def touch(self, name: str) -> None:
        """Modules doing long cooperative work can stamp explicitly."""
        self._heartbeats[name] = time.monotonic()

    def note_slow(self, name: str, elapsed_s: float, budget_s: float) -> None:
        """Attributed slow-section report (SolverSupervisor's per-solve
        deadline enforcement lands here): a section finished but blew its
        budget — below the fire threshold, above normal. Recorded per
        module so a watchdog fire that follows can name the culprit."""
        self.slow_sections[name] = self.slow_sections.get(name, 0) + 1
        log.warning(
            "module %s section ran %.3fs (budget %.3fs)",
            name,
            elapsed_s,
            budget_s,
        )

    def start(self) -> None:
        self._checker = self.loop().create_task(self._check_loop())

    def stop(self) -> None:
        if self._checker is not None:
            self._checker.cancel()
            self._checker = None
        for task in self._tasks.values():
            task.cancel()
        self._tasks.clear()

    # ------------------------------------------------------------------

    async def _beat(self, name: str) -> None:
        try:
            while True:
                self._heartbeats[name] = time.monotonic()
                await asyncio.sleep(min(1.0, self.config.interval_s / 4))
        except asyncio.CancelledError:
            pass

    async def _check_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.config.interval_s)
                self.check_once()
        except asyncio.CancelledError:
            pass

    def check_once(self) -> None:
        now = time.monotonic()
        for name, stamp in self._heartbeats.items():
            stalled = now - stamp
            if stalled > self.config.thread_timeout_s:
                self.fire(
                    f"module {name} stalled for {stalled:.1f}s "
                    f"(> {self.config.thread_timeout_s}s)"
                )
                return
        rss_mb = self.get_rss_mb()
        if rss_mb > self.config.max_memory_mb:
            self.fire(
                f"RSS {rss_mb}MB exceeds limit {self.config.max_memory_mb}MB"
            )

    @staticmethod
    def get_rss_mb() -> int:
        # ru_maxrss is KB on Linux
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
