"""Cross-node convergence reports.

The per-node trace substrate — CONVERGENCE_TRACE spans (monitor/spans.py)
and FLOOD_TRACE samples + `kvstore.flood.*` stats (kvstore/store.py) —
answers "how fast did THIS node converge". The network-wide question
("after one link flap, when did the LAST node program routes, and which
hop was slowest?") needs an aggregation layer:

  - `node_convergence_report(...)` distills one node's monitor ring and
    kvstore flood stats into a JSON-serializable report (served by ctrl
    `getConvergenceReport`);
  - `aggregate_convergence_reports(...)` folds the reports of every node
    of an emulator / VirtualNetwork run (or a `breeze perf report
    --hosts ...` sweep) into network-wide convergence percentiles
    (p50/p95/max node-to-converge), per-stage latency distributions with
    slowest-hop attribution, and flood-health stats (hop latencies,
    hop-count spread, redundant-flood ratio).

This is the instrument DeltaPath (PAPERS.md) argues for: the metric that
validates an accelerated SPF backend is event-to-network-wide-programmed-
routes latency, not local solve time.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional

from openr_tpu.monitor.spans import SPAN_EVENT

FLOOD_TRACE_EVENT = "FLOOD_TRACE"  # mirrors kvstore/store.py (no import
# cycle: kvstore.store already imports monitor.monitor)

# span-sample keys that are not per-stage durations
_NON_STAGE_KEYS = {"event", "span", "node_name", "total_ms"}


def percentile_summary(values: Iterable[float]) -> Dict[str, float]:
    """count/min/avg/p50/p95/max over a raw sample list (nearest-rank
    percentiles — report sample sets are small, no bucketing needed)."""
    samples = sorted(float(v) for v in values)
    if not samples:
        return {
            "count": 0,
            "min": 0.0,
            "avg": 0.0,
            "p50": 0.0,
            "p95": 0.0,
            "max": 0.0,
        }

    def rank(p: float) -> float:
        idx = max(0, math.ceil(p / 100.0 * len(samples)) - 1)
        return samples[min(idx, len(samples) - 1)]

    return {
        "count": len(samples),
        "min": samples[0],
        "avg": sum(samples) / len(samples),
        "p50": rank(50),
        "p95": rank(95),
        "max": samples[-1],
    }


def node_convergence_report(
    node_name: str, monitor, kvstore=None
) -> Dict[str, Any]:
    """One node's convergence evidence: finished spans and flood traces
    from the monitor's event-log ring, plus the kvstore flood counters and
    histogram exports. Everything in the result is JSON-serializable."""
    spans: List[Dict[str, Any]] = []
    floods: List[Dict[str, Any]] = []
    for sample in monitor.get_event_logs():
        event = sample.get("event")
        if event == SPAN_EVENT:
            spans.append(sample.values())
        elif event == FLOOD_TRACE_EVENT:
            floods.append(sample.values())
    flood_stats: Dict[str, Any] = {"received": 0, "duplicates": 0}
    if kvstore is not None:
        counters = kvstore.counters
        flood_stats["received"] = counters.get("kvstore.flood.received", 0)
        flood_stats["duplicates"] = counters.get(
            "kvstore.flood.duplicates", 0
        )
        flood_stats["hop_count_last"] = counters.get(
            "kvstore.flood.hop_count_last", 0
        )
        histograms = getattr(kvstore, "histograms", None) or {}
        for name in (
            "kvstore.flood.hop_ms",
            "kvstore.flood.e2e_ms",
            "kvstore.flood.buffer_delay_ms",
        ):
            hist = histograms.get(name)
            if hist is not None:
                flood_stats[name.rsplit(".", 1)[-1]] = hist.to_dict()
    received = flood_stats["received"]
    flood_stats["duplicate_ratio"] = (
        flood_stats["duplicates"] / received if received else 0.0
    )
    return {
        "node": node_name,
        "spans": spans,
        "e2e_ms": [
            s["total_ms"] for s in spans if s.get("total_ms") is not None
        ],
        "floods": floods,
        "flood": flood_stats,
    }


def _span_stages(span: Dict[str, Any]) -> Dict[str, float]:
    return {
        key[: -len("_ms")]: float(value)
        for key, value in span.items()
        if key.endswith("_ms")
        and key not in _NON_STAGE_KEYS
        and isinstance(value, (int, float))
    }


def aggregate_convergence_reports(
    reports: Iterable[Dict[str, Any]],
) -> Dict[str, Any]:
    """Fold per-node reports into the network-wide convergence view."""
    reports = list(reports)
    all_e2e: List[float] = []
    node_e2e: Dict[str, Dict[str, float]] = {}
    stage_samples: Dict[str, List[float]] = {}
    slowest: Optional[Dict[str, Any]] = None
    hop_ms: List[float] = []
    hop_counts: List[int] = []
    received = duplicates = 0
    for report in reports:
        node = report.get("node", "")
        e2e = [float(v) for v in report.get("e2e_ms", [])]
        all_e2e.extend(e2e)
        node_e2e[node] = percentile_summary(e2e)
        for span in report.get("spans", []):
            for stage, ms in _span_stages(span).items():
                stage_samples.setdefault(stage, []).append(ms)
                if slowest is None or ms > slowest["ms"]:
                    slowest = {"node": node, "stage": stage, "ms": ms}
        for flood in report.get("floods", []):
            if flood.get("hop_ms") is not None:
                hop_ms.append(float(flood["hop_ms"]))
            hop_counts.append(int(flood.get("hop_count", 0)))
        flood_stats = report.get("flood", {})
        received += int(flood_stats.get("received", 0))
        duplicates += int(flood_stats.get("duplicates", 0))
    return {
        "nodes": len(reports),
        "spans_total": sum(len(r.get("spans", [])) for r in reports),
        "e2e_ms": percentile_summary(all_e2e),
        "node_e2e_ms": node_e2e,
        "stages": {
            stage: percentile_summary(samples)
            for stage, samples in sorted(stage_samples.items())
        },
        "slowest_stage": slowest,
        "flood": {
            "received": received,
            "duplicates": duplicates,
            "duplicate_ratio": duplicates / received if received else 0.0,
            "hop_ms": percentile_summary(hop_ms),
            "hop_count_max": max(hop_counts, default=0),
        },
    }
