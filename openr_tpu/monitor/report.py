"""Cross-node convergence reports.

The per-node trace substrate — CONVERGENCE_TRACE spans (monitor/spans.py)
and FLOOD_TRACE samples + `kvstore.flood.*` stats (kvstore/store.py) —
answers "how fast did THIS node converge". The network-wide question
("after one link flap, when did the LAST node program routes, and which
hop was slowest?") needs an aggregation layer:

  - `node_convergence_report(...)` distills one node's monitor ring and
    kvstore flood stats into a JSON-serializable report (served by ctrl
    `getConvergenceReport`);
  - `aggregate_convergence_reports(...)` folds the reports of every node
    of an emulator / VirtualNetwork run (or a `breeze perf report
    --hosts ...` sweep) into network-wide convergence percentiles
    (p50/p95/max node-to-converge), per-stage latency distributions with
    slowest-hop attribution, and flood-health stats (hop latencies,
    hop-count spread, redundant-flood ratio).

This is the instrument DeltaPath (PAPERS.md) argues for: the metric that
validates an accelerated SPF backend is event-to-network-wide-programmed-
routes latency, not local solve time.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from openr_tpu.monitor.spans import SPAN_EVENT, sample_stage_durations
from openr_tpu.utils.counters import Histogram

FLOOD_TRACE_EVENT = "FLOOD_TRACE"  # mirrors kvstore/store.py (no import
# cycle: kvstore.store already imports monitor.monitor)


def percentile_summary(values: Iterable[float]) -> Dict[str, float]:
    """count/min/avg/p50/p95/max over a raw sample list (nearest-rank
    percentiles — report sample sets are small, no bucketing needed)."""
    samples = sorted(float(v) for v in values)
    if not samples:
        return {
            "count": 0,
            "min": 0.0,
            "avg": 0.0,
            "p50": 0.0,
            "p95": 0.0,
            "max": 0.0,
        }

    def rank(p: float) -> float:
        idx = max(0, math.ceil(p / 100.0 * len(samples)) - 1)
        return samples[min(idx, len(samples) - 1)]

    return {
        "count": len(samples),
        "min": samples[0],
        "avg": sum(samples) / len(samples),
        "p50": rank(50),
        "p95": rank(95),
        "max": samples[-1],
    }


# ---------------------------------------------------------------------------
# eviction-proof windowed rollups
# ---------------------------------------------------------------------------


class ConvergenceRollup:
    """Fixed-cost, eviction-proof aggregation of convergence spans.

    The monitor's event-log ring keeps the last `max_event_log` LogSamples
    of ANY kind, so on a busy node a span sample lives seconds before
    FLOOD_TRACEs push it out — which is why every convergence claim so far
    covered single flaps only. The rollup folds each finished span into
    two aggregate layers AT RECORD TIME (Monitor.add_event_log), before
    the ring can evict it:

      - **cumulative**: one mergeable Histogram per stage (plus the
        `total` end-to-end pseudo-stage) covering every span since
        process start — the layer the exporter serves and the layer that
        must account for 100% of events regardless of ring size;
      - **windowed**: the same per-stage histograms bucketed into
        `window_s`-wide wall-clock windows, kept in a bounded ring of
        `max_windows` (evicted windows fold their event count into
        `evicted_events`; their samples stay in the cumulative layer, so
        window eviction loses trend resolution, never data).

    Memory is O(max_windows x stages), independent of event rate; one
    record is O(stages) Histogram.record calls. Snapshots are
    JSON-serializable (sparse histograms) and merge across nodes —
    wall-clock window starts align inside an emulator host and are
    NTP-close across real hosts.
    """

    TOTAL_STAGE = "total"

    def __init__(
        self,
        window_s: float = 60.0,
        max_windows: int = 120,
        clock=time.time,
    ) -> None:
        assert window_s > 0 and max_windows >= 1
        self.window_s = float(window_s)
        self.max_windows = int(max_windows)
        self._clock = clock
        self.events_total = 0
        self.evicted_events = 0
        self.window_evictions = 0
        self.cumulative: Dict[str, Histogram] = {}
        # ordered oldest->newest: (window index, {"events": n, stages})
        self._windows: List[Tuple[int, Dict[str, Any]]] = []

    def record_span(
        self, values: Dict[str, Any], ts: Optional[float] = None
    ) -> None:
        """Fold one finished span's value map (LogSample shape) into the
        cumulative and windowed layers."""
        stages = sample_stage_durations(values)
        if not stages:
            return
        when = self._clock() if ts is None else float(ts)
        window = self._window_for(when)
        self.events_total += 1
        if window is None:  # stamp predates the retained window ring
            self.evicted_events += 1
        else:
            window["events"] += 1
        for stage, ms in stages.items():
            cum = self.cumulative.get(stage)
            if cum is None:
                cum = self.cumulative[stage] = Histogram()
            cum.record(ms)
            if window is None:
                continue
            win = window["stages"].get(stage)
            if win is None:
                win = window["stages"][stage] = Histogram()
            win.record(ms)

    def _window_for(self, when: float) -> Optional[Dict[str, Any]]:
        """Retained window for a wall-clock stamp; None when the stamp's
        window already left the bounded ring (the sample then counts as
        evicted and lands only in the cumulative layer). Out-of-order
        stamps (monitor-queue drain lag) fold into their retained window
        rather than tearing the ring order."""
        index = int(when // self.window_s)
        if self._windows:
            if self._windows[-1][0] == index:
                return self._windows[-1][1]
            for idx, window in reversed(self._windows):
                if idx == index:
                    return window
                if idx < index:
                    break
            if (
                index < self._windows[0][0]
                and len(self._windows) >= self.max_windows
            ):
                return None
        window: Dict[str, Any] = {"events": 0, "stages": {}}
        self._windows.append((index, window))
        self._windows.sort(key=lambda iw: iw[0])
        while len(self._windows) > self.max_windows:
            _, evicted = self._windows.pop(0)
            self.window_evictions += 1
            self.evicted_events += evicted["events"]
        return window

    def windowed_events(self) -> int:
        """Events still resolvable to a retained window; plus
        `evicted_events` this always equals `events_total` — the
        no-eviction-loss invariant the soak verdict checks."""
        return sum(w["events"] for _, w in self._windows)

    def last_window(self) -> Optional[Dict[str, Any]]:
        """Newest window (may still be filling): {"start", "events",
        "stages": {stage: Histogram}} — the exporter's windowed gauges."""
        if not self._windows:
            return None
        index, window = self._windows[-1]
        return {
            "start": index * self.window_s,
            "events": window["events"],
            "stages": window["stages"],
        }

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable export (sparse histograms), the shape served
        inside node_convergence_report and merged network-wide by
        merge_rollup_snapshots."""
        return {
            "window_s": self.window_s,
            "max_windows": self.max_windows,
            "events_total": self.events_total,
            "evicted_events": self.evicted_events,
            "window_evictions": self.window_evictions,
            "cumulative": {
                stage: h.to_sparse()
                for stage, h in sorted(self.cumulative.items())
            },
            "windows": [
                {
                    "start": index * self.window_s,
                    "events": window["events"],
                    "stages": {
                        stage: h.to_sparse()
                        for stage, h in sorted(window["stages"].items())
                    },
                }
                for index, window in self._windows
            ],
        }


def merge_rollup_snapshots(
    snapshots: Iterable[Dict[str, Any]],
) -> Dict[str, Any]:
    """Fold per-node rollup snapshots into one network-wide rollup with
    live Histogram objects: same-start windows merge across nodes (the
    wall clock is the shared axis). Returns {"window_s", "events_total",
    "evicted_events", "window_evictions", "cumulative": {stage: Histogram},
    "windows": [{"start", "events", "stages": {stage: Histogram}}]}."""
    window_s = 0.0
    events_total = evicted = window_evictions = 0
    cumulative: Dict[str, Histogram] = {}
    windows: Dict[float, Dict[str, Any]] = {}
    for snap in snapshots:
        if not snap:
            continue
        window_s = window_s or float(snap.get("window_s", 0.0))
        events_total += int(snap.get("events_total", 0))
        evicted += int(snap.get("evicted_events", 0))
        window_evictions += int(snap.get("window_evictions", 0))
        for stage, sparse in (snap.get("cumulative") or {}).items():
            hist = Histogram.from_sparse(sparse)
            if stage in cumulative:
                cumulative[stage].merge(hist)
            else:
                cumulative[stage] = hist
        for window in snap.get("windows") or []:
            start = float(window.get("start", 0.0))
            merged = windows.setdefault(
                start, {"start": start, "events": 0, "stages": {}}
            )
            merged["events"] += int(window.get("events", 0))
            for stage, sparse in (window.get("stages") or {}).items():
                hist = Histogram.from_sparse(sparse)
                if stage in merged["stages"]:
                    merged["stages"][stage].merge(hist)
                else:
                    merged["stages"][stage] = hist
    return {
        "window_s": window_s,
        "events_total": events_total,
        "evicted_events": evicted,
        "window_evictions": window_evictions,
        "cumulative": cumulative,
        "windows": [windows[start] for start in sorted(windows)],
    }


def node_convergence_report(
    node_name: str, monitor, kvstore=None
) -> Dict[str, Any]:
    """One node's convergence evidence: finished spans and flood traces
    from the monitor's event-log ring, plus the kvstore flood counters and
    histogram exports. Everything in the result is JSON-serializable."""
    spans: List[Dict[str, Any]] = []
    floods: List[Dict[str, Any]] = []
    for sample in monitor.get_event_logs():
        event = sample.get("event")
        if event == SPAN_EVENT:
            spans.append(sample.values())
        elif event == FLOOD_TRACE_EVENT:
            floods.append(sample.values())
    flood_stats: Dict[str, Any] = {"received": 0, "duplicates": 0}
    if kvstore is not None:
        counters = kvstore.counters
        flood_stats["received"] = counters.get("kvstore.flood.received", 0)
        flood_stats["duplicates"] = counters.get(
            "kvstore.flood.duplicates", 0
        )
        flood_stats["hop_count_last"] = counters.get(
            "kvstore.flood.hop_count_last", 0
        )
        histograms = getattr(kvstore, "histograms", None) or {}
        for name in (
            "kvstore.flood.hop_ms",
            "kvstore.flood.e2e_ms",
            "kvstore.flood.buffer_delay_ms",
        ):
            hist = histograms.get(name)
            if hist is not None:
                flood_stats[name.rsplit(".", 1)[-1]] = hist.to_dict()
    received = flood_stats["received"]
    flood_stats["duplicate_ratio"] = (
        flood_stats["duplicates"] / received if received else 0.0
    )
    # eviction-proof layer: the record-time windowed rollup covers every
    # span since start even after the ring above evicted its sample
    rollup = getattr(monitor, "rollup", None)
    return {
        "node": node_name,
        "spans": spans,
        "e2e_ms": [
            s["total_ms"] for s in spans if s.get("total_ms") is not None
        ],
        "floods": floods,
        "flood": flood_stats,
        "rollup": rollup.snapshot() if rollup is not None else None,
    }


def _span_stages(span: Dict[str, Any]) -> Dict[str, float]:
    stages = sample_stage_durations(span)
    stages.pop(ConvergenceRollup.TOTAL_STAGE, None)  # not a pipeline stage
    return stages


def _aggregate_rollups(reports: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Network-wide cumulative-vs-windowed split from the per-node rollup
    snapshots: unlike the ring-derived sections (bounded by max_event_log),
    `events_total` here accounts for every span since node start."""
    merged = merge_rollup_snapshots(
        r.get("rollup") for r in reports if r.get("rollup")
    )
    return {
        "window_s": merged["window_s"],
        "events_total": merged["events_total"],
        "evicted_events": merged["evicted_events"],
        "window_evictions": merged["window_evictions"],
        "cumulative": {
            stage: hist.to_dict()
            for stage, hist in sorted(merged["cumulative"].items())
        },
        "windows": [
            {
                "start": window["start"],
                "events": window["events"],
                "e2e_ms": (
                    window["stages"][ConvergenceRollup.TOTAL_STAGE].to_dict()
                    if ConvergenceRollup.TOTAL_STAGE in window["stages"]
                    else Histogram().to_dict()
                ),
            }
            for window in merged["windows"]
        ],
    }


def aggregate_convergence_reports(
    reports: Iterable[Dict[str, Any]],
) -> Dict[str, Any]:
    """Fold per-node reports into the network-wide convergence view."""
    reports = list(reports)
    all_e2e: List[float] = []
    node_e2e: Dict[str, Dict[str, float]] = {}
    stage_samples: Dict[str, List[float]] = {}
    slowest: Optional[Dict[str, Any]] = None
    hop_ms: List[float] = []
    hop_counts: List[int] = []
    received = duplicates = 0
    for report in reports:
        node = report.get("node", "")
        e2e = [float(v) for v in report.get("e2e_ms", [])]
        all_e2e.extend(e2e)
        node_e2e[node] = percentile_summary(e2e)
        for span in report.get("spans", []):
            for stage, ms in _span_stages(span).items():
                stage_samples.setdefault(stage, []).append(ms)
                if slowest is None or ms > slowest["ms"]:
                    slowest = {"node": node, "stage": stage, "ms": ms}
        for flood in report.get("floods", []):
            if flood.get("hop_ms") is not None:
                hop_ms.append(float(flood["hop_ms"]))
            hop_counts.append(int(flood.get("hop_count", 0)))
        flood_stats = report.get("flood", {})
        received += int(flood_stats.get("received", 0))
        duplicates += int(flood_stats.get("duplicates", 0))
    return {
        "nodes": len(reports),
        "spans_total": sum(len(r.get("spans", [])) for r in reports),
        "e2e_ms": percentile_summary(all_e2e),
        "node_e2e_ms": node_e2e,
        "stages": {
            stage: percentile_summary(samples)
            for stage, samples in sorted(stage_samples.items())
        },
        "slowest_stage": slowest,
        "rollup": _aggregate_rollups(reports),
        "flood": {
            "received": received,
            "duplicates": duplicates,
            "duplicate_ratio": duplicates / received if received else 0.0,
            "hop_ms": percentile_summary(hop_ms),
            "hop_count_max": max(hop_counts, default=0),
        },
    }
