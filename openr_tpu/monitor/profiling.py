"""On-demand JAX profiling windows (ctrl `startProfile` / breeze
`decision profile`).

The flight recorder (solver/flight_recorder.py) answers *which phase* of
a solve was slow; this module answers *why*, on demand: a bounded
profiling window wraps everything the daemon dispatches — the solver
kernels carry `jax.profiler.TraceAnnotation` names at their dispatch
seams (ops/spf.py, apsp/kernels.py), so the captured trace shows named
solve regions — into a TensorBoard-compatible trace directory via
`jax.profiler.start_trace` / `stop_trace`.

Design constraints, in order:

  - **Bounded.** A window has an explicit duration (clamped to
    [0.1s, 600s]) and is closed by whichever comes first: the scheduled
    expiry callback (the ctrl server arms one on the daemon loop), the
    next `status()` poll past the deadline, or an explicit `stop()`.
    There is no way to leave the profiler running unbounded.
  - **Degrade-safe.** `start`/`stop` failures (CPU-only builds, missing
    profiler support, unwritable directories) are captured into
    `last_error` and reported in the status record — a profiling request
    must never take down the daemon or a breaker-degraded solve path.
  - **Single-flight.** One window at a time; a second `start` while one
    is active is refused with the live status (the ctrl server
    additionally admission-controls the RPC like other expensive calls).
"""

from __future__ import annotations

import tempfile
import time
from typing import Any, Dict, Optional

# window duration clamp (seconds): long enough for a solve burst, short
# enough that a forgotten window cannot fill a disk
MIN_WINDOW_S = 0.1
MAX_WINDOW_S = 600.0


class ProfileController:
    """One daemon's bounded jax.profiler window state machine."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self.active = False
        self.out_dir: Optional[str] = None
        self.seconds = 0.0
        self.started_at: Optional[float] = None
        self.windows = 0  # windows ever started
        self.last_error: Optional[str] = None

    # -- lifecycle -------------------------------------------------------

    def start(
        self, out_dir: Optional[str] = None, seconds: float = 5.0
    ) -> Dict[str, Any]:
        """Open a bounded profiling window writing a TensorBoard trace
        under `out_dir` (a fresh temp dir when omitted). Returns the
        status record with `started` set; refusal (window already
        active, profiler unavailable) reports instead of raising."""
        self.maybe_expire()
        if self.active:
            return {
                "started": False,
                "error": "profiling window already active",
                **self.status(),
            }
        seconds = min(max(float(seconds), MIN_WINDOW_S), MAX_WINDOW_S)
        if not out_dir:
            out_dir = tempfile.mkdtemp(prefix="openr-profile-")
        try:
            import os

            import jax

            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
        except Exception as exc:
            # degrade-safe: CPU-only or profiler-less builds report the
            # failure in-band; the daemon keeps serving
            self.last_error = f"{type(exc).__name__}: {exc}"
            return {
                "started": False,
                "error": self.last_error,
                **self.status(),
            }
        self.active = True
        self.out_dir = out_dir
        self.seconds = seconds
        self.started_at = self._clock()
        self.windows += 1
        return {"started": True, **self.status()}

    def stop(self) -> Dict[str, Any]:
        """Close the window now (idempotent)."""
        if self.active:
            self._stop_trace()
        return self.status()

    def maybe_expire(self) -> None:
        """Close the window if its deadline passed — called by the
        scheduled expiry, by `status()` polls and by `start()`, so the
        bound holds even when no timer fired."""
        if (
            self.active
            and self.started_at is not None
            and self._clock() - self.started_at >= self.seconds
        ):
            self._stop_trace()

    def _stop_trace(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:
            self.last_error = f"{type(exc).__name__}: {exc}"
        self.active = False

    # -- read surface ----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        self.maybe_expire()
        remaining = 0.0
        if self.active and self.started_at is not None:
            remaining = max(
                0.0, self.seconds - (self._clock() - self.started_at)
            )
        return {
            "active": self.active,
            "out_dir": self.out_dir,
            "seconds": self.seconds,
            "remaining_s": round(remaining, 3),
            "windows": self.windows,
            "last_error": self.last_error,
        }
