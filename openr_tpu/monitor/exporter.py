"""Continuous metrics export: Prometheus text exposition + push loop.

Until now every metric existed only at the instant a ctrl client asked
(`breeze monitor counters`), so perf/robustness claims were demonstrable
over single flaps only. This module turns the registry continuous:

  - `render_metrics_text(...)` renders the full counter/histogram
    registry — plus the convergence rollup's cumulative-vs-windowed
    split — in Prometheus text exposition format (one `# TYPE` header
    per family, log-bucket histograms as cumulative `_bucket{le=...}`
    series). `parse_metrics_text` is its inverse, used by round-trip
    tests and the soak harness's scrape loop.
  - The ctrl server serves it as `getMetricsText` and as a plain
    HTTP-ish `GET /metrics` handler on the same port, so a stock
    Prometheus scraper (or `curl`) can poll a daemon with zero extra
    listeners.
  - `MetricsExporter` optionally *pushes* the rendered text on an
    interval to a configurable sink — `host:port` (TCP) or a file path
    (atomic replace) — with exponential backoff on failure
    (`monitor_config.exporter_push_{target,interval_s}`).

The exporter reads `Monitor.get_cumulative_histograms()`, the
non-resetting view: a scrape racing a `--reset` histogram snapshot from
another consumer still exports lifetime-cumulative distributions
(docs/Monitoring.md "reset-on-read vs the exporter").
"""

from __future__ import annotations

import asyncio
import os
import re
from typing import Any, Dict, Optional

from openr_tpu.monitor.report import ConvergenceRollup
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils.backoff import ExponentialBackoff
from openr_tpu.utils.counters import (
    CountersMixin,
    Histogram,
    HistogramsMixin,
)

PROM_PREFIX = "openr_"

# counter names that are point-in-time readings, not monotone totals
_GAUGE_MARKERS = (
    "_last",
    "_active",
    ".num_routes",
    ".num_unicast_routes",
    ".num_mpls_routes",
    ".mesh_devices",
    ".uptime.seconds",
    ".improved_last",
)

_INVALID_CHARS = re.compile(r"[^a-zA-Z0-9_]")


def prom_name(name: str) -> str:
    """Registry name -> Prometheus metric name (dots collapse to
    underscores under the `openr_` namespace); deterministic and
    injective over the `<module>.<name>` vocabulary."""
    return PROM_PREFIX + _INVALID_CHARS.sub("_", name)


def _is_gauge(name: str) -> bool:
    return name.endswith(_GAUGE_MARKERS)


def _fmt(value: float) -> str:
    if float(value) == int(value):
        return str(int(value))
    return repr(float(value))


def _labels(node_name: str, extra: str = "") -> str:
    parts = []
    if node_name:
        escaped = (
            node_name.replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )
        parts.append(f'node="{escaped}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_metrics_text(
    counters: Dict[str, int],
    histograms: Dict[str, Histogram],
    *,
    node_name: str = "",
    rollup: Optional[ConvergenceRollup] = None,
) -> str:
    """Full registry in Prometheus text exposition format (version 0.0.4):
    every counter as a counter/gauge family, every Histogram as a native
    prometheus histogram (cumulative `_bucket{le=...}` over the nonzero
    log buckets, `_sum`, `_count`), plus — when a rollup rides along —
    the cumulative-vs-windowed convergence split: the all-events-since-
    start total next to the newest window's summary gauges."""
    out = []
    for name in sorted(counters):
        pname = prom_name(name)
        kind = "gauge" if _is_gauge(name) else "counter"
        out.append(f"# TYPE {pname} {kind}")
        out.append(f"{pname}{_labels(node_name)} {_fmt(counters[name])}")
    for name in sorted(histograms):
        hist = histograms[name]
        pname = prom_name(name)
        out.append(f"# TYPE {pname} histogram")
        cum = 0
        for i, c in enumerate(hist.buckets):
            if not c:
                continue
            cum += c
            le_label = 'le="%s"' % _fmt(Histogram.bucket_bounds(i)[1])
            out.append(
                f"{pname}_bucket{_labels(node_name, le_label)} {cum}"
            )
        inf_label = 'le="+Inf"'
        out.append(
            f"{pname}_bucket{_labels(node_name, inf_label)} {hist.count}"
        )
        out.append(f"{pname}_sum{_labels(node_name)} {_fmt(hist.sum)}")
        out.append(f"{pname}_count{_labels(node_name)} {hist.count}")
    if rollup is not None:
        base = PROM_PREFIX + "monitor_rollup"
        out.append(f"# TYPE {base}_events_total counter")
        out.append(
            f"{base}_events_total{_labels(node_name)} "
            f"{rollup.events_total}"
        )
        out.append(f"# TYPE {base}_window_seconds gauge")
        out.append(
            f"{base}_window_seconds{_labels(node_name)} "
            f"{_fmt(rollup.window_s)}"
        )
        last = rollup.last_window()
        if last is not None:
            wname = PROM_PREFIX + "convergence_window"
            out.append(f"# TYPE {wname}_events gauge")
            out.append(
                f"{wname}_events{_labels(node_name)} {last['events']}"
            )
            total = last["stages"].get(ConvergenceRollup.TOTAL_STAGE)
            if total is not None:
                out.append(f"# TYPE {wname}_e2e_ms gauge")
                quantiles = (
                    ("p50", total.percentile(50)),
                    ("p95", total.percentile(95)),
                    ("max", total.max or 0.0),
                )
                for q, value in quantiles:
                    q_label = 'q="%s"' % q
                    out.append(
                        f"{wname}_e2e_ms{_labels(node_name, q_label)} "
                        f"{_fmt(value)}"
                    )
    return "\n".join(out) + "\n"


_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)


def parse_metrics_text(text: str) -> Dict[str, Any]:
    """Inverse of render_metrics_text: validates exposition-format syntax
    and returns {"types": {family: kind}, "samples": {name: {labelstr:
    value}}, "counters": {family: value}, "gauges": {...},
    "histograms": {family: {"count", "sum", "buckets": {le: cum}}}}
    (single-node exports: the node label is ignored for the scalar
    views). Raises ValueError on malformed lines."""
    types: Dict[str, str] = {}
    samples: Dict[str, Dict[str, float]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _LINE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line {lineno}: {line!r}")
        value = float(m.group("value").replace("+Inf", "inf"))
        samples.setdefault(m.group("name"), {})[
            m.group("labels") or ""
        ] = value

    def _first(series: Dict[str, float]) -> float:
        return next(iter(series.values()))

    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    histograms: Dict[str, Dict[str, Any]] = {}
    for family, kind in types.items():
        if kind == "histogram":
            buckets: Dict[str, float] = {}
            for labels, value in samples.get(family + "_bucket", {}).items():
                le = dict(
                    pair.split("=", 1)
                    for pair in labels.split(",")
                    if "=" in pair
                ).get("le", '""')
                buckets[le.strip('"')] = value
            histograms[family] = {
                "count": _first(samples.get(family + "_count", {"": 0.0})),
                "sum": _first(samples.get(family + "_sum", {"": 0.0})),
                "buckets": buckets,
            }
        elif kind == "counter" and family in samples:
            counters[family] = _first(samples[family])
        elif kind == "gauge" and family in samples:
            gauges[family] = _first(samples[family])
    return {
        "types": types,
        "samples": samples,
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
    }


class MetricsExporter(CountersMixin, HistogramsMixin):
    """Renders the monitor's registry on demand (scrape) and optionally
    pushes it on an interval (push). Registers with the monitor like any
    module, so its own overhead metrics (`monitor.exporter.*`) ride every
    export."""

    def __init__(
        self,
        monitor,
        *,
        push_target: Optional[str] = None,
        push_interval_s: float = 15.0,
        backoff_min_s: float = 0.5,
        backoff_max_s: float = 60.0,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.monitor = monitor
        self.push_target = push_target
        self.push_interval_s = push_interval_s
        self._backoff = ExponentialBackoff(backoff_min_s, backoff_max_s)
        self._loop = loop
        self._task: Optional[asyncio.Task] = None
        self._ensure_counters()
        self._ensure_histograms()

    # -- scrape --------------------------------------------------------

    def render(self) -> str:
        """One scrape: the full registry as exposition text. Uses the
        non-resetting cumulative histogram view, so a concurrent
        reset-on-read snapshot cannot drop samples from this consumer."""
        counters = self.monitor.get_counters()
        histograms = self.monitor.get_cumulative_histograms()
        rollup = getattr(self.monitor, "rollup", None)
        with self._timer("monitor.exporter.render_ms"):
            text = render_metrics_text(
                counters,
                histograms,
                node_name=self.monitor.node_name,
                rollup=rollup,
            )
        self._bump("monitor.exporter.scrapes")
        return text

    # -- push ----------------------------------------------------------

    def start(self) -> None:
        if self.push_target:
            loop = self._loop or asyncio.get_event_loop()
            self._task = loop.create_task(self._push_loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _push_loop(self) -> None:
        while True:
            try:
                text = self.render()
                fault_point("monitor.exporter.push", self)
                await self._push_once(text)
                self._bump("monitor.exporter.pushes")
                self._backoff.report_success()
                delay = self.push_interval_s
            except asyncio.CancelledError:
                return
            except Exception:
                self._bump("monitor.exporter.push_failures")
                self._backoff.report_error()
                delay = max(
                    self._backoff.get_time_remaining_until_retry(),
                    self._backoff.get_initial_backoff(),
                )
            await asyncio.sleep(delay)

    async def _push_once(self, text: str) -> None:
        host, port = _socket_target(self.push_target)
        if port is not None:
            writer = None
            try:
                _, writer = await asyncio.open_connection(host, port)
                writer.write(text.encode())
                await writer.drain()
            finally:
                if writer is not None:
                    writer.close()
            return
        # file sink: atomic replace so a scraping reader never sees a
        # half-written exposition
        tmp = f"{self.push_target}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(text)
        os.replace(tmp, self.push_target)


def _socket_target(target: str):
    """"host:port" -> (host, int port); anything else is a file path."""
    host, sep, port = (target or "").rpartition(":")
    if sep and host and port.isdigit():
        return host, int(port)
    return target, None
