"""Continuous metrics export: Prometheus text exposition + push loop.

Until now every metric existed only at the instant a ctrl client asked
(`breeze monitor counters`), so perf/robustness claims were demonstrable
over single flaps only. This module turns the registry continuous:

  - `render_metrics_text(...)` renders the full counter/histogram
    registry — plus the convergence rollup's cumulative-vs-windowed
    split — in Prometheus text exposition format (one `# TYPE` header
    per family, log-bucket histograms as cumulative `_bucket{le=...}`
    series). `parse_metrics_text` is its inverse, used by round-trip
    tests and the soak harness's scrape loop.
  - The ctrl server serves it as `getMetricsText` and as a plain
    HTTP-ish `GET /metrics` handler on the same port, so a stock
    Prometheus scraper (or `curl`) can poll a daemon with zero extra
    listeners.
  - `MetricsExporter` optionally *pushes* the rendered text on an
    interval to a configurable sink — `host:port` (TCP) or a file path
    (atomic replace) — with exponential backoff on failure
    (`monitor_config.exporter_push_{target,interval_s}`).

The exporter reads `Monitor.get_cumulative_histograms()`, the
non-resetting view: a scrape racing a `--reset` histogram snapshot from
another consumer still exports lifetime-cumulative distributions
(docs/Monitoring.md "reset-on-read vs the exporter").
"""

from __future__ import annotations

import asyncio
import os
import re
from typing import Any, Dict, Optional

from openr_tpu.monitor.report import ConvergenceRollup
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils.backoff import ExponentialBackoff
from openr_tpu.utils.counters import (
    CountersMixin,
    Histogram,
    HistogramsMixin,
)

PROM_PREFIX = "openr_"

# counter names that are point-in-time readings, not monotone totals
_GAUGE_MARKERS = (
    "_last",
    "_active",
    ".num_routes",
    ".num_unicast_routes",
    ".num_mpls_routes",
    ".num_stale_routes",
    ".num_dirty_prefixes",
    ".num_dirty_labels",
    ".synced",
    ".mesh_devices",
    ".uptime.seconds",
    ".improved_last",
)

_INVALID_CHARS = re.compile(r"[^a-zA-Z0-9_]")


def prom_name(name: str) -> str:
    """Registry name -> Prometheus metric name (dots collapse to
    underscores under the `openr_` namespace); deterministic and
    injective over the `<module>.<name>` vocabulary."""
    return PROM_PREFIX + _INVALID_CHARS.sub("_", name)


def _is_gauge(name: str) -> bool:
    return name.endswith(_GAUGE_MARKERS)


def _fmt(value: float) -> str:
    if float(value) == int(value):
        return str(int(value))
    return repr(float(value))


def _labels(node_name: str, extra: str = "") -> str:
    parts = []
    if node_name:
        escaped = (
            node_name.replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )
        parts.append(f'node="{escaped}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_metrics_text(
    counters: Dict[str, int],
    histograms: Dict[str, Histogram],
    *,
    node_name: str = "",
    rollup: Optional[ConvergenceRollup] = None,
) -> str:
    """Full registry in Prometheus text exposition format (version 0.0.4):
    every counter as a counter/gauge family, every Histogram as a native
    prometheus histogram (cumulative `_bucket{le=...}` over the nonzero
    log buckets, `_sum`, `_count`), plus — when a rollup rides along —
    the cumulative-vs-windowed convergence split: the all-events-since-
    start total next to the newest window's summary gauges."""
    out = []
    for name in sorted(counters):
        pname = prom_name(name)
        kind = "gauge" if _is_gauge(name) else "counter"
        out.append(f"# TYPE {pname} {kind}")
        out.append(f"{pname}{_labels(node_name)} {_fmt(counters[name])}")
    for name in sorted(histograms):
        hist = histograms[name]
        pname = prom_name(name)
        out.append(f"# TYPE {pname} histogram")
        cum = 0
        for i, c in enumerate(hist.buckets):
            if not c:
                continue
            cum += c
            le_label = 'le="%s"' % _fmt(Histogram.bucket_bounds(i)[1])
            out.append(
                f"{pname}_bucket{_labels(node_name, le_label)} {cum}"
            )
        inf_label = 'le="+Inf"'
        out.append(
            f"{pname}_bucket{_labels(node_name, inf_label)} {hist.count}"
        )
        out.append(f"{pname}_sum{_labels(node_name)} {_fmt(hist.sum)}")
        out.append(f"{pname}_count{_labels(node_name)} {hist.count}")
    if rollup is not None:
        base = PROM_PREFIX + "monitor_rollup"
        out.append(f"# TYPE {base}_events_total counter")
        out.append(
            f"{base}_events_total{_labels(node_name)} "
            f"{rollup.events_total}"
        )
        out.append(f"# TYPE {base}_window_seconds gauge")
        out.append(
            f"{base}_window_seconds{_labels(node_name)} "
            f"{_fmt(rollup.window_s)}"
        )
        last = rollup.last_window()
        if last is not None:
            wname = PROM_PREFIX + "convergence_window"
            out.append(f"# TYPE {wname}_events gauge")
            out.append(
                f"{wname}_events{_labels(node_name)} {last['events']}"
            )
            total = last["stages"].get(ConvergenceRollup.TOTAL_STAGE)
            if total is not None:
                out.append(f"# TYPE {wname}_e2e_ms gauge")
                quantiles = (
                    ("p50", total.percentile(50)),
                    ("p95", total.percentile(95)),
                    ("max", total.max or 0.0),
                )
                for q, value in quantiles:
                    q_label = 'q="%s"' % q
                    out.append(
                        f"{wname}_e2e_ms{_labels(node_name, q_label)} "
                        f"{_fmt(value)}"
                    )
    return "\n".join(out) + "\n"


_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)


def parse_metrics_text(text: str) -> Dict[str, Any]:
    """Inverse of render_metrics_text: validates exposition-format syntax
    and returns {"types": {family: kind}, "samples": {name: {labelstr:
    value}}, "counters": {family: value}, "gauges": {...},
    "histograms": {family: {"count", "sum", "buckets": {le: cum}}}}
    (single-node exports: the node label is ignored for the scalar
    views). Raises ValueError on malformed lines."""
    types: Dict[str, str] = {}
    samples: Dict[str, Dict[str, float]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _LINE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line {lineno}: {line!r}")
        value = float(m.group("value").replace("+Inf", "inf"))
        samples.setdefault(m.group("name"), {})[
            m.group("labels") or ""
        ] = value

    def _first(series: Dict[str, float]) -> float:
        return next(iter(series.values()))

    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    histograms: Dict[str, Dict[str, Any]] = {}
    for family, kind in types.items():
        if kind == "histogram":
            buckets: Dict[str, float] = {}
            for labels, value in samples.get(family + "_bucket", {}).items():
                le = dict(
                    pair.split("=", 1)
                    for pair in labels.split(",")
                    if "=" in pair
                ).get("le", '""')
                buckets[le.strip('"')] = value
            histograms[family] = {
                "count": _first(samples.get(family + "_count", {"": 0.0})),
                "sum": _first(samples.get(family + "_sum", {"": 0.0})),
                "buckets": buckets,
            }
        elif kind == "counter" and family in samples:
            counters[family] = _first(samples[family])
        elif kind == "gauge" and family in samples:
            gauges[family] = _first(samples[family])
    return {
        "types": types,
        "samples": samples,
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
    }


def histogram_from_parsed(parsed_hist: Dict[str, Any]) -> Histogram:
    """Rehydrate a live `Histogram` from a parsed exposition histogram
    ({"count", "sum", "buckets": {le: cumulative}}) — the scrape-side
    bridge into the sparse-codec/merge machinery. The exposition's `le`
    labels ARE the fixed log-bucket upper bounds (`render_metrics_text`
    emits `Histogram.bucket_bounds(i)[1]`), so each label maps back to
    its bucket index exactly; min/max are not carried on the wire and
    are approximated by the occupied bucket bounds."""
    out = Histogram()
    per_bucket: Dict[int, int] = {}
    prev_cum = 0.0
    for le, cum in sorted(
        parsed_hist.get("buckets", {}).items(),
        key=lambda kv: (
            float("inf") if kv[0] in ("+Inf", "inf") else float(kv[0])
        ),
    ):
        if le in ("+Inf", "inf"):
            continue  # the +Inf row re-states the total count
        upper = float(le)
        count = int(round(float(cum) - prev_cum))
        prev_cum = float(cum)
        if count <= 0:
            continue
        # a value epsilon under the upper bound lands in exactly the
        # bucket this `le` label was rendered from
        index = Histogram.bucket_index(upper * (1.0 - 1e-9))
        per_bucket[index] = per_bucket.get(index, 0) + count
    for index, count in per_bucket.items():
        out.buckets[index] = count
    out.count = int(parsed_hist.get("count", 0) or sum(per_bucket.values()))
    out.sum = float(parsed_hist.get("sum", 0.0))
    if per_bucket:
        out.min = Histogram.bucket_bounds(min(per_bucket))[0]
        out.max = Histogram.bucket_bounds(max(per_bucket))[1]
    elif out.count:
        out.min, out.max = 0.0, 0.0
    return out


class CounterEpochTracker:
    """Typed counter-reset detection over successive scrapes of one fleet.

    A restarted daemon re-exports every counter from zero. Consumers that
    difference consecutive scrapes (rate computation, the soak harness's
    monotonicity check, the fleet observer's interval rules) used to see
    that as a monotonicity *violation* and had to forgive it ad hoc.
    This tracker makes the reset a first-class **epoch**: `observe`
    compares a node's counter map against its previous scrape and
    returns, per scrape,

      - `epoch`: the node's epoch ordinal (bumped on every detected
        reset — Prometheus `rate()` semantics: any decrease of any
        counter is a reset, because counters never legitimately go
        backwards);
      - `reset`: whether THIS observation opened a new epoch;
      - `decreased`: the counter names that went backwards (evidence);
      - `deltas`: per-counter increments valid *within* the epoch — on a
        reset the new absolute values ARE the deltas (restart-from-zero
        rebase), so rates never go negative and never double-count.

    The caller decides attribution: a reset inside a known restart
    window is expected churn; a reset with no restart to blame is the
    violation the old check was really after.
    """

    def __init__(self) -> None:
        self._prev: Dict[str, Dict[str, float]] = {}
        self._epoch: Dict[str, int] = {}

    def epoch(self, node: str) -> int:
        return self._epoch.get(node, 0)

    def forget(self, node: str) -> None:
        """Drop a node's baseline without consuming a reset (the caller
        already knows the history is discontinuous — e.g. it re-dialed a
        brand-new emulator daemon object)."""
        self._prev.pop(node, None)

    def observe(
        self, node: str, counters: Dict[str, float]
    ) -> Dict[str, Any]:
        prev = self._prev.get(node)
        decreased = (
            []
            if prev is None
            else sorted(
                name
                for name, value in counters.items()
                if value < prev.get(name, 0.0)
            )
        )
        reset = bool(decreased)
        if reset:
            self._epoch[node] = self._epoch.get(node, 0) + 1
        base = {} if (reset or prev is None) else prev
        deltas = {
            name: value - base.get(name, 0.0)
            for name, value in counters.items()
        }
        self._prev[node] = dict(counters)
        return {
            "epoch": self._epoch.get(node, 0),
            "reset": reset,
            "first": prev is None,
            "decreased": decreased,
            "deltas": deltas,
        }


def histogram_interval(
    prev: Optional[Dict[str, Any]], cur: Dict[str, Any]
) -> Dict[str, float]:
    """Per-interval stats from two successive *cumulative* parsed
    histograms (`parse_metrics_text` shape: {"count", "sum",
    "buckets": {le: cumulative}}): bucket-diff the scrapes and return
    {"count", "sum", "avg", "p95"} of just the samples recorded between
    them. A count that went backwards is a post-restart reset — the
    current cumulative state IS the interval (epoch rebase, same rule as
    CounterEpochTracker)."""
    if prev is not None and float(cur.get("count", 0)) < float(
        prev.get("count", 0)
    ):
        prev = None  # counter reset: new epoch, rebase on zero
    p_buckets = dict(prev.get("buckets", {})) if prev else {}
    count = float(cur.get("count", 0)) - (
        float(prev.get("count", 0)) if prev else 0.0
    )
    total = float(cur.get("sum", 0.0)) - (
        float(prev.get("sum", 0.0)) if prev else 0.0
    )
    if count <= 0:
        return {"count": 0.0, "sum": 0.0, "avg": 0.0, "p95": 0.0}

    def le_key(le: str) -> float:
        return float("inf") if le in ("+Inf", "inf") else float(le)

    diffs = []  # (upper bound, interval cumulative count)
    for le, cum in cur.get("buckets", {}).items():
        d = float(cum) - float(p_buckets.get(le, 0.0))
        diffs.append((le_key(le), max(d, 0.0)))
    diffs.sort(key=lambda x: x[0])
    rank = 0.95 * count
    p95 = 0.0
    prev_bound = 0.0
    for bound, cum_d in diffs:
        if cum_d >= rank:
            # clamp +Inf to the last finite bound (the log-bucket
            # geometry keeps finite buckets up to multi-hour tails)
            p95 = prev_bound if bound == float("inf") else bound
            break
        if bound != float("inf"):
            prev_bound = bound
    else:
        p95 = prev_bound
    return {
        "count": count,
        "sum": total,
        "avg": total / count,
        "p95": p95,
    }


class MetricsExporter(CountersMixin, HistogramsMixin):
    """Renders the monitor's registry on demand (scrape) and optionally
    pushes it on an interval (push). Registers with the monitor like any
    module, so its own overhead metrics (`monitor.exporter.*`) ride every
    export."""

    def __init__(
        self,
        monitor,
        *,
        push_target: Optional[str] = None,
        push_interval_s: float = 15.0,
        backoff_min_s: float = 0.5,
        backoff_max_s: float = 60.0,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.monitor = monitor
        self.push_target = push_target
        self.push_interval_s = push_interval_s
        self._backoff = ExponentialBackoff(backoff_min_s, backoff_max_s)
        self._loop = loop
        self._task: Optional[asyncio.Task] = None
        self._ensure_counters()
        self._ensure_histograms()

    # -- scrape --------------------------------------------------------

    def render(self) -> str:
        """One scrape: the full registry as exposition text. Uses the
        non-resetting cumulative histogram view, so a concurrent
        reset-on-read snapshot cannot drop samples from this consumer."""
        counters = self.monitor.get_counters()
        histograms = self.monitor.get_cumulative_histograms()
        rollup = getattr(self.monitor, "rollup", None)
        with self._timer("monitor.exporter.render_ms"):
            text = render_metrics_text(
                counters,
                histograms,
                node_name=self.monitor.node_name,
                rollup=rollup,
            )
        self._bump("monitor.exporter.scrapes")
        return text

    # -- push ----------------------------------------------------------

    def start(self) -> None:
        if self.push_target:
            loop = self._loop or asyncio.get_event_loop()
            self._task = loop.create_task(self._push_loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _push_loop(self) -> None:
        while True:
            try:
                text = self.render()
                fault_point("monitor.exporter.push", self)
                await self._push_once(text)
                self._bump("monitor.exporter.pushes")
                self._backoff.report_success()
                delay = self.push_interval_s
            except asyncio.CancelledError:
                return
            except Exception:
                self._bump("monitor.exporter.push_failures")
                self._backoff.report_error()
                delay = max(
                    self._backoff.get_time_remaining_until_retry(),
                    self._backoff.get_initial_backoff(),
                )
            await asyncio.sleep(delay)

    async def _push_once(self, text: str) -> None:
        host, port = _socket_target(self.push_target)
        if port is not None:
            writer = None
            try:
                _, writer = await asyncio.open_connection(host, port)
                writer.write(text.encode())
                await writer.drain()
            finally:
                if writer is not None:
                    writer.close()
            return
        # file sink: atomic replace so a scraping reader never sees a
        # half-written exposition
        tmp = f"{self.push_target}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(text)
        os.replace(tmp, self.push_target)


def _socket_target(target: str):
    """"host:port" -> (host, int port); anything else is a file path."""
    host, sep, port = (target or "").rpartition(":")
    if sep and host and port.isdigit():
        return host, int(port)
    return target, None
