"""MEM_SMOKE tier-1 smoke (the device-memory sibling of FLEET_SMOKE):
a small VirtualNetwork of TPU-backend nodes with the fleet observer
attached, one injected ledger leak, and the observer must raise
*exactly* one `device_memory` breach — correct rule, the leaking
structure named in the attribution — with well-formed ledger forensics
and a `breeze decision memory` round-trip.

Sequence:

  1. an N-node line (every node on the supervised TPU solver backend,
     so real ledger registrations flow) converges; the observer scrapes
     every node with the leak-trend rule ARMED at a zero budget; a
     clean flap runs and NO rule may fire — solves register and release
     device structures constantly, and an exact ledger shows none of
     that churn as a leak (false-positive guard);
  2. ONE fault is injected: `solver.mem.retain` (monitor/memledger.py)
     pins the victim's next released buffer live — released by the
     solver, never freed by the ledger: the canonical leak signature;
  3. a second flap runs; the victim's solver rebuilds, the release is
     pinned, `decision.mem.retained` ticks, and the observer's
     `device_memory` rule must breach exactly once with the pinned
     structure named in the attribution, a forensics dump embedding the
     ledger snapshot (exact accounting, the retained entry visible in
     the victim's area), and the breach LogSample carrying the dump id.

The ledger is process-global (one device pool per process), so every
node's `decision.mem.*` series show the incident — but each node's
scrape picks the shared counters up in a different sweep, so WHICH node
a tick elects as worst offender is scrape-timing dependent. Three
mechanisms keep "exactly one breach" deterministic anyway: the rule
yields one worst-offender finding per tick (`eval_device_memory`), the
retain signal is judged over a trailing window (one pin stays visible
to every node's evaluation, then ages out), and the observer holds ONE
episode per pool-wide rule kind (`POOL_WIDE_RULES`) rather than per
node. The elected node's identity is NOT asserted — only that the one
finding names the leaked structure and carries well-formed forensics.

Topology size scales via MEM_SMOKE_NODES; returns a summary dict.
"""

from __future__ import annotations

import asyncio
import contextlib
import io
import json
import os
from typing import Any, Dict, List

from openr_tpu.fleet.observer import FleetConfig, FleetObserver
from openr_tpu.fleet.rules import SloConfig
from openr_tpu.monitor.memledger import MemLedger, get_ledger
from openr_tpu.testing.faults import FaultInjector, injected


def run_mem_smoke() -> Dict[str, Any]:
    from openr_tpu.cli.breeze import main as breeze_main
    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    n = max(3, int(os.environ.get("MEM_SMOKE_NODES", "3")))
    mid = n // 2
    # the leak is pinned to n0's area; the shared ledger means any node
    # may be elected to carry the finding (module docstring)
    victim = "n0"

    async def body() -> Dict[str, Any]:
        # the ledger is process-global and other tests may have left
        # entries behind: judge only what THIS smoke registers
        baseline_handles = {
            e["handle"] for e in get_ledger().snapshot()["entries"]
        }
        net = VirtualNetwork()
        for i in range(n):
            net.add_node(
                f"n{i}",
                loopback_prefix=f"10.{i}.0.0/24",
                # real ledger traffic needs the device solver path
                config_overrides={
                    "decision_config": {"solver_backend": "tpu"}
                },
            )
        await net.start_all()
        for i in range(n - 1):
            net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

        def converged() -> bool:
            for i in range(n):
                got = set(net.wrappers[f"n{i}"].programmed_prefixes())
                want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
                if not want.issubset(got):
                    return False
            return True

        def partitioned() -> bool:
            left = net.wrappers["n0"].programmed_prefixes()
            return f"10.{n - 1}.0.0/24" not in left

        observer = FleetObserver.for_network(
            net,
            config=FleetConfig(
                scrape_interval_s=0.15,
                eval_every=1,
                slo=SloConfig(
                    # the mem rule is under test; keep the latency rules
                    # from competing for the "exactly one" assertion
                    convergence_p95_budget_ms=60_000.0,
                    trend_min_windows=0,
                    # ARMED at zero budget: any pinned release breaches
                    mem_leak_slope_budget=0.0,
                    # live-bytes slope is legitimately noisy across a
                    # flap (buffers are released + re-registered); the
                    # deterministic leak signal is the retained counter,
                    # so leave the slope estimator unjudged
                    mem_leak_min_windows=10**6,
                ),
            ),
        )

        def flap():
            net.fail_link(
                f"n{mid}", f"if{mid}r", f"n{mid + 1}", f"if{mid + 1}l"
            )

        def heal():
            net.restore_link(
                f"n{mid}", f"if{mid}r", f"n{mid + 1}", f"if{mid + 1}l"
            )

        def _breeze_memory(port: int):
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = breeze_main(
                    ["--host", "127.0.0.1", "--port", str(port),
                     "decision", "memory", "--json"]
                )
            return rc, json.loads(buf.getvalue())

        leaked: List[Any] = []

        def _pin(ctx) -> None:
            ctx.retain = True
            leaked.append(ctx.entry)

        with injected(FaultInjector(seed=7)) as inj:
            try:
                await wait_until(converged, timeout=60.0)
                await observer.start()
                await wait_until(
                    lambda: observer.counters.get("fleet.stream_frames", 0)
                    >= n,
                    timeout=30.0,
                )
                # the solvers actually registered device structures
                await wait_until(
                    lambda: observer.store.series(
                        victim, "gauge.decision.mem.live_bytes_last"
                    )
                    != [],
                    timeout=30.0,
                )
                # phase 1: a clean flap — releases + re-registers churn
                # the ledger, and no rule may fire
                flap()
                await wait_until(partitioned, timeout=60.0)
                heal()
                await wait_until(converged, timeout=60.0)
                await asyncio.sleep(0.5)  # a few clean evaluation ticks
                clean_findings = len(observer.findings)

                # phase 2: ONE injected leak — the victim's next release
                # is pinned live by the ledger
                inj.arm(
                    "solver.mem.retain",
                    times=1,
                    when=lambda ctx: ctx.entry.area.endswith(
                        "/" + victim
                    ),
                    action=_pin,
                )
                flap()
                await wait_until(partitioned, timeout=60.0)
                await wait_until(
                    lambda: len(observer.findings) > clean_findings,
                    timeout=60.0,
                )
                heal()
                await wait_until(converged, timeout=60.0)
                fired = inj.fired("solver.mem.retain")

                # breeze round-trip against the victim's live ctrl port
                rc, breeze_snap = await asyncio.get_event_loop(
                ).run_in_executor(
                    None,
                    _breeze_memory,
                    net.wrappers[victim].ctrl_port,
                )
            finally:
                await observer.stop()
                await net.stop_all()

        report = observer.report()
        ledger = get_ledger()
        snap = ledger.snapshot()
        summary = {
            "nodes": n,
            "victim": victim,
            "clean_findings": clean_findings,
            "faults_fired": fired,
            "leaked_structure": leaked[0].structure if leaked else None,
            "leaked_bytes": leaked[0].nbytes if leaked else 0,
            "findings": [f.to_dict() for f in observer.findings],
            "samples": [s.values() for s in observer.samples],
            "forensics": observer.forensics,
            "ledger": snap,
            "breeze": breeze_snap,
            "report": report,
        }
        # -- the smoke's contract ----------------------------------------
        assert fired == 1, summary["faults_fired"]
        assert len(leaked) == 1, summary["leaked_structure"]
        assert clean_findings == 0, summary["findings"]
        assert len(observer.findings) == 1, summary["findings"]
        finding = observer.findings[0]
        assert finding.kind == "device_memory", finding.to_dict()
        nodes = {f"n{i}" for i in range(n)}
        assert finding.node in nodes, finding.to_dict()
        assert finding.evidence.get("retained", 0) >= 1, finding.to_dict()
        # the pinned structure is named in the attribution
        folded = MemLedger._fold_structure(leaked[0].structure)
        named = [s["structure"] for s in finding.attribution]
        assert folded in named, (folded, finding.to_dict())
        # the breach sample is typed and carries the forensics id
        sample = observer.samples[-1].values()
        assert sample["event"] == "FLEET_SLO_BREACH", sample
        assert sample["rule"] == "device_memory", sample
        assert sample["node"] == finding.node, sample
        # well-formed forensics: id linkage + embedded ledger snapshot
        assert len(observer.forensics) == 1, summary["forensics"]
        dump = observer.forensics[0]
        assert dump["id"] == finding.forensics_id, dump["id"]
        assert dump["id"] == sample["forensics_id"], dump["id"]
        assert dump["reason"] == "device_memory", dump
        mem = dump["device_memory"]
        assert mem is not None, dump
        assert mem["exact"], mem["totals"]
        totals = mem["totals"]
        assert (
            totals["registered_bytes"]
            == totals["live_bytes"] + totals["freed_bytes"]
        ), totals
        pinned = [e for e in mem["entries"] if e["retained"]]
        assert any(
            e["area"].endswith("/" + victim)
            and e["structure"] == leaked[0].structure
            for e in pinned
        ), pinned
        # breeze decision memory --json round-trips the same snapshot
        assert rc == 0, rc
        assert breeze_snap["exact"], breeze_snap["totals"]
        assert breeze_snap["totals"]["retained"] == totals["retained"], (
            breeze_snap["totals"],
            totals,
        )
        assert any(
            e["retained"] and e["structure"] == leaked[0].structure
            for e in breeze_snap["entries"]
        ), breeze_snap["entries"]
        # daemon teardown released everything the fleet registered
        # except the pinned entry (decision.stop -> solver.close)
        assert snap["exact"], snap["totals"]
        live_fleet = [
            e
            for e in snap["entries"]
            if e["handle"] not in baseline_handles
        ]
        assert all(e["retained"] for e in live_fleet), live_fleet
        assert any(
            e["structure"] == leaked[0].structure for e in live_fleet
        ), live_fleet
        # the observer actually scraped the whole fleet, cleanly
        counters = report["counters"]
        assert counters.get("fleet.scrapes", 0) >= 2 * n, counters
        assert counters.get("fleet.scrape_errors", 0) == 0, counters
        checks = report["verdict"]["checks"]
        assert checks["store_accounting"]["ok"], checks
        assert checks["scrape_health"]["ok"], checks
        assert not checks["no_slo_breach"]["ok"], checks
        return summary

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()
