"""Bounded, crash-safe state journal: every KvStore publication delta and
every DecisionRouteUpdate, recorded per node.

Where the flight recorder keeps *metrics about* solves and the exporter
keeps rollups, the journal keeps the **state history itself** — the raw
deltas that produced the LSDB and RIB — so "what did the RIB look like at
T" and "which publication made this route exist" are answerable after the
fact. Recording rides the same ReplicateQueue fan-out the streaming layer
uses (`get_reader()` per source; StreamManager pattern), so cost is
O(changes): the journal sees exactly the deltas the daemon already
produced, never a full-state walk. A sampled-overhead guard mirrors the
flight recorder's: every record is kept, but only every Nth record takes
`perf_counter` stamps into ``journal.record_ms`` — measuring the tap must
not become the tap's cost.

In-memory shape: a bounded ring of `JournalRecord`s plus a **compacted
base** — when the ring overflows, the oldest record is folded into the
base (publication records fold into a per-area key→Value map, which is
lossless for replay because KvStore is a CRDT map: replaying the folded
map as one synthetic publication reproduces the same LSDB as replaying
the evicted history; RIB records fold with the delta algebra,
`apply_route_delta`). Accounting invariant (like the flight recorder's):
``journal.records == retained + journal.evicted``.

On disk (optional ``path``): a `RecordLog` (the PR 14 journaled-file
framing, shared with PersistentStore) holding one snapshot record (the
base) followed by appended journal records. Appends are batched on a
debounced flush and fsynced per batch — a crash loses at most the last
unflushed interval, and a torn tail recovers to the longest well-formed
record prefix exactly like the config store. When the appended tail
outgrows ``max(snapshot_bytes, min_compact_bytes)`` the next flush
compacts: one atomic rewrite of base + ring.
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

from openr_tpu.configstore import record_log
from openr_tpu.journal import codec
from openr_tpu.messaging import QueueClosedError
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin

_MAGIC = b"ONRSJ1\n"
_REC_SNAPSHOT, _REC_RECORD = 0, 1


@dataclass
class JournalConfig:
    enabled: bool = False
    ring_size: int = 4096  # in-memory record ring bound
    key_history: int = 16  # per-(area,key) history entries retained
    sample_every: int = 16  # Nth-record timing guard (0 disables)
    path: Optional[str] = None  # durable log; None = memory only
    flush_interval_s: float = 0.2  # append-batch debounce
    min_compact_bytes: int = 65536  # journal tail size forcing compaction


@dataclass
class JournalRecord:
    seq: int
    ts: float  # wall clock (time.time()) — the replay/query time axis
    kind: str  # "pub" | "rib"
    payload: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "ts": self.ts,
            "kind": self.kind,
            "payload": self.payload,
        }


class StateJournal(CountersMixin, HistogramsMixin):
    """Per-node state journal: recorder + compacted base + durable log.

    Registered with the Monitor as the ``journal`` module so ``journal.*``
    counters land in every scrape (docs/Monitoring.md "State journal").
    """

    def __init__(
        self,
        node_name: str,
        config: Optional[JournalConfig] = None,
        *,
        kvstore_updates=None,
        route_updates=None,
        solver_flags: Optional[Dict[str, Any]] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.node_name = node_name
        self.config = config or JournalConfig()
        self._kvstore_updates = kvstore_updates
        self._route_updates = route_updates
        # CPU-oracle flags for the replay audit — must match Decision's
        # so re-derived routes are comparable to the recorded ones
        self.solver_flags = dict(solver_flags or {})
        self._loop = loop
        self._ring: Deque[JournalRecord] = deque()
        # compacted base: everything evicted from the ring, folded
        self._base_keys: Dict[str, Dict[str, Any]] = {}  # area -> key -> Value jsonable
        self._base_rib: Dict[str, Dict[str, Any]] = {"unicast": {}, "mpls": {}}
        self._base_seq = 0
        self._base_ts = 0.0
        self._seq = 0
        # bounded per-(area,key) publication history for `kvstore history`
        self._key_history: Dict[Tuple[str, str], Deque[Dict[str, Any]]] = {}
        # durable log state (PersistentStore geometry discipline)
        self._log: Optional[record_log.RecordLog] = None
        self._pending: List[bytes] = []
        self._flush_timer: Optional[asyncio.TimerHandle] = None
        self._snapshot_bytes = 0
        self._journal_bytes = 0
        self._needs_compact = True
        self._tasks: List[asyncio.Task] = []
        self._started = False
        self._ensure_counters()
        self._ensure_histograms()
        if self.config.path:
            self._log = record_log.RecordLog(
                self.config.path, _MAGIC, (_REC_SNAPSHOT, _REC_RECORD)
            )
            self._load_from_disk()

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    # ------------------------------------------------------------------
    # lifecycle (StreamManager dispatch-task pattern)
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._started or not self.config.enabled:
            return
        self._started = True
        if self._kvstore_updates is not None:
            self._tasks.append(
                self.loop().create_task(
                    self._consume(
                        self._kvstore_updates.get_reader(),
                        self.record_publication,
                    )
                )
            )
        if self._route_updates is not None:
            self._tasks.append(
                self.loop().create_task(
                    self._consume(
                        self._route_updates.get_reader(),
                        self.record_route_update,
                    )
                )
            )

    def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        self._tasks.clear()
        self._started = False
        self.flush()

    async def _consume(self, reader, recorder) -> None:
        try:
            while True:
                item = await reader.get()
                try:
                    recorder(item)
                except Exception:
                    # a malformed item must not kill the tap
                    import logging

                    logging.getLogger(__name__).exception(
                        "journal record failed"
                    )
                    self._bump("journal.record_errors")
        except (QueueClosedError, asyncio.CancelledError):
            return
        finally:
            reader.close()

    # ------------------------------------------------------------------
    # recording (hot path: O(changes), host-side only)
    # ------------------------------------------------------------------

    def record_publication(self, pub) -> None:
        t0 = self._maybe_t0()
        payload = codec.encode_publication(pub)
        rec = self._record("pub", payload)
        self._bump("journal.pub_records")
        for key, val in payload["key_vals"].items():
            self._push_history(
                pub.area,
                key,
                {
                    "seq": rec.seq,
                    "ts": rec.ts,
                    "version": val.get("version"),
                    "ttl_version": val.get("ttl_version"),
                    "originator_id": val.get("originator_id"),
                    "deleted": False,
                },
            )
        for key in payload["expired_keys"]:
            self._push_history(
                pub.area,
                key,
                {
                    "seq": rec.seq,
                    "ts": rec.ts,
                    "version": None,
                    "ttl_version": None,
                    "originator_id": None,
                    "deleted": True,
                },
            )
        self._maybe_observe(t0)

    def record_route_update(self, update) -> None:
        if update.empty():
            return
        t0 = self._maybe_t0()
        self._record("rib", codec.encode_route_update(update))
        self._bump("journal.rib_records")
        self._maybe_observe(t0)

    def _record(self, kind: str, payload: Dict[str, Any]) -> JournalRecord:
        self._seq += 1
        rec = JournalRecord(self._seq, time.time(), kind, payload)
        self._ring.append(rec)
        self._bump("journal.records")
        while len(self._ring) > max(self.config.ring_size, 1):
            self._evict(self._ring.popleft())
        if self._log is not None:
            self._pending.append(
                record_log.pack(
                    _REC_RECORD, b"", json.dumps(rec.to_dict()).encode()
                )
            )
            self._schedule_flush()
        return rec

    def _maybe_t0(self) -> Optional[float]:
        n = self.config.sample_every
        if n <= 0 or self.counters.get("journal.records", 0) % n:
            return None
        return time.perf_counter()

    def _maybe_observe(self, t0: Optional[float]) -> None:
        if t0 is not None:
            self._observe(
                "journal.record_ms", (time.perf_counter() - t0) * 1e3
            )

    def _push_history(self, area: str, key: str, entry: Dict[str, Any]) -> None:
        hist = self._key_history.get((area, key))
        if hist is None:
            hist = deque(maxlen=max(self.config.key_history, 1))
            self._key_history[(area, key)] = hist
        hist.append(entry)

    # ------------------------------------------------------------------
    # eviction: fold the oldest record into the compacted base
    # ------------------------------------------------------------------

    def _evict(self, rec: JournalRecord) -> None:
        if rec.kind == "pub":
            area_keys = self._base_keys.setdefault(
                rec.payload.get("area", "0"), {}
            )
            for key, val in rec.payload.get("key_vals", {}).items():
                area_keys[key] = val
            for key in rec.payload.get("expired_keys", []):
                area_keys.pop(key, None)
        else:
            unicast = self._base_rib["unicast"]
            mpls = self._base_rib["mpls"]
            for entry in rec.payload.get("unicast_update", []):
                unicast[entry["prefix"]] = entry
            for prefix in rec.payload.get("unicast_delete", []):
                unicast.pop(prefix, None)
            for entry in rec.payload.get("mpls_update", []):
                mpls[str(entry["label"])] = entry
            for label in rec.payload.get("mpls_delete", []):
                mpls.pop(str(label), None)
        self._base_seq = rec.seq
        self._base_ts = rec.ts
        self._bump("journal.evicted")

    # ------------------------------------------------------------------
    # durable log (PersistentStore write-behind discipline)
    # ------------------------------------------------------------------

    def flush(self) -> None:
        if self._flush_timer is not None:
            self._flush_timer.cancel()
            self._flush_timer = None
        self._flush_to_disk()

    def _schedule_flush(self, retry: bool = False) -> None:
        try:
            loop = self._loop or asyncio.get_running_loop()
        except RuntimeError:
            if not retry:
                self._flush_to_disk()  # no loop (tools): write now
            return
        if self._flush_timer is not None:
            return
        self._flush_timer = loop.call_later(
            self.config.flush_interval_s, self._flush_cb
        )

    def _flush_cb(self) -> None:
        self._flush_timer = None
        self._flush_to_disk()

    def _flush_to_disk(self) -> None:
        """One durable write: append the pending batch, or compact when
        the tail outgrew the snapshot (or is suspect). Failures keep the
        batch pending and retry on the flush interval — journaling must
        never crash the daemon."""
        if self._log is None or (not self._pending and not self._needs_compact):
            return
        t0 = time.perf_counter()
        try:
            blob = b"".join(self._pending)
            if (
                self._needs_compact
                or not self._log.exists()
                or self._journal_bytes + len(blob)
                >= max(self._snapshot_bytes, self.config.min_compact_bytes)
            ):
                self._write_snapshot()
            else:
                self._log.append(blob)
                self._pending.clear()
                self._journal_bytes += len(blob)
                self._bump("journal.appends")
        except Exception:
            self._bump("journal.write_failures")
            import logging

            logging.getLogger(__name__).exception(
                "journal write failed; retrying"
            )
            self._schedule_flush(retry=True)
            return
        self._observe("journal.flush_ms", (time.perf_counter() - t0) * 1e3)

    def _write_snapshot(self) -> None:
        """Atomic rewrite: base snapshot + the live ring re-appended."""
        snap = {
            "seq": self._base_seq,
            "ts": self._base_ts,
            "keys": self._base_keys,
            "rib": self._base_rib,
        }
        payload = json.dumps(snap, sort_keys=True).encode()
        blob = record_log.pack(_REC_SNAPSHOT, b"", payload)
        blob += b"".join(
            record_log.pack(
                _REC_RECORD, b"", json.dumps(rec.to_dict()).encode()
            )
            for rec in self._ring
        )
        self._log.rewrite(blob)
        self._pending.clear()
        self._snapshot_bytes = len(payload)
        self._journal_bytes = len(blob) - record_log.HEADER.size - len(payload)
        self._needs_compact = False
        self._bump("journal.snapshots")

    def _load_from_disk(self) -> None:
        if not self._log.exists():
            return
        try:
            records, truncated = self._log.scan()
        except record_log.BadMagicError:
            self._needs_compact = True
            return
        except Exception:
            self._bump("journal.load_errors")
            self._needs_compact = True
            return
        for rec_type, _key, value in records:
            try:
                doc = json.loads(value)
            except Exception:
                truncated = True  # torn body
                break
            if rec_type == _REC_SNAPSHOT:
                self._base_keys = doc.get("keys", {})
                self._base_rib = doc.get(
                    "rib", {"unicast": {}, "mpls": {}}
                )
                self._base_seq = int(doc.get("seq", 0))
                self._base_ts = float(doc.get("ts", 0.0))
                self._ring.clear()
                self._seq = self._base_seq
            else:
                rec = JournalRecord(
                    int(doc["seq"]),
                    float(doc["ts"]),
                    doc["kind"],
                    doc.get("payload", {}),
                )
                self._ring.append(rec)
                self._seq = max(self._seq, rec.seq)
                self._bump("journal.records")
                while len(self._ring) > max(self.config.ring_size, 1):
                    self._evict(self._ring.popleft())
        # rebuild bounded key history: base keys at the base seq, then
        # ring publication records in order
        for area, keys in self._base_keys.items():
            for key, val in keys.items():
                self._push_history(
                    area,
                    key,
                    {
                        "seq": self._base_seq,
                        "ts": self._base_ts,
                        "version": val.get("version"),
                        "ttl_version": val.get("ttl_version"),
                        "originator_id": val.get("originator_id"),
                        "deleted": False,
                    },
                )
        for rec in self._ring:
            if rec.kind != "pub":
                continue
            area = rec.payload.get("area", "0")
            for key, val in rec.payload.get("key_vals", {}).items():
                self._push_history(
                    area,
                    key,
                    {
                        "seq": rec.seq,
                        "ts": rec.ts,
                        "version": val.get("version"),
                        "ttl_version": val.get("ttl_version"),
                        "originator_id": val.get("originator_id"),
                        "deleted": False,
                    },
                )
            for key in rec.payload.get("expired_keys", []):
                self._push_history(
                    area,
                    key,
                    {
                        "seq": rec.seq,
                        "ts": rec.ts,
                        "version": None,
                        "ttl_version": None,
                        "originator_id": None,
                        "deleted": True,
                    },
                )
        if truncated:
            self._bump("journal.load_truncations")
            self._needs_compact = True  # never append after garbage
        else:
            self._needs_compact = False

    # ------------------------------------------------------------------
    # query surfaces (ctrl handlers call these; all host-side)
    # ------------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {
            "enabled": self.config.enabled,
            "node": self.node_name,
            "retained": len(self._ring),
            "base_seq": self._base_seq,
            "last_seq": self._seq,
            "base_ts": self._base_ts,
            "ring_size": self.config.ring_size,
            "path": self.config.path,
            "counters": dict(self.counters),
        }

    def tail(self, last_n: int = 32) -> List[Dict[str, Any]]:
        n = max(int(last_n), 0)
        recs = list(self._ring)[-n:] if n else []
        return [rec.to_dict() for rec in recs]

    def key_history(
        self, key: str, area: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for (a, k), hist in self._key_history.items():
            if k != key or (area is not None and a != area):
                continue
            out.extend(dict(entry, area=a, key=k) for entry in hist)
        out.sort(key=lambda e: e["seq"])
        return out

    def records(self) -> List[JournalRecord]:
        return list(self._ring)

    def base(self) -> Dict[str, Any]:
        return {
            "seq": self._base_seq,
            "ts": self._base_ts,
            "keys": self._base_keys,
            "rib": self._base_rib,
        }

    # ------------------------------------------------------------------
    # replay entry points (journal/replay.py does the work)
    # ------------------------------------------------------------------

    def replayer(self):
        from openr_tpu.journal.replay import JournalReplay

        return JournalReplay(
            self.node_name, self.base(), self.records(), self.solver_flags
        )

    def _timed_replay(self, fn):
        t0 = time.perf_counter()
        try:
            return fn(self.replayer())
        finally:
            self._bump("journal.replays")
            self._observe(
                "journal.replay_ms", (time.perf_counter() - t0) * 1e3
            )

    def replay_at(self, at: Optional[float] = None):
        """Reconstructed (LSDB folder, RIB, meta) at instant `at`."""
        return self._timed_replay(lambda r: r.replay(at))

    def verify_replay(self, at: Optional[float] = None) -> Dict[str, Any]:
        """Standing correctness audit: re-derive routes through the CPU
        oracle over the reconstructed LSDB and diff against the journaled
        RIB. Advisory — exact at quiescent instants with no RibPolicy."""
        return self._timed_replay(lambda r: r.verify(at))

    def explain_route(
        self, prefix: str, at: Optional[float] = None
    ) -> Dict[str, Any]:
        return self._timed_replay(lambda r: r.explain_route(prefix, at))

    def rib_diff(
        self, from_ts: Optional[float], to_ts: Optional[float]
    ) -> Dict[str, Any]:
        return self._timed_replay(lambda r: r.rib_diff(from_ts, to_ts))
