"""State journal: journaled state history, deterministic replay, and
route provenance (docs/Journal.md).

  - `StateJournal` (journal.py): the per-node recorder — every KvStore
    publication delta and every DecisionRouteUpdate into a bounded ring
    with a compacted base and an optional crash-safe on-disk log (the
    PR 14 `RecordLog` framing shared with PersistentStore).
  - `JournalReplay` / `LsdbFolder` (replay.py): reconstruct LSDB + RIB
    at any journaled instant, audit the reconstruction against the CPU
    oracle, and walk route → keys → publication provenance chains.
"""

from openr_tpu.journal.journal import (
    JournalConfig,
    JournalRecord,
    StateJournal,
)
from openr_tpu.journal.replay import JournalReplay, LsdbFolder, resolve_ts

__all__ = [
    "JournalConfig",
    "JournalRecord",
    "JournalReplay",
    "LsdbFolder",
    "StateJournal",
    "resolve_ts",
]
