"""Deterministic replay: reconstruct the LSDB and RIB at any journaled
instant, and answer provenance queries over the reconstruction.

`LsdbFolder` mirrors Decision's publication fold exactly (decision.py
`process_publication` → `_process_key` → `_update_node_prefix_database`):
adj values load with copy-on-write area stamping, prefix values aggregate
per (node, area) with per-prefix keys overriding full-db keys and the
self-redistribution filter applied, expired keys delete the matching db.
The one intentional difference: ordered-FIB hold TTLs are replayed as
zero — holds only stage *when* an update lands, and replay targets the
settled state, not the schedule.

The base-seeding trick that keeps the journal bounded: KvStore is a CRDT
**map**, so folding evicted publication records into a key→Value map and
replaying that map as one synthetic publication reproduces the same
LSDB/aggregation state as replaying the evicted history record by record.
RIB records fold with the delta algebra (`apply_route_delta`), whose
round-trip identity PR 7 proved. replay(T) therefore equals the live
RIB snapshot at T for any T the ring still brackets — the standing
correctness audit `verify()` re-derives routes through the CPU oracle
over the reconstructed LSDB and diffs against the journaled RIB
(advisory: exact at quiescent instants with no active RibPolicy).
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from openr_tpu.journal import codec
from openr_tpu.journal.journal import JournalRecord
from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.solver import SpfSolver, get_route_delta
from openr_tpu.solver.routes import DecisionRouteDb, apply_route_delta
from openr_tpu.types import (
    ADJ_DB_MARKER,
    PREFIX_DB_MARKER,
    AdjacencyDatabase,
    IpPrefix,
    PrefixDatabase,
    Publication,
    parse_prefix_key,
)
from openr_tpu.utils import serializer

# the CPU-oracle flags replay accepts (must match Decision's so
# re-derived routes are comparable to the recorded ones)
_SOLVER_FLAGS = (
    "enable_v4",
    "compute_lfa_paths",
    "enable_ordered_fib",
    "bgp_dry_run",
    "bgp_use_igp_metric",
)


def resolve_ts(t: Optional[float]) -> Optional[float]:
    """CLI time axis: None = latest, t >= 0 = unix seconds, t < 0 =
    seconds relative to now (`--at -40` = forty seconds ago)."""
    if t is None:
        return None
    t = float(t)
    return time.time() + t if t < 0 else t


class LsdbFolder:
    """Decision's LSDB fold, replayed offline (no debounce, no solver)."""

    def __init__(self, my_node_name: str) -> None:
        self.my_node_name = my_node_name
        self.area_link_states: Dict[str, LinkState] = {}
        self.prefix_state = PrefixState()
        self._per_prefix: Dict[Tuple[str, str], Dict] = {}
        self._full_db: Dict[Tuple[str, str], Dict] = {}
        self.errors = 0
        # provenance indexes maintained during the fold:
        #   key_last_applied: (area, key) -> the publication that last
        #       touched the key at the replayed instant
        #   prefix_sources: prefix str -> {(area, key): seq} — which
        #       prefix keys currently advertise the prefix
        self.key_last_applied: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.prefix_sources: Dict[str, Dict[Tuple[str, str], int]] = {}
        self._key_contrib: Dict[Tuple[str, str], Set[str]] = {}

    # -- publication fold (mirrors decision.process_publication) --------

    def apply_publication(
        self, pub: Publication, seq: int, ts: float
    ) -> None:
        area = pub.area
        link_state = self.area_link_states.get(area)
        if link_state is None:
            link_state = LinkState(area)
            self.area_link_states[area] = link_state
        for key in sorted(pub.key_vals):
            value = pub.key_vals[key]
            if value.value is None:
                continue  # ttl refresh only
            try:
                self._apply_key(key, value, area, link_state, seq, ts)
            except Exception:
                self.errors += 1
        for key in pub.expired_keys:
            try:
                self._apply_expired(key, area, link_state, seq, ts)
            except Exception:
                self.errors += 1

    def _apply_key(
        self, key: str, value, area: str, link_state: LinkState,
        seq: int, ts: float,
    ) -> None:
        self.key_last_applied[(area, key)] = {
            "seq": seq,
            "ts": ts,
            "version": value.version,
            "ttl_version": value.ttl_version,
            "originator_id": value.originator_id,
            "deleted": False,
        }
        if key.startswith(ADJ_DB_MARKER):
            adj_db = serializer.loads(value.value)
            assert isinstance(adj_db, AdjacencyDatabase)
            if adj_db.area != area:
                adj_db = dataclasses.replace(adj_db, area=area)
            # holds replayed as zero: ordered-FIB TTLs stage apply
            # *timing*, and replay reconstructs the settled state
            link_state.update_adjacency_database(adj_db, 0, 0)
        elif key.startswith(PREFIX_DB_MARKER):
            prefix_db = serializer.loads(value.value)
            assert isinstance(prefix_db, PrefixDatabase)
            self._apply_prefix_db(key, prefix_db, area, seq)

    def _apply_expired(
        self, key: str, area: str, link_state: LinkState,
        seq: int, ts: float,
    ) -> None:
        self.key_last_applied[(area, key)] = {
            "seq": seq,
            "ts": ts,
            "version": None,
            "ttl_version": None,
            "originator_id": None,
            "deleted": True,
        }
        if key.startswith(ADJ_DB_MARKER):
            link_state.delete_adjacency_database(key[len(ADJ_DB_MARKER):])
        elif key.startswith(PREFIX_DB_MARKER):
            node, _, _ = parse_prefix_key(key)
            delete_db = PrefixDatabase(
                this_node_name=node, delete_prefix=True
            )
            self._apply_prefix_db(key, delete_db, area, seq)

    def _apply_prefix_db(
        self, key: str, prefix_db: PrefixDatabase, area: str, seq: int
    ) -> None:
        node_db = self._update_node_prefix_database(
            key, prefix_db, area, seq
        )
        if node_db is None:
            return
        node_db.area = area
        self.prefix_state.update_prefix_database(node_db)

    def _update_node_prefix_database(
        self, key: str, prefix_db: PrefixDatabase, pub_area: str, seq: int
    ) -> Optional[PrefixDatabase]:
        """Per-(node, area) aggregation — decision.py's
        `_update_node_prefix_database` with provenance tracking bolted
        on; the merge semantics are byte-for-byte the same."""
        node = prefix_db.this_node_name
        _, key_area, key_prefix = parse_prefix_key(key)
        area = key_area if key_area is not None else pub_area
        agg_key = (node, area)
        per_prefix = self._per_prefix.setdefault(agg_key, {})
        full_db = self._full_db.setdefault(agg_key, {})
        src = (area, key)
        if key_prefix is not None:
            if prefix_db.delete_prefix:
                per_prefix.pop(key_prefix, None)
                self._drop_source(str(key_prefix), src)
            else:
                assert len(prefix_db.prefix_entries) == 1, key
                entry = prefix_db.prefix_entries[0]
                if (
                    node == self.my_node_name
                    and entry.area_stack
                    and entry.area_stack[0] in self.area_link_states
                ):
                    return None  # self-redistribution reflection
                per_prefix[key_prefix] = entry
                self.prefix_sources.setdefault(str(key_prefix), {})[
                    src
                ] = seq
        else:
            full_db.clear()
            fresh = {str(e.prefix) for e in prefix_db.prefix_entries}
            for stale in self._key_contrib.get(src, set()) - fresh:
                self._drop_source(stale, src)
            self._key_contrib[src] = fresh
            for entry in prefix_db.prefix_entries:
                full_db[entry.prefix] = entry
                self.prefix_sources.setdefault(str(entry.prefix), {})[
                    src
                ] = seq

        node_db = PrefixDatabase(this_node_name=node)
        node_db.prefix_entries.extend(per_prefix.values())
        node_db.prefix_entries.extend(
            entry
            for prefix, entry in full_db.items()
            if prefix not in per_prefix
        )
        return node_db

    def _drop_source(self, prefix_str: str, src: Tuple[str, str]) -> None:
        sources = self.prefix_sources.get(prefix_str)
        if sources is not None:
            sources.pop(src, None)
            if not sources:
                del self.prefix_sources[prefix_str]


@dataclass
class ReplayResult:
    folder: LsdbFolder
    rib: DecisionRouteDb
    at_ts: Optional[float]
    at_seq: int
    applied: int
    base_seq: int
    fold_errors: int = 0


class JournalReplay:
    """Replay a journal's (base, record ring) into state-at-T."""

    def __init__(
        self,
        node_name: str,
        base: Dict[str, Any],
        records: List[JournalRecord],
        solver_flags: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.node_name = node_name
        self.base = base
        self.records = records
        self.solver_flags = {
            k: v
            for k, v in (solver_flags or {}).items()
            if k in _SOLVER_FLAGS
        }

    def replay(self, at: Optional[float] = None) -> ReplayResult:
        at = resolve_ts(at)
        folder = LsdbFolder(self.node_name)
        base_seq = int(self.base.get("seq", 0))
        at_seq = base_seq
        # base: the folded key map replays as one synthetic publication
        # per area (the CRDT-map property; module docstring)
        for area in sorted(self.base.get("keys", {})):
            keys = self.base["keys"][area]
            if not keys:
                continue
            pub = Publication(
                key_vals={
                    k: serializer.from_jsonable(v) for k, v in keys.items()
                },
                area=area,
            )
            folder.apply_publication(
                pub, base_seq, float(self.base.get("ts", 0.0))
            )
        rib = codec.decode_route_db(self.base.get("rib"))
        applied = 0
        for rec in self.records:
            if at is not None and rec.ts > at:
                continue  # ts may jitter vs seq order; filter, not break
            if rec.kind == "pub":
                folder.apply_publication(
                    codec.decode_publication(rec.payload), rec.seq, rec.ts
                )
            else:
                rib = apply_route_delta(
                    rib, codec.decode_route_update(rec.payload)
                )
            applied += 1
            at_seq = max(at_seq, rec.seq)
        return ReplayResult(
            folder=folder,
            rib=rib,
            at_ts=at,
            at_seq=at_seq,
            applied=applied,
            base_seq=base_seq,
            fold_errors=folder.errors,
        )

    # ------------------------------------------------------------------
    # standing correctness audit
    # ------------------------------------------------------------------

    def verify(self, at: Optional[float] = None) -> Dict[str, Any]:
        """Re-derive routes through the CPU oracle over the reconstructed
        LSDB and diff against the journaled RIB."""
        result = self.replay(at)
        solver = SpfSolver(self.node_name, **self.solver_flags)
        oracle = solver.build_route_db(
            self.node_name, result.folder.area_link_states,
            result.folder.prefix_state,
        )
        mismatches: List[Dict[str, Any]] = []
        oracle_unicast = oracle.unicast_entries if oracle else {}
        oracle_mpls = oracle.mpls_entries if oracle else {}
        for prefix, entry in oracle_unicast.items():
            got = result.rib.unicast_entries.get(prefix)
            if got is None:
                mismatches.append({"prefix": str(prefix), "why": "missing"})
            elif got != entry:
                mismatches.append({"prefix": str(prefix), "why": "differs"})
        for prefix in result.rib.unicast_entries:
            if prefix not in oracle_unicast:
                mismatches.append({"prefix": str(prefix), "why": "extra"})
        for label, entry in oracle_mpls.items():
            got = result.rib.mpls_entries.get(label)
            if got is None:
                mismatches.append({"label": label, "why": "missing"})
            elif got != entry:
                mismatches.append({"label": label, "why": "differs"})
        for label in result.rib.mpls_entries:
            if label not in oracle_mpls:
                mismatches.append({"label": label, "why": "extra"})
        return {
            "at_ts": result.at_ts,
            "at_seq": result.at_seq,
            "applied": result.applied,
            "fold_errors": result.fold_errors,
            "routes": len(result.rib.unicast_entries),
            "oracle_routes": len(oracle_unicast),
            "mismatches": mismatches,
            "match": not mismatches,
        }

    # ------------------------------------------------------------------
    # provenance queries
    # ------------------------------------------------------------------

    def explain_route(
        self, prefix: str, at: Optional[float] = None
    ) -> Dict[str, Any]:
        """route → contributing prefix/adjacency keys → originating
        publication. The SolveTrace link is attached ctrl-side (the
        flight recorder lives in Decision, not the journal)."""
        result = self.replay(at)
        pfx = IpPrefix(prefix)
        out: Dict[str, Any] = {
            "prefix": str(pfx),
            "at_ts": result.at_ts,
            "at_seq": result.at_seq,
            "found": False,
            "prefix_keys": [],
            "adjacency_keys": [],
            "complete": False,
        }
        entry = result.rib.unicast_entries.get(pfx)
        if entry is None:
            return out
        out["found"] = True
        out["route"] = codec.encode_unicast_entry(entry)

        def key_info(
            area: str, key: str, seq: Optional[int] = None
        ) -> Dict[str, Any]:
            pub = result.folder.key_last_applied.get((area, key))
            if seq is None:
                seq = pub["seq"] if pub is not None else 0
            info = {"area": area, "key": key, "seq": seq}
            if pub is not None:
                info["publication"] = dict(pub)
            return info

        for (area, key), seq in sorted(
            result.folder.prefix_sources.get(str(pfx), {}).items()
        ):
            out["prefix_keys"].append(key_info(area, key, seq))

        # adjacency attribution: my own adj db plus the neighbor behind
        # each nexthop (matched by neighbor_node when stamped, else by
        # the adjacency's nexthop address)
        unattributed = set()
        adj_keys: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for area, link_state in result.folder.area_link_states.items():
            dbs = link_state.get_adjacency_databases()
            my_db = dbs.get(self.node_name)
            if my_db is None:
                continue
            me_key = (area, ADJ_DB_MARKER + self.node_name)
            adj_keys.setdefault(me_key, key_info(*me_key))
            for nh in entry.nexthops:
                neighbor = nh.neighbor_node
                if neighbor is None:
                    for adj in my_db.adjacencies:
                        if nh.address in (adj.nexthop_v4, adj.nexthop_v6):
                            neighbor = adj.other_node_name
                            break
                if neighbor is None or neighbor not in dbs:
                    unattributed.add(nh.address)
                    continue
                unattributed.discard(nh.address)
                nbr_key = (area, ADJ_DB_MARKER + neighbor)
                adj_keys.setdefault(nbr_key, key_info(*nbr_key))
        out["adjacency_keys"] = [
            adj_keys[k] for k in sorted(adj_keys)
        ]
        out["complete"] = bool(out["prefix_keys"]) and (
            not entry.nexthops or not unattributed
        )
        return out

    def rib_diff(
        self, from_ts: Optional[float], to_ts: Optional[float]
    ) -> Dict[str, Any]:
        r_from = self.replay(from_ts)
        r_to = self.replay(to_ts)
        delta = get_route_delta(r_to.rib, r_from.rib)
        return {
            "from": {
                "at_ts": r_from.at_ts,
                "at_seq": r_from.at_seq,
                "routes": len(r_from.rib.unicast_entries),
            },
            "to": {
                "at_ts": r_to.at_ts,
                "at_seq": r_to.at_seq,
                "routes": len(r_to.rib.unicast_entries),
            },
            "changed": not delta.empty(),
            "delta": codec.encode_route_update(delta),
        }
