"""Journal record payload codec.

Journal records persist across restarts, so payloads are the tagged
plain-JSON form of the wire serializer (utils/serializer.py) — the same
deterministic encoding KvStore values already use on the wire. Two rules
keep records replayable:

  - only wire-crossing state is recorded: a publication's host-local
    fields (``ts_monotonic``, ``span_stages``, ``perf_events``) and a
    route update's ``span``/``perf_events`` are dropped — they are
    meaningless across processes and would break record determinism;
  - RIB entries carry ``nexthops`` as a Python set, which the serializer
    refuses (sets have no canonical JSON form), so entries are encoded
    field-by-field with nexthops sorted the same way
    ``to_unicast_route`` sorts them: ``(address, iface or "")``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from openr_tpu import types as T
from openr_tpu.solver.routes import (
    DecisionRouteDb,
    DecisionRouteUpdate,
    RibMplsEntry,
    RibUnicastEntry,
)
from openr_tpu.utils import serializer

# journal payloads embed KvStore Values verbatim
serializer.register_type(T.Value)


def _nh_key(nh: T.NextHop):
    return (nh.address, nh.iface or "")


def _encode_nexthops(nexthops) -> List[Any]:
    return [
        serializer.to_jsonable(nh) for nh in sorted(nexthops, key=_nh_key)
    ]


def _decode_nexthops(items: List[Any]):
    return {serializer.from_jsonable(nh) for nh in items}


# ---------------------------------------------------------------------------
# publications
# ---------------------------------------------------------------------------


def encode_publication(pub: T.Publication) -> Dict[str, Any]:
    return {
        "area": pub.area,
        "key_vals": {
            k: serializer.to_jsonable(v) for k, v in pub.key_vals.items()
        },
        "expired_keys": list(pub.expired_keys),
        "node_ids": list(pub.node_ids) if pub.node_ids else None,
    }


def decode_publication(payload: Dict[str, Any]) -> T.Publication:
    return T.Publication(
        key_vals={
            k: serializer.from_jsonable(v)
            for k, v in payload.get("key_vals", {}).items()
        },
        expired_keys=list(payload.get("expired_keys", [])),
        node_ids=payload.get("node_ids"),
        area=payload.get("area", "0"),
    )


# ---------------------------------------------------------------------------
# RIB entries / deltas / full dbs
# ---------------------------------------------------------------------------


def encode_unicast_entry(entry: RibUnicastEntry) -> Dict[str, Any]:
    return {
        "prefix": str(entry.prefix),
        "nexthops": _encode_nexthops(entry.nexthops),
        "best_prefix_entry": serializer.to_jsonable(entry.best_prefix_entry),
        "best_area": entry.best_area,
        "do_not_install": entry.do_not_install,
        "best_nexthop": serializer.to_jsonable(entry.best_nexthop),
    }


def decode_unicast_entry(payload: Dict[str, Any]) -> RibUnicastEntry:
    return RibUnicastEntry(
        prefix=T.IpPrefix(payload["prefix"]),
        nexthops=_decode_nexthops(payload.get("nexthops", [])),
        best_prefix_entry=serializer.from_jsonable(
            payload.get("best_prefix_entry")
        ),
        best_area=payload.get("best_area"),
        do_not_install=bool(payload.get("do_not_install", False)),
        best_nexthop=serializer.from_jsonable(payload.get("best_nexthop")),
    )


def encode_mpls_entry(entry: RibMplsEntry) -> Dict[str, Any]:
    return {
        "label": entry.label,
        "nexthops": _encode_nexthops(entry.nexthops),
    }


def decode_mpls_entry(payload: Dict[str, Any]) -> RibMplsEntry:
    return RibMplsEntry(
        label=int(payload["label"]),
        nexthops=_decode_nexthops(payload.get("nexthops", [])),
    )


def encode_route_update(update: DecisionRouteUpdate) -> Dict[str, Any]:
    return {
        "unicast_update": [
            encode_unicast_entry(e) for e in update.unicast_routes_to_update
        ],
        "unicast_delete": [
            str(p) for p in update.unicast_routes_to_delete
        ],
        "mpls_update": [
            encode_mpls_entry(e) for e in update.mpls_routes_to_update
        ],
        "mpls_delete": list(update.mpls_routes_to_delete),
    }


def decode_route_update(payload: Dict[str, Any]) -> DecisionRouteUpdate:
    return DecisionRouteUpdate(
        unicast_routes_to_update=[
            decode_unicast_entry(e)
            for e in payload.get("unicast_update", [])
        ],
        unicast_routes_to_delete=[
            T.IpPrefix(p) for p in payload.get("unicast_delete", [])
        ],
        mpls_routes_to_update=[
            decode_mpls_entry(e) for e in payload.get("mpls_update", [])
        ],
        mpls_routes_to_delete=list(payload.get("mpls_delete", [])),
    )


def encode_route_db(db: DecisionRouteDb) -> Dict[str, Any]:
    return {
        "unicast": {
            str(p): encode_unicast_entry(e)
            for p, e in db.unicast_entries.items()
        },
        "mpls": {
            str(label): encode_mpls_entry(e)
            for label, e in db.mpls_entries.items()
        },
    }


def decode_route_db(payload: Optional[Dict[str, Any]]) -> DecisionRouteDb:
    db = DecisionRouteDb()
    if not payload:
        return db
    for p, e in payload.get("unicast", {}).items():
        entry = decode_unicast_entry(e)
        db.unicast_entries[entry.prefix] = entry
    for label, e in payload.get("mpls", {}).items():
        entry = decode_mpls_entry(e)
        db.mpls_entries[entry.label] = entry
    return db
