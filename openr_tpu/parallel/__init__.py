"""Device-mesh sharding for the batched SPF solver.

Scaling axes (the TPU analog of the reference's parallelism, SURVEY.md §2.4):
  - 'batch': the multi-source batch dimension — each device relaxes its slice
    of sources with the edge list replicated (pure data parallelism, no
    cross-chip traffic inside a relaxation round)
  - 'graph': the destination/node dimension — with a graph axis bigger than
    one the distance matrix is tiled P('batch', 'graph') and relaxation
    rounds exchange only per-partition frontier minima around a ppermute
    ring (GraphTiling / tile_graph + the ops.spf tiled kernels); the same
    axis also shards the per-edge ECMP DAG extraction work

plan_degraded_mesh walks the partial-mesh degradation ladder after a
device-loss fault: the largest strictly-smaller (batch, graph)
factorization over the chips still answering probes (docs/Robustness.md).
"""

from openr_tpu.parallel.mesh import (
    GraphTiling,
    make_mesh,
    plan_degraded_mesh,
    resolve_mesh,
    sharded_batched_spf,
    sharded_spf_step,
    shrink_candidates,
    surviving_devices,
    tile_graph,
)

__all__ = [
    "GraphTiling",
    "make_mesh",
    "plan_degraded_mesh",
    "resolve_mesh",
    "sharded_batched_spf",
    "sharded_spf_step",
    "shrink_candidates",
    "surviving_devices",
    "tile_graph",
]
