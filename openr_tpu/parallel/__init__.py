"""Device-mesh sharding for the batched SPF solver.

Scaling axes (the TPU analog of the reference's parallelism, SURVEY.md §2.4):
  - 'batch': the multi-source batch dimension — each device relaxes its slice
    of sources with the edge list replicated (pure data parallelism, no
    cross-chip traffic inside a relaxation round)
  - 'graph': the edge dimension of the ECMP first-hop DAG extraction —
    sharding the per-edge work for very large LSDBs
"""

from openr_tpu.parallel.mesh import (
    make_mesh,
    resolve_mesh,
    sharded_batched_spf,
    sharded_spf_step,
)

__all__ = [
    "make_mesh",
    "resolve_mesh",
    "sharded_batched_spf",
    "sharded_spf_step",
]
