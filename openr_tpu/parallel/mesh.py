"""Mesh construction and sharded SPF steps (pjit/GSPMD).

The batched min-plus solve shards its sources axis across the 'batch' mesh
axis: D [S, N] is row-sharded, the (small) edge list is replicated, so each
relaxation round is local to a device — XLA inserts no collectives until
results are consumed. The ECMP DAG extraction shards its edge axis across the
'graph' mesh axis, all-gathering the (row-sharded) distance matrix it reads.
This is the design the reference cannot express: its SPF is a single-threaded
per-source Dijkstra (openr/decision/LinkState.cpp:806).

The warm-start incremental event path (ops.spf._sell_solver_warm) rides the
same scheme: the device-resident previous distance matrix is row-sharded
P('batch', None) exactly like the solver output it came from, the
invalidation boolean fixpoint runs on the same dest-major layout as the
relaxation rounds (source axis minor, sharded), and the fixed-shape patch /
increased-edge index arrays are replicated — so a meshed link-flap event is
still a single collective-free dispatch per chip until D is consumed.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from openr_tpu.ops.graph import CompiledGraph
from openr_tpu.ops.spf import _bf_fixpoint, _ecmp_dag, _sell_solver_raw


def make_mesh(
    devices: Optional[Sequence] = None,
    shape: Optional[Tuple[int, int]] = None,
    axis_names: Tuple[str, str] = ("batch", "graph"),
) -> Mesh:
    """2D device mesh. Default shape puts all devices on the batch axis."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    assert shape[0] * shape[1] == n, (shape, n)
    return Mesh(np.array(devices).reshape(shape), axis_names)


def resolve_mesh(spec) -> Optional[Mesh]:
    """Mesh | (batch, graph) shape | None -> Mesh | None.

    The config-facing form of make_mesh: DecisionConfig.solver_mesh carries a
    plain shape tuple (configs must stay picklable / thrift-ish), resolved
    against the actual device set the first time the solver needs it."""
    if spec is None or isinstance(spec, Mesh):
        return spec
    shape = tuple(int(x) for x in spec)
    if len(shape) != 2:
        raise ValueError(
            f"solver_mesh must be (batch, graph), got {spec!r}"
        )
    n = shape[0] * shape[1]
    devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"solver_mesh {shape} needs {n} devices, have {len(devices)}"
        )
    return make_mesh(devices[:n], shape=shape)


def _pad_sources(source_rows: np.ndarray, multiple: int) -> np.ndarray:
    """Pad the source batch to a multiple of the batch-axis size; padding
    rows re-solve source 0 (cheap, discarded by the caller)."""
    s = len(source_rows)
    rem = (-s) % multiple
    if rem == 0:
        return np.asarray(source_rows, dtype=np.int32)
    return np.concatenate(
        [
            np.asarray(source_rows, dtype=np.int32),
            np.full(rem, source_rows[0] if s else 0, dtype=np.int32),
        ]
    )


def _sell_operands(sell, sources, overloaded, mesh: Mesh):
    """Device-placed sliced-ELL solve operands shared by the sharded entry
    points: sources batch-sharded, layout leaves + overload mask
    replicated. Returns (args, in_shardings) aligned with
    _sell_solver_raw's (sources, nbrs, wgs, overloaded) signature."""
    row_sharded = NamedSharding(mesh, P("batch"))
    replicated = NamedSharding(mesh, P())
    args = (
        jax.device_put(jnp.asarray(sources), row_sharded),
        tuple(jax.device_put(jnp.asarray(a), replicated) for a in sell.nbr),
        tuple(jax.device_put(jnp.asarray(a), replicated) for a in sell.wg),
        jax.device_put(jnp.asarray(overloaded), replicated),
    )
    shardings = (row_sharded, replicated, replicated, replicated)
    return args, shardings


def sharded_batched_spf(
    graph: CompiledGraph, source_rows: np.ndarray, mesh: Mesh
) -> jnp.ndarray:
    """Batched SPF with the sources axis sharded over mesh axis 'batch'.

    Uses the sliced-ELL pull kernel when the graph qualifies (dest-major
    [N, S] matrix: the source axis is the minor dim, still sharded over
    'batch' since the kernel returns D transposed). Returns D
    [S_padded, n_pad] sharded P('batch', None).
    """
    batch = mesh.shape["batch"]
    sources = _pad_sources(source_rows, batch)

    row_sharded = NamedSharding(mesh, P("batch"))
    replicated = NamedSharding(mesh, P())
    out_sharding = NamedSharding(mesh, P("batch", None))
    if graph.sell is not None:
        args, shardings = _sell_operands(
            graph.sell, sources, graph.overloaded, mesh
        )
        fn = jax.jit(
            _sell_solver_raw(graph.sell.shape_key()),
            in_shardings=shardings,
            out_shardings=out_sharding,
        )
        return fn(*args)
    fn = jax.jit(
        _bf_fixpoint,
        in_shardings=(row_sharded, replicated, replicated, replicated, replicated),
        out_shardings=out_sharding,
    )
    return fn(
        jax.device_put(jnp.asarray(sources), row_sharded),
        jax.device_put(jnp.asarray(graph.src), replicated),
        jax.device_put(jnp.asarray(graph.dst), replicated),
        jax.device_put(jnp.asarray(graph.w), replicated),
        jax.device_put(jnp.asarray(graph.overloaded), replicated),
    )


def sharded_spf_step(
    graph: CompiledGraph, source_rows: np.ndarray, mesh: Mesh
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Full solver step sharded over the mesh: batched all-sources SPF
    (row-sharded over 'batch') followed by ECMP first-hop DAG extraction
    (edge-sharded over 'graph'). This is the step the multichip dry-run
    compiles and executes.

    source_rows must cover all node ids (the DAG reads D rows by node id).
    """
    batch = mesh.shape["batch"]
    sources = _pad_sources(source_rows, batch)

    row_sharded = NamedSharding(mesh, P("batch"))
    edge_sharded = NamedSharding(mesh, P("graph"))
    replicated = NamedSharding(mesh, P())

    if graph.sell is not None:
        # flagship path: sliced-ELL solve (sources batch-sharded, layout
        # replicated) feeding the edge-sharded ECMP DAG extraction
        solve = _sell_solver_raw(graph.sell.shape_key())
        sell_args, sell_shardings = _sell_operands(
            graph.sell, sources, graph.overloaded, mesh
        )

        def step(sources_a, nbrs, wgs, overloaded, src_e, dst_e, w_e):
            d = solve(sources_a, nbrs, wgs, overloaded)
            dag = _ecmp_dag(d, src_e, dst_e, w_e, overloaded)
            return d, dag

        fn = jax.jit(
            step,
            in_shardings=sell_shardings
            + (edge_sharded, edge_sharded, edge_sharded),
            out_shardings=(
                NamedSharding(mesh, P("batch", None)),
                NamedSharding(mesh, P("graph", None)),
            ),
        )
        return fn(
            *sell_args,
            jax.device_put(jnp.asarray(graph.src), edge_sharded),
            jax.device_put(jnp.asarray(graph.dst), edge_sharded),
            jax.device_put(jnp.asarray(graph.w), edge_sharded),
        )

    def step(sources_a, src_e, dst_e, w_e, overloaded):
        d = _bf_fixpoint(sources_a, src_e, dst_e, w_e, overloaded)
        dag = _ecmp_dag(d, src_e, dst_e, w_e, overloaded)
        return d, dag

    fn = jax.jit(
        step,
        in_shardings=(
            row_sharded,
            edge_sharded,
            edge_sharded,
            edge_sharded,
            replicated,
        ),
        out_shardings=(
            NamedSharding(mesh, P("batch", None)),
            NamedSharding(mesh, P("graph", None)),
        ),
    )
    return fn(
        jax.device_put(jnp.asarray(sources), row_sharded),
        jax.device_put(jnp.asarray(graph.src), edge_sharded),
        jax.device_put(jnp.asarray(graph.dst), edge_sharded),
        jax.device_put(jnp.asarray(graph.w), edge_sharded),
        jax.device_put(jnp.asarray(graph.overloaded), replicated),
    )
