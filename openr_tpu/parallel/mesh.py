"""Mesh construction, graph-axis tiling and sharded SPF steps (pjit/GSPMD).

The batched min-plus solve shards its sources axis across the 'batch' mesh
axis: D [S, N] is row-sharded, the (small) edge list is replicated, so each
relaxation round is local to a device — XLA inserts no collectives until
results are consumed. The ECMP DAG extraction shards its edge axis across the
'graph' mesh axis, all-gathering the (row-sharded) distance matrix it reads.
This is the design the reference cannot express: its SPF is a single-threaded
per-source Dijkstra (openr/decision/LinkState.cpp:806).

The warm-start incremental event path (ops.spf._sell_solver_warm) rides the
same scheme: the device-resident previous distance matrix is row-sharded
P('batch', None) exactly like the solver output it came from, the
invalidation boolean fixpoint runs on the same dest-major layout as the
relaxation rounds (source axis minor, sharded), and the fixed-shape patch /
increased-edge index arrays are replicated — so a meshed link-flap event is
still a single collective-free dispatch per chip until D is consumed.

Destination tiling (the 2-D P('batch', 'graph') layout): when the mesh has a
'graph' axis bigger than one, the row-sharded replica above stops scaling —
every chip still holds all n_pad destination columns. `GraphTiling`
partitions the destination/node axis into `graph`-many contiguous column
tiles and regroups the edge list by SOURCE tile so each device relaxes only
the edges whose tail it owns, contributing per-destination minima into a
compact per-tile frontier. Between relaxation rounds the frontiers — not
the distance rows — move one hop around a `lax.ppermute` ring along the
'graph' axis (the halo exchange); each device folds the passing frontier
into the columns it owns with a scatter-min and drops the rest. Persistent
per-device distance state shrinks from the full [S, n_pad] replica to a
[S/batch, n_pad/graph] tile (docs/Decision.md "Distance layout and halo
exchange").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from openr_tpu.ops.graph import INF, CompiledGraph, _next_bucket
from openr_tpu.ops.spf import _bf_fixpoint, _ecmp_dag, _sell_solver_raw
from openr_tpu.utils.shape_contract import shape_contract


def make_mesh(
    devices: Optional[Sequence] = None,
    shape: Optional[Tuple[int, int]] = None,
    axis_names: Tuple[str, str] = ("batch", "graph"),
) -> Mesh:
    """2D device mesh. Default shape puts all devices on the batch axis."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    assert shape[0] * shape[1] == n, (shape, n)
    return Mesh(np.array(devices).reshape(shape), axis_names)


def resolve_mesh(spec) -> Optional[Mesh]:
    """Mesh | (batch, graph) shape | None -> Mesh | None.

    The config-facing form of make_mesh: DecisionConfig.solver_mesh carries a
    plain shape tuple (configs must stay picklable / thrift-ish), resolved
    against the actual device set the first time the solver needs it."""
    if spec is None or isinstance(spec, Mesh):
        return spec
    shape = tuple(int(x) for x in spec)
    if len(shape) != 2:
        raise ValueError(
            f"solver_mesh must be (batch, graph), got {spec!r}"
        )
    n = shape[0] * shape[1]
    devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"solver_mesh {shape} needs {n} devices, have {len(devices)}"
        )
    return make_mesh(devices[:n], shape=shape)


def shrink_candidates(shape: Tuple[int, int]) -> List[Tuple[int, int]]:
    """Degradation ladder below a (batch, graph) mesh shape: every strictly
    smaller power-of-two factorization, largest first, preferring to keep
    the graph axis (the destination tiling is the memory win worth
    preserving; batch rows re-pad cheaply)."""
    b, g = shape
    total = b * g
    out: List[Tuple[int, int]] = []
    new_total = total // 2
    while new_total >= 1:
        new_g = min(g, new_total)
        out.append((new_total // new_g, new_g))
        new_total //= 2
    return out


def surviving_devices(devices: Sequence) -> List:
    """The subset of `devices` that still answers a trivial dispatch — the
    partial-mesh degradation probe (docs/Robustness.md). A dead chip fails
    the put or the scalar read; both classify it out of the next mesh."""
    alive = []
    for dev in devices:
        try:
            x = jax.device_put(np.int32(1), dev)
            if int(x) == 1:
                alive.append(dev)
        except Exception:  # noqa: BLE001 — any failure means "not viable"
            continue
    return alive


def plan_degraded_mesh(mesh: Mesh) -> Optional[Mesh]:
    """The next rung of the partial-mesh degradation ladder: the largest
    strictly-smaller (batch, graph) factorization that fits the devices
    still answering probes. None when no viable smaller mesh remains (a
    single-device mesh has no rung below it — the caller falls back to
    the CPU oracle)."""
    shape = (mesh.shape["batch"], mesh.shape["graph"])
    alive = surviving_devices(list(mesh.devices.flat))
    for b, g in shrink_candidates(shape):
        if b * g <= len(alive):
            return make_mesh(alive[: b * g], shape=(b, g))
    return None


@dataclass
class GraphTiling:
    """Destination-tiled edge layout for the 2-D P('batch', 'graph') solve.

    The node axis is split into `g` contiguous column tiles of `n_tile`
    ids each (n_pad is a power of two, so g | n_pad whenever g is).
    Edges are grouped by the tile that owns their SOURCE node — the tail
    values a relaxation round reads are then always tile-local — and
    padded to a uniform `e_tile` per partition so the stacked arrays
    shard P('graph', None). Each partition's distinct destination columns
    are compacted into `h` frontier slots: `hseg` maps each edge to its
    slot, `hcols` maps slots back to global columns (sentinel 1<<30 =
    unused/padding, dropped by the halo fold). Slot h-1 is reserved for
    padding edges so a full frontier can never alias one.
    """

    g: int  # graph-axis partitions
    n_tile: int  # destination columns per partition
    e_tile: int  # padded edges per partition (power-of-two bucket)
    h: int  # padded frontier slots per partition
    e: int  # real directed edge count (graph.e)
    src_l: np.ndarray  # int32 [g, e_tile] tile-LOCAL source ids (pad 0)
    hseg: np.ndarray  # int32 [g, e_tile] per-edge frontier slot (pad h-1)
    w: np.ndarray  # int32 [g, e_tile] edge weights (pad INF)
    hcols: np.ndarray  # int32 [g, h] global column per slot (pad 1<<30)
    edge_tile: np.ndarray  # int32 [e] dst-sorted edge pos -> partition
    edge_pos: np.ndarray  # int32 [e] dst-sorted edge pos -> slot in e_tile

    def shape_key(self) -> Tuple:
        """Static structure key: tilings with equal keys share the jitted
        tiled-solver executables (weight patches never change it)."""
        return (self.g, self.n_tile, self.e_tile, self.h)

    def tile_bytes(self) -> int:
        """Device-resident bytes of the tiled edge planes (src_l + hseg +
        w), the unit the memory ledger registers as the `tile` structure
        and `predict_fit` forecasts from the same shapes."""
        return 3 * self.g * self.e_tile * 4  # three int32 [g, e_tile] planes

    def halo_bytes(self) -> int:
        """Device-resident bytes of the halo frontier map (hcols): the
        [g, h] int32 slot->column table the cross-tile fold gathers
        through — the ledger's `halo` structure."""
        return self.g * self.h * 4

    @shape_contract("w_edges:[e_pad]:int32", returns="[g,e_tile]:int32:inf")
    def tile_weights(self, w_edges: np.ndarray) -> np.ndarray:
        """[e_pad] dst-sorted edge weights -> the [g, e_tile] tiled form
        (padding slots stay INF) — the per-event weight upload unit."""
        out = np.full((self.g, self.e_tile), INF, dtype=np.int32)
        out[self.edge_tile, self.edge_pos] = w_edges[: self.e]
        return out


def tile_graph(graph: CompiledGraph, g: int) -> GraphTiling:
    """Partition a compiled graph's edge list by source tile for a
    'graph'-axis of size g. Requires g | n_pad (both are powers of two in
    practice; callers fall back to the row-sharded layout otherwise)."""
    n_pad = graph.n_pad
    assert n_pad % g == 0, (n_pad, g)
    n_tile = n_pad // g
    e = graph.e
    src = graph.src[:e]
    dst = graph.dst[:e]
    w = graph.w[:e]
    tile_of = (src // n_tile).astype(np.int64) if e else np.empty(0, np.int64)
    counts = np.bincount(tile_of, minlength=g) if e else np.zeros(g, int)
    e_tile = _next_bucket(int(counts.max()) if e else 1, minimum=8)
    per_tile = []
    max_u = 0
    for t in range(g):
        idx = np.nonzero(tile_of == t)[0]
        # the global edge array is dst-sorted, so each partition's
        # subsequence stays dst-sorted: slots are assigned in ascending
        # destination order and hseg is non-decreasing — segment_min's
        # sorted fast path holds per tile
        uniq, seg = np.unique(dst[idx], return_inverse=True)
        per_tile.append((idx, uniq, seg))
        max_u = max(max_u, len(uniq))
    h = _next_bucket(max_u + 1, minimum=8)  # +1 reserves the padding slot
    src_l = np.zeros((g, e_tile), dtype=np.int32)
    hseg = np.full((g, e_tile), h - 1, dtype=np.int32)
    w2 = np.full((g, e_tile), INF, dtype=np.int32)
    hcols = np.full((g, h), 1 << 30, dtype=np.int32)
    edge_tile = np.zeros(e, dtype=np.int32)
    edge_pos = np.zeros(e, dtype=np.int32)
    for t, (idx, uniq, seg) in enumerate(per_tile):
        k = len(idx)
        if not k:
            continue
        src_l[t, :k] = src[idx] - t * n_tile
        hseg[t, :k] = seg
        w2[t, :k] = w[idx]
        hcols[t, : len(uniq)] = uniq
        edge_tile[idx] = t
        edge_pos[idx] = np.arange(k, dtype=np.int32)
    return GraphTiling(
        g=g,
        n_tile=n_tile,
        e_tile=e_tile,
        h=h,
        e=e,
        src_l=src_l,
        hseg=hseg,
        w=w2,
        hcols=hcols,
        edge_tile=edge_tile,
        edge_pos=edge_pos,
    )


def _pad_sources(source_rows: np.ndarray, multiple: int) -> np.ndarray:
    """Pad the source batch to a multiple of the batch-axis size; padding
    rows re-solve source 0 (cheap, discarded by the caller)."""
    s = len(source_rows)
    rem = (-s) % multiple
    if rem == 0:
        return np.asarray(source_rows, dtype=np.int32)
    return np.concatenate(
        [
            np.asarray(source_rows, dtype=np.int32),
            np.full(rem, source_rows[0] if s else 0, dtype=np.int32),
        ]
    )


def _sell_operands(sell, sources, overloaded, mesh: Mesh):
    """Device-placed sliced-ELL solve operands shared by the sharded entry
    points: sources batch-sharded, layout leaves + overload mask
    replicated. Returns (args, in_shardings) aligned with
    _sell_solver_raw's (sources, nbrs, wgs, overloaded) signature."""
    row_sharded = NamedSharding(mesh, P("batch"))
    replicated = NamedSharding(mesh, P())
    args = (
        jax.device_put(jnp.asarray(sources), row_sharded),
        tuple(jax.device_put(jnp.asarray(a), replicated) for a in sell.nbr),
        tuple(jax.device_put(jnp.asarray(a), replicated) for a in sell.wg),
        jax.device_put(jnp.asarray(overloaded), replicated),
    )
    shardings = (row_sharded, replicated, replicated, replicated)
    return args, shardings


def sharded_batched_spf(
    graph: CompiledGraph, source_rows: np.ndarray, mesh: Mesh
) -> jnp.ndarray:
    """Batched SPF with the sources axis sharded over mesh axis 'batch'.

    Uses the sliced-ELL pull kernel when the graph qualifies (dest-major
    [N, S] matrix: the source axis is the minor dim, still sharded over
    'batch' since the kernel returns D transposed). Returns D
    [S_padded, n_pad] sharded P('batch', None).
    """
    batch = mesh.shape["batch"]
    sources = _pad_sources(source_rows, batch)

    row_sharded = NamedSharding(mesh, P("batch"))
    replicated = NamedSharding(mesh, P())
    out_sharding = NamedSharding(mesh, P("batch", None))
    if graph.sell is not None:
        args, shardings = _sell_operands(
            graph.sell, sources, graph.overloaded, mesh
        )
        fn = jax.jit(
            _sell_solver_raw(graph.sell.shape_key()),
            in_shardings=shardings,
            out_shardings=out_sharding,
        )
        return fn(*args)
    fn = jax.jit(
        _bf_fixpoint,
        in_shardings=(row_sharded, replicated, replicated, replicated, replicated),
        out_shardings=out_sharding,
    )
    return fn(
        jax.device_put(jnp.asarray(sources), row_sharded),
        jax.device_put(jnp.asarray(graph.src), replicated),
        jax.device_put(jnp.asarray(graph.dst), replicated),
        jax.device_put(jnp.asarray(graph.w), replicated),
        jax.device_put(jnp.asarray(graph.overloaded), replicated),
    )


def sharded_spf_step(
    graph: CompiledGraph, source_rows: np.ndarray, mesh: Mesh
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Full solver step sharded over the mesh: batched all-sources SPF
    (row-sharded over 'batch') followed by ECMP first-hop DAG extraction
    (edge-sharded over 'graph'). This is the step the multichip dry-run
    compiles and executes.

    source_rows must cover all node ids (the DAG reads D rows by node id).
    """
    batch = mesh.shape["batch"]
    sources = _pad_sources(source_rows, batch)

    row_sharded = NamedSharding(mesh, P("batch"))
    edge_sharded = NamedSharding(mesh, P("graph"))
    replicated = NamedSharding(mesh, P())

    if graph.sell is not None:
        # flagship path: sliced-ELL solve (sources batch-sharded, layout
        # replicated) feeding the edge-sharded ECMP DAG extraction
        solve = _sell_solver_raw(graph.sell.shape_key())
        sell_args, sell_shardings = _sell_operands(
            graph.sell, sources, graph.overloaded, mesh
        )

        def step(sources_a, nbrs, wgs, overloaded, src_e, dst_e, w_e):
            d = solve(sources_a, nbrs, wgs, overloaded)
            dag = _ecmp_dag(d, src_e, dst_e, w_e, overloaded)
            return d, dag

        fn = jax.jit(
            step,
            in_shardings=sell_shardings
            + (edge_sharded, edge_sharded, edge_sharded),
            out_shardings=(
                NamedSharding(mesh, P("batch", None)),
                NamedSharding(mesh, P("graph", None)),
            ),
        )
        return fn(
            *sell_args,
            jax.device_put(jnp.asarray(graph.src), edge_sharded),
            jax.device_put(jnp.asarray(graph.dst), edge_sharded),
            jax.device_put(jnp.asarray(graph.w), edge_sharded),
        )

    def step(sources_a, src_e, dst_e, w_e, overloaded):
        d = _bf_fixpoint(sources_a, src_e, dst_e, w_e, overloaded)
        dag = _ecmp_dag(d, src_e, dst_e, w_e, overloaded)
        return d, dag

    fn = jax.jit(
        step,
        in_shardings=(
            row_sharded,
            edge_sharded,
            edge_sharded,
            edge_sharded,
            replicated,
        ),
        out_shardings=(
            NamedSharding(mesh, P("batch", None)),
            NamedSharding(mesh, P("graph", None)),
        ),
    )
    return fn(
        jax.device_put(jnp.asarray(sources), row_sharded),
        jax.device_put(jnp.asarray(graph.src), edge_sharded),
        jax.device_put(jnp.asarray(graph.dst), edge_sharded),
        jax.device_put(jnp.asarray(graph.w), edge_sharded),
        jax.device_put(jnp.asarray(graph.overloaded), replicated),
    )
