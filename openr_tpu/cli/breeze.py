"""breeze — operator CLI for the openr-tpu daemon.

Equivalent of openr/py/openr/cli/breeze.py (the click CLI root) and the
command impls under openr/py/openr/cli/commands/: per-module command groups
talking to the ctrl server (kvstore / decision / fib / lm / prefixmgr /
monitor / openr). argparse instead of click (no extra deps in this image);
same command vocabulary:

  breeze kvstore keys|keyvals|peers|peer-health|areas|history KEY [--area A]
  breeze decision adj|prefixes|routes|rib-policy|solver-health|
                  memory [--area A] [--json]
                  (device-memory observatory ledger, docs/Monitoring.md)|
                  solve-traces [--json]|profile [--seconds N] [--out DIR]|
                  profile-status|
                  te-optimize [--demands file.json] [--steps N] [--json]|
                  explain-route PREFIX [--at T]|
                  rib-diff [--from T1] [--to T2]|verify-replay [--at T]
                  (state-journal provenance + time travel, docs/Journal.md)
  breeze fib routes|unicast-routes|mpls-routes|counters
  breeze lm links|set-node-overload|unset-node-overload|
            set-link-overload|unset-link-overload|
            set-link-metric|unset-link-metric
  breeze prefixmgr view|advertise|withdraw|sync
  breeze monitor counters|histograms[--reset]|logs
  breeze openr version|config
  breeze perf view                   (fib perf event database — 'breeze perf')
  breeze fleet status|watch|report   (fleet observer + SLO watchdog,
                                      docs/Monitoring.md "Fleet observer")
  breeze config show|dryrun          (running config / validate candidate)
  breeze tech-support                (one-shot full state dump)

plus `breeze decision path SRC DST` (all shortest paths between two nodes,
computed client-side from the adjacency dump like
openr/py/openr/cli/commands/decision.py PathCmd) and `breeze kvstore snoop`
(stream deltas; the standalone snooper lives in openr_tpu.kvstore.snooper).

Run as: python -m openr_tpu.cli.breeze --host H --port P <module> <cmd> ...
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, List

from openr_tpu.ctrl.client import (
    BlockingCtrlClient,
    decode_obj,
    encode_obj,
)

from openr_tpu.utils.build_info import PACKAGE as _PKG
from openr_tpu.utils.build_info import VERSION as _PKG_VERSION

VERSION = f"{_PKG} {_PKG_VERSION} (Open/R protocol compatible rebuild)"


def _print_json(data: Any) -> None:
    print(json.dumps(data, indent=2, sort_keys=True, default=str))


def _print_table(headers: List[str], rows: List[List[Any]]) -> None:
    widths = [
        max(len(str(h)), *(len(str(r[i])) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    line = "  ".join(str(h).ljust(w) for h, w in zip(headers, widths))
    print(line)
    print("-" * len(line))
    for row in rows:
        print("  ".join(str(c).ljust(w) for c, w in zip(row, widths)))


def _fmt_nexthops(route) -> str:
    return ", ".join(
        f"{nh.address}%{nh.iface or '*'} (m={nh.metric}, w={nh.weight})"
        for nh in route.nexthops
    )


# ---------------------------------------------------------------------------
# command handlers
# ---------------------------------------------------------------------------


def cmd_kvstore(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "keys":
        pub = client.call(
            "getKvStoreKeyValsFiltered",
            area=args.area,
            prefixes=[args.prefix] if args.prefix else [],
        )
        rows = [
            [k, v["originator_id"], v["version"], v["ttl"], v["ttl_version"]]
            for k, v in sorted(pub["key_vals"].items())
        ]
        _print_table(
            ["Key", "Originator", "Version", "TTL(ms)", "TTL-Version"], rows
        )
    elif args.cmd == "keyvals":
        pub = client.call(
            "getKvStoreKeyVals", area=args.area, keys=args.keys
        )
        for key, v in sorted(pub["key_vals"].items()):
            print(f"> {key}")
            obj = decode_obj(v["value"])
            _print_json(
                obj if not hasattr(obj, "__dict__") else vars(obj)
            )
    elif args.cmd == "peers":
        peers = client.call("getKvStorePeers", area=args.area)
        _print_table(
            ["Peer", "Address"],
            [[name, spec["peer_addr"]] for name, spec in sorted(peers.items())],
        )
    elif args.cmd == "peer-health":
        health = client.call("getKvStorePeerHealth", area=args.area)
        _print_table(
            [
                "Peer",
                "State",
                "Health",
                "Failures",
                "Probes",
                "Streak",
                "FloodsSkipped",
                "Quarantined(ms)",
            ],
            [
                [
                    name,
                    h["state"],
                    h["health"],
                    h["failures"],
                    h["probes"],
                    h["probe_streak"],
                    h["floods_skipped"],
                    h["quarantined_ms"],
                ]
                for name, h in sorted(health.items())
            ],
        )
    elif args.cmd == "areas":
        _print_json(client.call("getAreasConfig"))
    elif args.cmd == "snoop":
        for delta in client.subscribe(
            "subscribeKvStoreFilter",
            area=args.area,
            prefixes=[args.prefix] if args.prefix else [],
        ):
            for key, val in sorted(delta.get("key_vals", {}).items()):
                print(
                    f"{key} v={val['version']} "
                    f"from={val['originator_id']} ttl={val['ttl']}"
                )
            for key in delta.get("expired_keys", []):
                print(f"{key} EXPIRED")
    elif args.cmd == "subscribe":
        # the streaming control plane's typed frames (docs/Streaming.md):
        # snapshot -> deltas, with marked snapshot-resyncs after a
        # bounded fan-out overflow ("[RESYNC]": replace local state)
        for frame in client.subscribe(
            "subscribeKvStore",
            area=args.area,
            prefixes=[args.prefix] if args.prefix else [],
            originators=args.originator or [],
            client=args.client,
            codec=args.codec,
        ):
            kind = frame.get("type", "delta")
            pub = frame.get("pub", {})
            tag = {"snapshot": "[SNAPSHOT]", "resync": "[RESYNC]"}.get(
                kind, ""
            )
            if tag:
                print(
                    f"{tag} seq={frame.get('seq')} "
                    f"{len(pub.get('key_vals', {}))} key(s)"
                )
            for key, val in sorted(pub.get("key_vals", {}).items()):
                print(
                    f"{key} v={val['version']} "
                    f"from={val['originator_id']} ttl={val['ttl']}"
                )
            for key in pub.get("expired_keys", []):
                print(f"{key} EXPIRED")
    elif args.cmd == "history":
        # journaled publication history of one key (docs/Journal.md)
        report = client.call(
            "getKvStoreKeyHistory", key=args.key, area=args.area
        )
        if args.json:
            _print_json(report)
            return
        if not report.get("enabled"):
            print("state journal not enabled (journal_config.enabled)")
            return
        rows = [
            [
                e["seq"],
                _fmt_ts(e.get("ts")),
                e.get("area", "-"),
                "DELETED" if e.get("deleted") else e.get("version"),
                e.get("ttl_version") if not e.get("deleted") else "-",
                e.get("originator_id") or "-",
            ]
            for e in report.get("history", [])
        ]
        if not rows:
            print(f"no journaled history for {args.key}")
            return
        _print_table(
            ["Seq", "Time", "Area", "Version", "TTL-Version", "Originator"],
            rows,
        )


def _fmt_ts(ts) -> str:
    if ts is None:
        return "-"
    import datetime

    return datetime.datetime.fromtimestamp(ts).strftime("%H:%M:%S.%f")[:-3]


def cmd_decision(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "adj":
        dbs = client.call("getDecisionAdjacencyDbs")
        rows = []
        for node, blob in sorted(dbs.items()):
            db = decode_obj(blob)
            for adj in db.adjacencies:
                rows.append(
                    [
                        node,
                        adj.other_node_name,
                        adj.if_name,
                        adj.metric,
                        "overloaded" if adj.is_overloaded else "",
                    ]
                )
        _print_table(["Node", "Neighbor", "Iface", "Metric", "Flags"], rows)
    elif args.cmd == "prefixes":
        dbs = client.call("getDecisionPrefixDbs")
        rows = []
        for node_area, blob in sorted(dbs.items()):
            db = decode_obj(blob)
            for entry in db.prefix_entries:
                rows.append(
                    [node_area, str(entry.prefix), entry.type.value]
                )
        _print_table(["Node:Area", "Prefix", "Type"], rows)
    elif args.cmd == "routes":
        db = client.call("getRouteDbComputed", node=args.node)
        rows = []
        for blob in db["unicast_routes"]:
            route = decode_obj(blob)
            rows.append([str(route.dest), _fmt_nexthops(route)])
        _print_table(["Prefix", "Nexthops"], rows)
        if db["mpls_routes"]:
            rows = []
            for blob in db["mpls_routes"]:
                route = decode_obj(blob)
                rows.append([route.top_label, _fmt_nexthops(route)])
            _print_table(["Label", "Nexthops"], rows)
    elif args.cmd == "rib-policy":
        _print_json(client.call("getRibPolicy"))
    elif args.cmd == "solver-health":
        health = client.call("getSolverHealth")
        state = "DEGRADED" if health.get("degraded") else "HEALTHY"
        print(f"solver: {state} (breaker: {health.get('breaker_state')})")
        _print_json(health)
    elif args.cmd == "memory":
        snap = client.call("getDeviceMemory", area=args.area)
        if args.json:
            _print_json(snap)
            return
        totals = snap.get("totals", {})
        print(
            f"device memory: {totals.get('live_bytes', 0)} live / "
            f"{totals.get('peak_bytes', 0)} peak bytes, "
            f"accounting {'EXACT' if snap.get('exact') else 'VIOLATED'} "
            f"({totals.get('registers', 0)} registers, "
            f"{totals.get('releases', 0)} releases, "
            f"{totals.get('retained', 0)} retained)"
        )
        cap = snap.get("capacity", {})
        rec = snap.get("reconcile", {})
        print(
            f"capacity: {cap.get('capacity_bytes') or '-'} bytes "
            f"(source: {cap.get('source')}); reconcile via "
            f"{rec.get('source')}: backend={rec.get('backend_bytes')} "
            f"drift={rec.get('drift_bytes')}"
        )
        refusal = snap.get("last_refusal")
        if totals.get("capacity_refusals"):
            print(
                f"capacity refusals: {totals['capacity_refusals']} "
                f"(last: {refusal})"
            )
        _print_table(
            ["Structure", "LiveBytes"],
            [
                [name, nbytes]
                for name, nbytes in sorted(
                    snap.get("structures", {}).items()
                )
                if nbytes
            ],
        )
        rows = [
            [
                e["area"],
                e["structure"],
                e["layout"],
                e["dtype"],
                "x".join(str(s) for s in e["shape"]) or "-",
                e["nbytes"],
                "retained" if e["retained"] else "",
            ]
            for e in snap.get("entries", [])
        ]
        if rows:
            _print_table(
                ["Area", "Structure", "Layout", "Dtype", "Shape",
                 "Bytes", "Flags"],
                rows,
            )
    elif args.cmd == "solve-traces":
        report = client.call(
            "getSolveTraces", area=args.area, last_n=args.last
        )
        if args.json:
            _print_json(report)
            return
        if not report.get("enabled"):
            print("flight recorder not enabled (solver unsupervised)")
            return
        stats = report.get("stats", {})
        print(
            f"flight recorder: {stats.get('recorded', 0)} recorded = "
            f"{stats.get('retained', 0)} retained + "
            f"{stats.get('evicted', 0)} evicted; "
            f"{stats.get('sampled_solves', 0)} sampled "
            f"(every {stats.get('sample_every', 0)}th), "
            f"ring {stats.get('ring_size', 0)}/area"
        )
        rows = []
        for t in report.get("traces", []):
            phases = t.get("phases") or {}
            rows.append(
                [
                    t["seq"],
                    t["area"],
                    t["event"],
                    t["layout"],
                    "warm" if t["warm"] else "cold",
                    (
                        f"{t['solve_ms']:.2f}"
                        if t.get("solve_ms") is not None
                        else "-"
                    ),
                    t.get("rounds") if t.get("rounds") is not None else "-",
                    (
                        " ".join(
                            f"{k}={v:.2f}" for k, v in sorted(phases.items())
                        )
                        if phases
                        else ("-" if not t.get("fault_kind")
                              else t["fault_kind"])
                    ),
                ]
            )
        _print_table(
            ["Seq", "Area", "Event", "Layout", "Disp", "ms", "Rounds",
             "Phases(ms) / fault"],
            rows,
        )
        dumps = report.get("forensics", [])
        if dumps:
            print("forensics dumps:")
            _print_table(
                ["Id", "Reason", "Traces", "Path"],
                [
                    [d["id"], d["reason"], d["traces"], d.get("path") or "-"]
                    for d in dumps
                ],
            )
    elif args.cmd == "profile":
        status = client.call(
            "startProfile", seconds=args.seconds, out=args.out
        )
        if status.get("started"):
            print(
                f"profiling window open: {status['seconds']}s -> "
                f"{status['out_dir']} (TensorBoard-compatible)"
            )
        else:
            print(f"profiling not started: {status.get('error')}")
        if args.json:
            _print_json(status)
    elif args.cmd == "profile-status":
        _print_json(client.call("getProfileStatus"))
    elif args.cmd == "te-optimize":
        params = {}
        if args.demands:
            with open(args.demands) as fh:
                params["demands"] = json.load(fh)
        if args.steps is not None:
            params["steps"] = args.steps
        if args.scenarios is not None:
            params["scenarios"] = args.scenarios
        report = client.call("runTeOptimize", **params)
        if args.json:
            _print_json(report)
            return
        state = "DEGRADED cpu-fallback" if report.get("degraded") else "ok"
        print(
            f"te-optimize [{state}]: max link util "
            f"{report['initial_max_util']:.3f} -> "
            f"{report['optimized_max_util']:.3f} "
            f"({report['scenarios']} scenario(s), {report['steps']} steps, "
            f"{report['solve_ms']:.1f}ms)"
        )
        if not report["weight_changes"]:
            print("no improving weight change found")
        else:
            _print_table(
                ["Node", "Neighbor", "Iface", "Metric", "Proposed"],
                [
                    [
                        c["node"],
                        c["neighbor"],
                        c["iface"],
                        c["metric_before"],
                        c["metric_after"],
                    ]
                    for c in report["weight_changes"]
                ],
            )
        hottest = report["top_links"]["optimized"]
        if hottest:
            print("hottest links (proposed weights, worst scenario):")
            _print_table(
                ["Src", "Dst", "Util"],
                [[l["src"], l["dst"], l["util"]] for l in hottest],
            )
    elif args.cmd == "subscribe-routes":
        # initial RIB snapshot then per-event DecisionRouteUpdate deltas
        # fed from Decision's DeltaPath stream (docs/Streaming.md)
        for frame in client.subscribe(
            "subscribeRouteDb", client=args.client, codec=args.codec
        ):
            kind = frame.get("type", "delta")
            if kind in ("snapshot", "resync"):
                print(
                    f"[{kind.upper()}] seq={frame.get('seq')} "
                    f"{len(frame.get('unicast_to_update', []))} unicast, "
                    f"{len(frame.get('mpls_to_update', []))} mpls route(s)"
                )
            for blob in frame.get("unicast_to_update", []):
                route = decode_obj(blob)
                print(f"+ {route.dest} via {_fmt_nexthops(route)}")
            for prefix in frame.get("unicast_to_delete", []):
                print(f"- {prefix}")
            for blob in frame.get("mpls_to_update", []):
                route = decode_obj(blob)
                print(
                    f"+ label {route.top_label} via {_fmt_nexthops(route)}"
                )
            for label in frame.get("mpls_to_delete", []):
                print(f"- label {label}")
    elif args.cmd == "explain-route":
        # provenance chain over the state journal (docs/Journal.md):
        # route -> contributing keys -> originating publication -> solve
        report = client.call(
            "explainRoute", prefix=args.prefix, at=args.at
        )
        if args.json:
            _print_json(report)
            return
        if not report.get("enabled"):
            print("state journal not enabled (journal_config.enabled)")
            return
        when = (
            _fmt_ts(report.get("at_ts"))
            if report.get("at_ts") is not None
            else "latest"
        )
        if not report.get("found"):
            print(
                f"{report['prefix']}: no route at {when} "
                f"(seq {report.get('at_seq')})"
            )
            return
        route = report.get("route", {})
        nexthops = ", ".join(
            f"{nh.get('address')}%{nh.get('iface') or '-'}"
            for nh in route.get("nexthops", [])
        )
        chain = "complete" if report.get("complete") else "INCOMPLETE"
        print(
            f"{report['prefix']} at {when} (seq {report.get('at_seq')}) "
            f"via [{nexthops}] — provenance {chain}"
        )
        rows = []
        for info in report.get("prefix_keys", []) + report.get(
            "adjacency_keys", []
        ):
            pub = info.get("publication") or {}
            rows.append(
                [
                    info["key"],
                    info["area"],
                    pub.get("seq", info.get("seq", "-")),
                    _fmt_ts(pub.get("ts")),
                    "DELETED" if pub.get("deleted") else pub.get("version"),
                    pub.get("originator_id") or "-",
                ]
            )
        _print_table(
            ["Contributing key", "Area", "Seq", "Published", "Version",
             "Originator"],
            rows,
        )
        trace = report.get("solve_trace")
        if trace:
            phases = trace.get("phases") or {}
            print(
                f"solve: seq={trace.get('seq')} event={trace.get('event')} "
                f"layout={trace.get('layout')} "
                f"ms={trace.get('solve_ms')}"
                + (
                    "  " + " ".join(
                        f"{k}={v:.2f}" for k, v in sorted(phases.items())
                    )
                    if phases
                    else ""
                )
            )
        if report.get("rib_policy_active"):
            print(
                "note: RibPolicy is active — journaled routes include "
                "policy edits the replay oracle does not model"
            )
    elif args.cmd == "rib-diff":
        report = client.call(
            "getRibDiff", from_ts=args.from_ts, to_ts=args.to_ts
        )
        if args.json:
            _print_json(report)
            return
        if not report.get("enabled"):
            print("state journal not enabled (journal_config.enabled)")
            return
        f, t = report.get("from", {}), report.get("to", {})
        print(
            f"rib-diff: {f.get('routes')} route(s) at seq {f.get('at_seq')}"
            f" -> {t.get('routes')} route(s) at seq {t.get('at_seq')}"
        )
        if not report.get("changed"):
            print("no route changes across the window")
            return
        delta = report.get("delta", {})
        for entry in delta.get("unicast_update", []):
            nexthops = ", ".join(
                f"{nh.get('address')}%{nh.get('iface') or '-'}"
                for nh in entry.get("nexthops", [])
            )
            print(f"+ {entry['prefix']} via [{nexthops}]")
        for prefix in delta.get("unicast_delete", []):
            print(f"- {prefix}")
        for entry in delta.get("mpls_update", []):
            print(f"+ label {entry['label']}")
        for label in delta.get("mpls_delete", []):
            print(f"- label {label}")
    elif args.cmd == "verify-replay":
        report = client.call("verifyJournalReplay", at=args.at)
        if args.json:
            _print_json(report)
            return
        if not report.get("enabled"):
            print("state journal not enabled (journal_config.enabled)")
            return
        verdict = "MATCH" if report.get("match") else "MISMATCH"
        print(
            f"replay audit: {verdict} — {report.get('routes')} journaled "
            f"route(s) vs {report.get('oracle_routes')} oracle route(s) "
            f"({report.get('applied')} record(s) replayed)"
        )
        for mm in report.get("mismatches", []):
            print(f"  {mm}")
    elif args.cmd == "path":
        # all shortest paths src -> dst over the live adjacency dump
        # (py/openr/cli/commands/decision.py PathCmd equivalent)
        dbs = client.call("getDecisionAdjacencyDbs")
        graph = {}  # node -> {neighbor: (metric, iface)}
        for node, blob in dbs.items():
            db = decode_obj(blob)
            for adj in db.adjacencies:
                if adj.is_overloaded:
                    continue
                cur = graph.setdefault(node, {}).get(adj.other_node_name)
                if cur is None or adj.metric < cur[0]:
                    graph[node][adj.other_node_name] = (
                        adj.metric, adj.if_name
                    )
        paths = _all_shortest_paths(graph, args.src, args.dst)
        if not paths:
            print(f"no path from {args.src} to {args.dst}")
            return
        for i, (cost, hops) in enumerate(paths):
            legs = " -> ".join(
                f"{a}[{graph[a][b][1]}]" for a, b in zip(hops, hops[1:])
            )
            print(f"path {i + 1}: cost {cost}: {legs} -> {args.dst}")


def _all_shortest_paths(graph, src, dst, limit=16):
    """Dijkstra from src, then enumerate up to `limit` equal-cost paths by
    walking the shortest-path DAG."""
    import heapq

    dist = {src: 0}
    pq = [(0, src)]
    while pq:
        d, u = heapq.heappop(pq)
        if d > dist.get(u, float("inf")):
            continue
        for v, (w, _) in graph.get(u, {}).items():
            nd = d + w
            if nd < dist.get(v, float("inf")):
                dist[v] = nd
                heapq.heappush(pq, (nd, v))
    if dst not in dist:
        return []
    paths = []

    def walk(node, acc):
        if len(paths) >= limit:
            return
        if node == dst:
            paths.append((dist[dst], acc))
            return
        for v, (w, _) in sorted(graph.get(node, {}).items()):
            if dist.get(v) == dist[node] + w and v not in set(acc):
                walk(v, acc + [v])

    walk(src, [src])
    return [(c, p) for c, p in paths]


def _check_artifact_schema(artifact: dict) -> None:
    """SOAK_r*/BENCH_r*/fleet artifacts are stamped with schema_version +
    build fingerprint (utils/build_info.py). An unknown version means the
    offline render below may misread fields — warn and render best-effort
    anyway; a missing stamp just gets a note (pre-stamp artifacts stay
    readable)."""
    from openr_tpu.utils.build_info import ARTIFACT_SCHEMA_VERSION

    version = artifact.get("schema_version")
    if version is None:
        print(
            "note: artifact has no schema_version stamp (written by a "
            f"pre-v{ARTIFACT_SCHEMA_VERSION} build); rendering best-effort"
        )
    elif version != ARTIFACT_SCHEMA_VERSION:
        print(
            f"warning: artifact schema_version {version} != supported "
            f"{ARTIFACT_SCHEMA_VERSION} "
            f"(build {artifact.get('build', 'unknown')}): fields may "
            "render incorrectly"
        )


def cmd_soak_report(args) -> None:
    """Render a judged soak report written by the topology-churn harness
    (python -m openr_tpu.testing.soak --out FILE). Offline: reads the
    JSON file, never dials a daemon."""
    with open(args.file) as fh:
        report = json.load(fh)
    _check_artifact_schema(report)
    if "verdict" not in report and isinstance(report.get("soak"), dict):
        report = report["soak"]  # a SOAK_r* artifact wraps the report
    verdict = report.get("verdict", {})
    checks = verdict.get("checks", {})
    state = "PASS" if verdict.get("pass") else "FAIL"
    print(f"soak verdict: {state} ({len(checks)} check(s))")
    for name, check in sorted(checks.items()):
        mark = "ok " if check.get("ok") else "FAIL"
        print(f"  [{mark}] {name}: {check.get('detail', '')}")
    events = report.get("events", {})
    print(
        f"events: {events.get('total', 0)} total = "
        f"{events.get('windowed', 0)} windowed + "
        f"{events.get('evicted_window_events', 0)} window-evicted; "
        f"LogSample rings retained {events.get('spans_in_rings', 0)}"
    )
    waves = report.get("waves", [])
    if waves:
        _print_table(
            ["Wave", "Added", "Removed", "Chaos", "Converged", "ms"],
            [
                [
                    w["index"],
                    ",".join(w["added"]) or "-",
                    ",".join(w["removed"]) or "-",
                    "yes" if w["faulted"] else "",
                    "yes" if w["converged"] else "NO",
                    w["converge_ms"],
                ]
                for w in waves
            ],
        )
    windows = report.get("windows", [])
    if windows:
        print("windowed convergence trend:")
        _print_table(
            ["Window", "Events", "Chaos", "p50 ms", "p95 ms", "max ms"],
            [
                [
                    int(w["start"]),
                    w["events"],
                    "yes" if w["faulted"] else "",
                    f"{w['e2e_p50_ms']:.2f}",
                    f"{w['e2e_p95_ms']:.2f}",
                    f"{w['e2e_max_ms']:.2f}",
                ]
                for w in windows
            ],
        )
    trend = report.get("trend")
    if trend:
        print(
            f"trend: p95 slope {trend['p95_slope_ms_per_window']:+.3f} "
            f"ms/window over {trend['windows']} window(s)"
        )
        step = trend.get("step")
        if step:
            stages = ", ".join(
                s["stage"] for s in trend.get("attributed_stages", [])
            )
            print(
                f"  step break at window {step['index']}: "
                f"{step['before_ms']} -> {step['after_ms']} ms "
                f"({'fault-attributed' if step['faulted'] else 'CLEAN'}"
                + (f"; stages: {stages}" if stages else "")
                + ")"
            )
    stream = report.get("stream")
    if stream and stream.get("enabled"):
        print(
            f"stream scrapes: {stream['frames_total']} frame(s), "
            f"{stream['resyncs_total']} resync(s) over "
            f"{len(stream.get('nodes', {}))} subscription(s)"
        )
    attribution = report.get("attribution")
    if attribution:
        clean = attribution["clean_e2e_ms"]
        faulted = attribution["faulted_e2e_ms"]
        print(
            f"attribution: clean {attribution['clean_windows']} window(s) "
            f"p95 {clean['p95']:.2f}ms vs chaos "
            f"{attribution['faulted_windows']} window(s) "
            f"p95 {faulted['p95']:.2f}ms"
        )
    if args.json:
        _print_json(report)


def cmd_fleet(client: BlockingCtrlClient, args) -> None:
    """Fleet observer surfaces (docs/Monitoring.md "Fleet observer & SLO
    watchdog"): `status` one-shot-scrapes the connected node plus
    --hosts peers and renders the health gauges the standing rules
    watch; `watch` attaches the live observer (scrape + stream + SLO
    watchdog) for --seconds and reports breaches."""
    from openr_tpu.monitor.exporter import parse_metrics_text, prom_name

    endpoints = [h for h in (args.hosts or "").split(",") if h]
    if args.cmd == "status":
        rows = []
        unhealthy = []

        def one(c: BlockingCtrlClient) -> None:
            node = c.call("getMyNodeName")
            parsed = parse_metrics_text(c.call("getMetricsText"))

            def sample(name: str, default=0.0) -> float:
                pname = prom_name(name)
                for view in ("counters", "gauges"):
                    if pname in parsed[view]:
                        return parsed[view][pname]
                return default

            window_p95 = 0.0
            for labels, value in parsed["samples"].get(
                "openr_convergence_window_e2e_ms", {}
            ).items():
                if 'q="p95"' in labels:
                    window_p95 = value
            fallback = int(sample("decision.spf.fallback_active"))
            stale = int(sample("fib.num_stale_routes"))
            flushes = int(sample("fib.stale_deadline_flushes"))
            resyncs = int(sample("ctrl.stream.resyncs"))
            rejected = int(
                sample("ctrl.admission.rejected_queue_full")
                + sample("ctrl.admission.rejected_client_cap")
                + sample("ctrl.admission.timeouts")
            )
            state = "OK"
            if fallback or flushes:
                state = "DEGRADED"
                unhealthy.append(node)
            rows.append(
                [
                    node,
                    state,
                    f"{window_p95:.1f}",
                    fallback,
                    stale,
                    resyncs,
                    rejected,
                    int(sample("process.uptime.seconds")),
                ]
            )

        one(client)
        for endpoint in endpoints:
            host, _, port = endpoint.rpartition(":")
            with BlockingCtrlClient(
                host or "127.0.0.1",
                int(port),
                ssl_context=client.ssl_context,
            ) as peer:
                one(peer)
        _print_table(
            ["Node", "State", "win p95 ms", "Fallback", "Stale",
             "Resyncs", "Rejected", "Uptime s"],
            rows,
        )
        print(
            f"fleet: {len(rows)} node(s), "
            f"{len(unhealthy)} degraded"
            + (f" ({', '.join(unhealthy)})" if unhealthy else "")
        )
        if args.json:
            _print_json({"nodes": rows, "degraded": unhealthy})
    elif args.cmd == "watch":
        from openr_tpu.fleet import FleetConfig, SloConfig, watch_hosts

        hosts = [f"{args.host}:{args.port}"] + endpoints
        report = watch_hosts(
            hosts,
            seconds=args.seconds,
            config=FleetConfig(
                scrape_interval_s=args.interval,
                forensics_dir=args.forensics_dir,
                slo=SloConfig(
                    convergence_p95_budget_ms=args.budget_ms
                ),
            ),
        )
        _render_fleet_report(report, json_too=args.json)


def _render_fleet_report(report: dict, json_too: bool = False) -> None:
    """Shared renderer for `breeze fleet watch` and the offline
    `breeze fleet report FILE` (which must round-trip with --json)."""
    verdict = report.get("verdict", {})
    checks = verdict.get("checks", {})
    state = "PASS" if verdict.get("pass") else "BREACH"
    print(
        f"fleet verdict: {state} ({len(report.get('nodes', []))} node(s), "
        f"{report.get('ticks', 0)} watchdog tick(s))"
    )
    for name, check in sorted(checks.items()):
        mark = "ok " if check.get("ok") else "FAIL"
        print(f"  [{mark}] {name}: {check.get('detail', '')}")
    findings = report.get("findings", [])
    if findings:
        _print_table(
            ["Rule", "Node", "Value", "Budget", "Stages", "Forensics"],
            [
                [
                    f["kind"],
                    f["node"],
                    f["value"],
                    f["budget"],
                    ",".join(
                        s["stage"] for s in f.get("attribution", [])
                    )
                    or "-",
                    f.get("forensics_id") or "-",
                ]
                for f in findings
            ],
        )
    store = report.get("store", {})
    acc = store.get("accounting", {})
    print(
        f"store: {acc.get('recorded', 0)} points = "
        f"{acc.get('retained', 0)} retained + "
        f"{acc.get('evicted', 0)} evicted over {acc.get('rings', 0)} "
        f"ring(s); {store.get('gaps_marked', 0)} gap(s) marked"
    )
    if json_too:
        _print_json(report)


def cmd_fleet_report(args) -> None:
    """Offline: render a fleet report JSON written by the observer
    (`python -m openr_tpu.fleet --out` / a SOAK_r* artifact's `fleet`
    section). Never dials a daemon; --json re-emits the full report
    (the round-trip the FLEET_SMOKE pins)."""
    with open(args.file) as fh:
        report = json.load(fh)
    _check_artifact_schema(report)
    if "findings" not in report:
        # also accept a soak report / SOAK_r* artifact: render the
        # embedded fleet section
        if isinstance(report.get("fleet"), dict):
            report = report["fleet"]
        elif isinstance(report.get("soak"), dict) and isinstance(
            report["soak"].get("fleet"), dict
        ):
            report = report["soak"]["fleet"]
    _render_fleet_report(report, json_too=args.json)


def cmd_perf(client: BlockingCtrlClient, args) -> None:
    if getattr(args, "cmd", None) == "report":
        _perf_report(client, args)
        return
    perf_db = client.call("getPerfDb")
    for blob in perf_db:
        perf = decode_obj(blob)  # PerfEvents; unix_ts already in ms
        print("PerfEvents:")
        base = None
        for ev in perf.events:
            if base is None:
                base = ev.unix_ts
            print(
                f"  {ev.event_descr:<40} {ev.node_name:<16} "
                f"+{ev.unix_ts - base}ms"
            )


def _perf_report(client: BlockingCtrlClient, args) -> None:
    """Network-wide convergence report: collect getConvergenceReport from
    every named node (--hosts host:port,... — or just the connected one)
    and render the aggregate (monitor/report.py)."""
    from openr_tpu.monitor.report import aggregate_convergence_reports

    reports = [client.call("getConvergenceReport")]
    for endpoint in [h for h in (args.hosts or "").split(",") if h]:
        host, _, port = endpoint.rpartition(":")
        with BlockingCtrlClient(
            host or "127.0.0.1", int(port), ssl_context=client.ssl_context
        ) as peer:
            reports.append(peer.call("getConvergenceReport"))
    agg = aggregate_convergence_reports(reports)

    def ms(value: float) -> str:
        return f"{value:.3f}"

    print(
        f"network-wide convergence: {agg['nodes']} node(s), "
        f"{agg['spans_total']} finished span(s)"
    )
    e2e = agg["e2e_ms"]
    _print_table(
        ["Metric", "Count", "p50", "p95", "Max"],
        [
            [
                "node-to-converge e2e_ms",
                e2e["count"],
                ms(e2e["p50"]),
                ms(e2e["p95"]),
                ms(e2e["max"]),
            ]
        ]
        + [
            [f"stage {stage}_ms", s["count"], ms(s["p50"]), ms(s["p95"]),
             ms(s["max"])]
            for stage, s in agg["stages"].items()
        ],
    )
    slowest = agg.get("slowest_stage")
    if slowest:
        print(
            f"slowest hop: {slowest['stage']} on {slowest['node']} "
            f"({ms(slowest['ms'])}ms)"
        )
    flood = agg["flood"]
    print(
        f"flood: {flood['received']} received, "
        f"{flood['duplicates']} redundant "
        f"(ratio {flood['duplicate_ratio']:.2f}), "
        f"max hop count {flood['hop_count_max']}, "
        f"per-hop p50/p95/max "
        f"{ms(flood['hop_ms']['p50'])}/{ms(flood['hop_ms']['p95'])}/"
        f"{ms(flood['hop_ms']['max'])}ms"
    )
    if args.json:
        _print_json(agg)


def cmd_config(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "show":
        _print_json(client.call("getRunningConfig"))
    elif args.cmd == "dryrun":
        with open(args.file) as fh:
            text = fh.read()
        _print_json(client.call("dryrunConfig", file=text))
        print("config OK", file=sys.stderr)


def _dump_all_areas(client: BlockingCtrlClient):
    def dump():
        areas = client.call("getAreasConfig")["areas"]
        return {
            area: client.call(
                "getKvStoreKeyValsFiltered", area=area, prefixes=[]
            )
            for area in areas
        }

    return dump


def cmd_tech_support(client: BlockingCtrlClient, args) -> None:
    """One-shot dump of everything an operator needs for a bug report
    (py/openr/cli/clis/tech_support.py equivalent)."""
    sections = [
        ("version", lambda: VERSION),
        ("node", lambda: client.call("getMyNodeName")),
        ("config", lambda: client.call("getRunningConfig")),
        ("counters", lambda: client.call("getCounters")),
        ("interfaces", lambda: client.call("getInterfaces")),
        ("adjacencies", lambda: client.call("getLinkMonitorAdjacencies")),
        ("routes", lambda: client.call("getRouteDb")),
        ("kvstore-keys", _dump_all_areas(client)),
        ("event-logs", lambda: client.call("getEventLogs")),
    ]
    for title, fn in sections:
        print(f"\n==== {title} ====")
        try:
            _print_json(fn())
        except Exception as exc:  # a module may not be wired in
            print(f"<unavailable: {exc}>")


def cmd_fib(client: BlockingCtrlClient, args) -> None:
    if args.cmd in ("routes", "unicast-routes"):
        routes = client.call(
            "getUnicastRoutesFiltered", prefixes=args.prefixes or []
        )
        rows = []
        for blob in routes:
            route = decode_obj(blob)
            rows.append([str(route.dest), _fmt_nexthops(route)])
        _print_table(["Prefix", "Nexthops"], rows)
    elif args.cmd == "mpls-routes":
        routes = client.call("getMplsRoutesFiltered", labels=[])
        rows = []
        for blob in routes:
            route = decode_obj(blob)
            rows.append([route.top_label, _fmt_nexthops(route)])
        _print_table(["Label", "Nexthops"], rows)
    elif args.cmd == "counters":
        counters = client.call("getCounters")
        fib_counters = {
            k: v for k, v in sorted(counters.items()) if k.startswith("fib.")
        }
        _print_json(fib_counters)


def cmd_lm(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "links":
        ifaces = client.call("getInterfaces")
        rows = [
            [
                name,
                "UP" if info["is_up"] else "DOWN",
                "active" if info["is_active"] else "dampened",
                ",".join(info["addresses"]) or "-",
            ]
            for name, info in sorted(ifaces.items())
        ]
        _print_table(["Interface", "Status", "Dampening", "Addresses"], rows)
    elif args.cmd == "set-node-overload":
        client.call("setNodeOverload")
        print("node overload: SET")
    elif args.cmd == "unset-node-overload":
        client.call("unsetNodeOverload")
        print("node overload: UNSET")
    elif args.cmd == "set-link-overload":
        client.call("setInterfaceOverload", interface=args.interface)
        print(f"link overload SET on {args.interface}")
    elif args.cmd == "unset-link-overload":
        client.call("unsetInterfaceOverload", interface=args.interface)
        print(f"link overload UNSET on {args.interface}")
    elif args.cmd == "set-link-metric":
        client.call(
            "setInterfaceMetric",
            interface=args.interface,
            metric=args.metric,
        )
        print(f"metric {args.metric} SET on {args.interface}")
    elif args.cmd == "unset-link-metric":
        client.call("unsetInterfaceMetric", interface=args.interface)
        print(f"metric override UNSET on {args.interface}")


def cmd_prefixmgr(client: BlockingCtrlClient, args) -> None:
    from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType

    if args.cmd == "view":
        entries = [decode_obj(b) for b in client.call("getPrefixes")]
        _print_table(
            ["Prefix", "Type", "Forwarding"],
            [
                [str(e.prefix), e.type.value, e.forwarding_type.name]
                for e in entries
            ],
        )
    elif args.cmd == "advertise":
        entries = [
            PrefixEntry(
                prefix=IpPrefix(p), type=PrefixType(args.prefix_type)
            )
            for p in args.prefixes
        ]
        client.call(
            "advertisePrefixes",
            prefixes=[encode_obj(e) for e in entries],
        )
        print(f"advertised {len(entries)} prefixes")
    elif args.cmd == "withdraw":
        entries = [
            PrefixEntry(
                prefix=IpPrefix(p), type=PrefixType(args.prefix_type)
            )
            for p in args.prefixes
        ]
        client.call(
            "withdrawPrefixes",
            prefixes=[encode_obj(e) for e in entries],
        )
        print(f"withdrew {len(entries)} prefixes")
    elif args.cmd == "sync":
        entries = [
            PrefixEntry(
                prefix=IpPrefix(p), type=PrefixType(args.prefix_type)
            )
            for p in args.prefixes
        ]
        client.call(
            "syncPrefixesByType",
            type=args.prefix_type,
            prefixes=[encode_obj(e) for e in entries],
        )
        print(f"synced {len(entries)} prefixes of type {args.prefix_type}")


def cmd_monitor(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "counters":
        _print_json(client.call("getCounters"))
    elif args.cmd == "histograms":
        # --reset: reset-on-read windowing — this export clears the
        # sources, so the next call describes a fresh window (rates)
        hists = client.call("getHistograms", reset=bool(args.reset))

        def ms(v: float) -> str:
            return f"{v:.3f}"

        rows = [
            [
                name,
                h["count"],
                ms(h["avg"]),
                ms(h["p50"]),
                ms(h["p95"]),
                ms(h["p99"]),
                ms(h["max"]),
            ]
            for name, h in sorted(hists.items())
        ]
        _print_table(
            ["Histogram", "Count", "Avg", "p50", "p95", "p99", "Max"], rows
        )
    elif args.cmd == "logs":
        for log_json in client.call("getEventLogs"):
            print(log_json)
    elif args.cmd == "scrape":
        # the full registry in Prometheus text exposition format — the
        # same bytes GET /metrics on the ctrl port serves (the scrape
        # endpoint a stock Prometheus instance polls)
        sys.stdout.write(client.call("getMetricsText"))
    elif args.cmd == "stream-stats":
        # live fan-out + admission state (docs/Streaming.md)
        _print_json(client.call("getStreamStats"))


def cmd_openr(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "version":
        print(VERSION)
        print("node:", client.call("getMyNodeName"))
        build_info = client.call("getBuildInfo")
        for k, v in sorted(build_info.items()):
            print(f"{k}: {v}")
        if "build_analysis_version" not in build_info:
            # older daemon: report the CLI side's own lint contract
            from openr_tpu.utils.build_info import get_analysis_build_info

            for k, v in sorted(get_analysis_build_info().items()):
                print(f"{k} (local): {v}")
    elif args.cmd == "config":
        _print_json(client.call("getRunningConfig"))


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="breeze", description="openr-tpu operator CLI"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=2018)
    # mutual TLS against a secured daemon (--enable_secure_thrift_server)
    parser.add_argument("--x509_ca_path", default=None)
    parser.add_argument("--x509_cert_path", default=None)
    parser.add_argument("--x509_key_path", default=None)
    sub = parser.add_subparsers(dest="module", required=True)

    kv = sub.add_parser("kvstore").add_subparsers(dest="cmd", required=True)
    p = kv.add_parser("keys")
    p.add_argument("--prefix", default="")
    p.add_argument("--area", default="0")
    p = kv.add_parser("keyvals")
    p.add_argument("keys", nargs="+")
    p.add_argument("--area", default="0")
    p = kv.add_parser("peers")
    p.add_argument("--area", default="0")
    p = kv.add_parser("peer-health")
    p.add_argument("--area", default="0")
    kv.add_parser("areas")
    p = kv.add_parser("snoop")
    p.add_argument("--prefix", default="")
    p.add_argument("--area", default="0")
    p = kv.add_parser("history")
    p.add_argument("key", help="exact key, e.g. adj:r1")
    p.add_argument(
        "--area", default=None, help="area filter (all areas when omitted)"
    )
    p.add_argument("--json", action="store_true")
    p = kv.add_parser("subscribe")
    p.add_argument("--prefix", default="")
    p.add_argument(
        "--originator",
        action="append",
        default=None,
        help="originator-id filter (repeatable)",
    )
    p.add_argument("--area", default="0")
    p.add_argument(
        "--client",
        default="breeze",
        help="client label (admission fairness / stream stats)",
    )
    p.add_argument(
        "--codec",
        default="json",
        choices=["json", "binary"],
        help="stream frame codec; binary negotiates length-prefixed "
        "frames, falling back to JSON on old servers",
    )

    dec = sub.add_parser("decision").add_subparsers(dest="cmd", required=True)
    dec.add_parser("adj")
    dec.add_parser("prefixes")
    p = dec.add_parser("routes")
    p.add_argument("--node", default=None)
    dec.add_parser("rib-policy")
    dec.add_parser("solver-health")
    p = dec.add_parser("memory")
    p.add_argument("--area", default=None)
    p.add_argument(
        "--json", action="store_true", help="dump the raw ledger snapshot"
    )
    p = dec.add_parser("solve-traces")
    p.add_argument("--area", default=None)
    p.add_argument(
        "--last", type=int, default=None, help="most recent N traces"
    )
    p.add_argument(
        "--json", action="store_true", help="dump raw trace records"
    )
    p = dec.add_parser("profile")
    p.add_argument(
        "--seconds", type=float, default=5.0,
        help="profiling window duration (clamped to [0.1, 600])",
    )
    p.add_argument(
        "--out", default=None,
        help="TensorBoard trace directory (temp dir when omitted)",
    )
    p.add_argument("--json", action="store_true")
    dec.add_parser("profile-status")
    p = dec.add_parser("te-optimize")
    p.add_argument(
        "--demands",
        default=None,
        help="JSON demand spec file (docs/TrafficEngineering.md format)",
    )
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--scenarios", type=int, default=None)
    p.add_argument(
        "--json", action="store_true", help="dump the full report"
    )
    p = dec.add_parser("subscribe-routes")
    p.add_argument(
        "--client",
        default="breeze",
        help="client label (admission fairness / stream stats)",
    )
    p.add_argument(
        "--codec",
        default="json",
        choices=["json", "binary"],
        help="stream frame codec; binary negotiates length-prefixed "
        "frames, falling back to JSON on old servers",
    )
    p = dec.add_parser("explain-route")
    p.add_argument("prefix", help="route prefix, e.g. 10.0.0.0/24")
    p.add_argument(
        "--at", type=float, default=None,
        help="replay instant: unix seconds, negative = seconds before "
        "now (default: latest journaled state)",
    )
    p.add_argument("--json", action="store_true")
    p = dec.add_parser("rib-diff")
    p.add_argument(
        "--from", dest="from_ts", type=float, default=None,
        help="window start (unix seconds; negative = relative to now)",
    )
    p.add_argument(
        "--to", dest="to_ts", type=float, default=None,
        help="window end (same axis; default: latest)",
    )
    p.add_argument("--json", action="store_true")
    p = dec.add_parser("verify-replay")
    p.add_argument("--at", type=float, default=None)
    p.add_argument("--json", action="store_true")
    p = dec.add_parser("path")
    p.add_argument("src")
    p.add_argument("dst")

    fib = sub.add_parser("fib").add_subparsers(dest="cmd", required=True)
    p = fib.add_parser("routes")
    p.add_argument("prefixes", nargs="*")
    p = fib.add_parser("unicast-routes")
    p.add_argument("prefixes", nargs="*")
    fib.add_parser("mpls-routes")
    fib.add_parser("counters")

    lm = sub.add_parser("lm").add_subparsers(dest="cmd", required=True)
    lm.add_parser("links")
    lm.add_parser("set-node-overload")
    lm.add_parser("unset-node-overload")
    for name in ("set-link-overload", "unset-link-overload",
                 "unset-link-metric"):
        p = lm.add_parser(name)
        p.add_argument("interface")
    p = lm.add_parser("set-link-metric")
    p.add_argument("interface")
    p.add_argument("metric", type=int)

    pm = sub.add_parser("prefixmgr").add_subparsers(dest="cmd", required=True)
    pm.add_parser("view")
    for name in ("advertise", "withdraw", "sync"):
        p = pm.add_parser(name)
        p.add_argument("prefixes", nargs="+")
        p.add_argument("--prefix-type", default="BREEZE")

    mon = sub.add_parser("monitor").add_subparsers(dest="cmd", required=True)
    mon.add_parser("counters")
    p = mon.add_parser("histograms")
    p.add_argument("--reset", action="store_true")
    mon.add_parser("logs")
    mon.add_parser("scrape")
    mon.add_parser("stream-stats")

    op = sub.add_parser("openr").add_subparsers(dest="cmd", required=True)
    op.add_parser("version")
    op.add_parser("config")

    perf = sub.add_parser("perf").add_subparsers(dest="cmd", required=True)
    perf.add_parser("view")
    p = perf.add_parser("report")
    p.add_argument(
        "--hosts",
        default="",
        help="additional host:port ctrl endpoints to fold into the "
        "network-wide report (comma-separated)",
    )
    p.add_argument(
        "--json", action="store_true", help="dump the full aggregate too"
    )
    p = perf.add_parser("soak-report")
    p.add_argument("file", help="JSON soak report (testing/soak.py --out)")
    p.add_argument(
        "--json", action="store_true", help="dump the full report too"
    )

    fleet = sub.add_parser("fleet").add_subparsers(dest="cmd", required=True)
    p = fleet.add_parser("status")
    p.add_argument(
        "--hosts",
        default="",
        help="additional host:port ctrl endpoints (comma-separated)",
    )
    p.add_argument("--json", action="store_true")
    p = fleet.add_parser("watch")
    p.add_argument(
        "--hosts",
        default="",
        help="additional host:port ctrl endpoints (comma-separated)",
    )
    p.add_argument("--seconds", type=float, default=15.0)
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument(
        "--budget-ms",
        type=float,
        default=1000.0,
        help="convergence e2e p95 SLO budget",
    )
    p.add_argument(
        "--forensics-dir", default=None, help="write breach dumps here"
    )
    p.add_argument("--json", action="store_true")
    p = fleet.add_parser("report")
    p.add_argument(
        "file", help="fleet report JSON (python -m openr_tpu.fleet --out)"
    )
    p.add_argument(
        "--json", action="store_true", help="re-emit the full report"
    )

    cfg = sub.add_parser("config").add_subparsers(dest="cmd", required=True)
    cfg.add_parser("show")
    p = cfg.add_parser("dryrun")
    p.add_argument("file")

    sub.add_parser("tech-support")

    return parser


_HANDLERS = {
    "kvstore": cmd_kvstore,
    "decision": cmd_decision,
    "fib": cmd_fib,
    "lm": cmd_lm,
    "prefixmgr": cmd_prefixmgr,
    "monitor": cmd_monitor,
    "openr": cmd_openr,
    "perf": cmd_perf,
    "fleet": cmd_fleet,
    "config": cmd_config,
    "tech-support": cmd_tech_support,
}


def main(argv=None) -> int:
    from openr_tpu.ctrl.client import CtrlError

    args = build_parser().parse_args(argv)
    if args.module == "perf" and getattr(args, "cmd", None) == "soak-report":
        # offline renderer: reads a report file, never dials a daemon
        cmd_soak_report(args)
        return 0
    if args.module == "fleet" and getattr(args, "cmd", None) == "report":
        # offline renderer: reads a fleet report file, never dials a daemon
        cmd_fleet_report(args)
        return 0
    ssl_ctx = None
    if args.x509_ca_path:
        from openr_tpu.utils.tls import client_ssl_context

        ssl_ctx = client_ssl_context(
            args.x509_ca_path, args.x509_cert_path, args.x509_key_path
        )
    try:
        with BlockingCtrlClient(
            args.host, args.port, ssl_context=ssl_ctx
        ) as client:
            _HANDLERS[args.module](client, args)
        return 0
    except CtrlError as exc:
        if exc.server_busy:
            # typed admission rejection: the daemon is shedding load, not
            # broken — report the backoff hint and exit distinctly
            retry = exc.retry_after_ms or 0
            print(
                f"server busy: {exc} (retry in ~{retry}ms)",
                file=sys.stderr,
            )
            return 2
        raise
    except ConnectionRefusedError:
        print(
            f"cannot connect to openr-tpu at {args.host}:{args.port}",
            file=sys.stderr,
        )
        return 1
    except BrokenPipeError:
        # distinguish a closed stdout (pager/head quit: quiet success) from
        # a broken daemon socket (real RPC failure: report it)
        try:
            sys.stdout.flush()
        except (BrokenPipeError, ValueError):
            try:
                sys.stdout.close()
            except Exception:
                pass
            return 0
        print(
            f"connection to openr-tpu at {args.host}:{args.port} broke",
            file=sys.stderr,
        )
        return 1


if __name__ == "__main__":
    sys.exit(main())
