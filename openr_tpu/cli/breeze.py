"""breeze — operator CLI for the openr-tpu daemon.

Equivalent of openr/py/openr/cli/breeze.py (the click CLI root) and the
command impls under openr/py/openr/cli/commands/: per-module command groups
talking to the ctrl server (kvstore / decision / fib / lm / prefixmgr /
monitor / openr). argparse instead of click (no extra deps in this image);
same command vocabulary:

  breeze kvstore keys|keyvals|peers|areas
  breeze decision adj|prefixes|routes|rib-policy
  breeze fib routes|unicast-routes|mpls-routes|counters
  breeze lm links|set-node-overload|unset-node-overload|
            set-link-overload|unset-link-overload|
            set-link-metric|unset-link-metric
  breeze prefixmgr view|advertise|withdraw|sync
  breeze monitor counters|logs
  breeze openr version|config

Run as: python -m openr_tpu.cli.breeze --host H --port P <module> <cmd> ...
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, List

from openr_tpu.ctrl.client import (
    BlockingCtrlClient,
    decode_obj,
    encode_obj,
)

VERSION = "openr-tpu 1.0 (Open/R protocol compatible rebuild)"


def _print_json(data: Any) -> None:
    print(json.dumps(data, indent=2, sort_keys=True, default=str))


def _print_table(headers: List[str], rows: List[List[Any]]) -> None:
    widths = [
        max(len(str(h)), *(len(str(r[i])) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    line = "  ".join(str(h).ljust(w) for h, w in zip(headers, widths))
    print(line)
    print("-" * len(line))
    for row in rows:
        print("  ".join(str(c).ljust(w) for c, w in zip(row, widths)))


def _fmt_nexthops(route) -> str:
    return ", ".join(
        f"{nh.address}%{nh.iface or '*'} (m={nh.metric}, w={nh.weight})"
        for nh in route.nexthops
    )


# ---------------------------------------------------------------------------
# command handlers
# ---------------------------------------------------------------------------


def cmd_kvstore(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "keys":
        pub = client.call(
            "getKvStoreKeyValsFiltered",
            area=args.area,
            prefixes=[args.prefix] if args.prefix else [],
        )
        rows = [
            [k, v["originator_id"], v["version"], v["ttl"], v["ttl_version"]]
            for k, v in sorted(pub["key_vals"].items())
        ]
        _print_table(
            ["Key", "Originator", "Version", "TTL(ms)", "TTL-Version"], rows
        )
    elif args.cmd == "keyvals":
        pub = client.call(
            "getKvStoreKeyVals", area=args.area, keys=args.keys
        )
        for key, v in sorted(pub["key_vals"].items()):
            print(f"> {key}")
            obj = decode_obj(v["value"])
            _print_json(
                obj if not hasattr(obj, "__dict__") else vars(obj)
            )
    elif args.cmd == "peers":
        peers = client.call("getKvStorePeers", area=args.area)
        _print_table(
            ["Peer", "Address"],
            [[name, spec["peer_addr"]] for name, spec in sorted(peers.items())],
        )
    elif args.cmd == "areas":
        _print_json(client.call("getAreasConfig"))


def cmd_decision(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "adj":
        dbs = client.call("getDecisionAdjacencyDbs")
        rows = []
        for node, blob in sorted(dbs.items()):
            db = decode_obj(blob)
            for adj in db.adjacencies:
                rows.append(
                    [
                        node,
                        adj.other_node_name,
                        adj.if_name,
                        adj.metric,
                        "overloaded" if adj.is_overloaded else "",
                    ]
                )
        _print_table(["Node", "Neighbor", "Iface", "Metric", "Flags"], rows)
    elif args.cmd == "prefixes":
        dbs = client.call("getDecisionPrefixDbs")
        rows = []
        for node_area, blob in sorted(dbs.items()):
            db = decode_obj(blob)
            for entry in db.prefix_entries:
                rows.append(
                    [node_area, str(entry.prefix), entry.type.value]
                )
        _print_table(["Node:Area", "Prefix", "Type"], rows)
    elif args.cmd == "routes":
        db = client.call("getRouteDbComputed", node=args.node)
        rows = []
        for blob in db["unicast_routes"]:
            route = decode_obj(blob)
            rows.append([str(route.dest), _fmt_nexthops(route)])
        _print_table(["Prefix", "Nexthops"], rows)
        if db["mpls_routes"]:
            rows = []
            for blob in db["mpls_routes"]:
                route = decode_obj(blob)
                rows.append([route.top_label, _fmt_nexthops(route)])
            _print_table(["Label", "Nexthops"], rows)
    elif args.cmd == "rib-policy":
        _print_json(client.call("getRibPolicy"))


def cmd_fib(client: BlockingCtrlClient, args) -> None:
    if args.cmd in ("routes", "unicast-routes"):
        routes = client.call(
            "getUnicastRoutesFiltered", prefixes=args.prefixes or []
        )
        rows = []
        for blob in routes:
            route = decode_obj(blob)
            rows.append([str(route.dest), _fmt_nexthops(route)])
        _print_table(["Prefix", "Nexthops"], rows)
    elif args.cmd == "mpls-routes":
        routes = client.call("getMplsRoutesFiltered", labels=[])
        rows = []
        for blob in routes:
            route = decode_obj(blob)
            rows.append([route.top_label, _fmt_nexthops(route)])
        _print_table(["Label", "Nexthops"], rows)
    elif args.cmd == "counters":
        counters = client.call("getCounters")
        fib_counters = {
            k: v for k, v in sorted(counters.items()) if k.startswith("fib.")
        }
        _print_json(fib_counters)


def cmd_lm(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "links":
        ifaces = client.call("getInterfaces")
        rows = [
            [
                name,
                "UP" if info["is_up"] else "DOWN",
                "active" if info["is_active"] else "dampened",
                ",".join(info["addresses"]) or "-",
            ]
            for name, info in sorted(ifaces.items())
        ]
        _print_table(["Interface", "Status", "Dampening", "Addresses"], rows)
    elif args.cmd == "set-node-overload":
        client.call("setNodeOverload")
        print("node overload: SET")
    elif args.cmd == "unset-node-overload":
        client.call("unsetNodeOverload")
        print("node overload: UNSET")
    elif args.cmd == "set-link-overload":
        client.call("setInterfaceOverload", interface=args.interface)
        print(f"link overload SET on {args.interface}")
    elif args.cmd == "unset-link-overload":
        client.call("unsetInterfaceOverload", interface=args.interface)
        print(f"link overload UNSET on {args.interface}")
    elif args.cmd == "set-link-metric":
        client.call(
            "setInterfaceMetric",
            interface=args.interface,
            metric=args.metric,
        )
        print(f"metric {args.metric} SET on {args.interface}")
    elif args.cmd == "unset-link-metric":
        client.call("unsetInterfaceMetric", interface=args.interface)
        print(f"metric override UNSET on {args.interface}")


def cmd_prefixmgr(client: BlockingCtrlClient, args) -> None:
    from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType

    if args.cmd == "view":
        entries = [decode_obj(b) for b in client.call("getPrefixes")]
        _print_table(
            ["Prefix", "Type", "Forwarding"],
            [
                [str(e.prefix), e.type.value, e.forwarding_type.name]
                for e in entries
            ],
        )
    elif args.cmd == "advertise":
        entries = [
            PrefixEntry(
                prefix=IpPrefix(p), type=PrefixType(args.prefix_type)
            )
            for p in args.prefixes
        ]
        client.call(
            "advertisePrefixes",
            prefixes=[encode_obj(e) for e in entries],
        )
        print(f"advertised {len(entries)} prefixes")
    elif args.cmd == "withdraw":
        entries = [
            PrefixEntry(
                prefix=IpPrefix(p), type=PrefixType(args.prefix_type)
            )
            for p in args.prefixes
        ]
        client.call(
            "withdrawPrefixes",
            prefixes=[encode_obj(e) for e in entries],
        )
        print(f"withdrew {len(entries)} prefixes")
    elif args.cmd == "sync":
        entries = [
            PrefixEntry(
                prefix=IpPrefix(p), type=PrefixType(args.prefix_type)
            )
            for p in args.prefixes
        ]
        client.call(
            "syncPrefixesByType",
            type=args.prefix_type,
            prefixes=[encode_obj(e) for e in entries],
        )
        print(f"synced {len(entries)} prefixes of type {args.prefix_type}")


def cmd_monitor(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "counters":
        _print_json(client.call("getCounters"))
    elif args.cmd == "logs":
        for log_json in client.call("getEventLogs"):
            print(log_json)


def cmd_openr(client: BlockingCtrlClient, args) -> None:
    if args.cmd == "version":
        print(VERSION)
        print("node:", client.call("getMyNodeName"))
    elif args.cmd == "config":
        _print_json(client.call("getRunningConfig"))


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="breeze", description="openr-tpu operator CLI"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=2018)
    sub = parser.add_subparsers(dest="module", required=True)

    kv = sub.add_parser("kvstore").add_subparsers(dest="cmd", required=True)
    p = kv.add_parser("keys")
    p.add_argument("--prefix", default="")
    p.add_argument("--area", default="0")
    p = kv.add_parser("keyvals")
    p.add_argument("keys", nargs="+")
    p.add_argument("--area", default="0")
    p = kv.add_parser("peers")
    p.add_argument("--area", default="0")
    kv.add_parser("areas")

    dec = sub.add_parser("decision").add_subparsers(dest="cmd", required=True)
    dec.add_parser("adj")
    dec.add_parser("prefixes")
    p = dec.add_parser("routes")
    p.add_argument("--node", default=None)
    dec.add_parser("rib-policy")

    fib = sub.add_parser("fib").add_subparsers(dest="cmd", required=True)
    p = fib.add_parser("routes")
    p.add_argument("prefixes", nargs="*")
    p = fib.add_parser("unicast-routes")
    p.add_argument("prefixes", nargs="*")
    fib.add_parser("mpls-routes")
    fib.add_parser("counters")

    lm = sub.add_parser("lm").add_subparsers(dest="cmd", required=True)
    lm.add_parser("links")
    lm.add_parser("set-node-overload")
    lm.add_parser("unset-node-overload")
    for name in ("set-link-overload", "unset-link-overload",
                 "unset-link-metric"):
        p = lm.add_parser(name)
        p.add_argument("interface")
    p = lm.add_parser("set-link-metric")
    p.add_argument("interface")
    p.add_argument("metric", type=int)

    pm = sub.add_parser("prefixmgr").add_subparsers(dest="cmd", required=True)
    pm.add_parser("view")
    for name in ("advertise", "withdraw", "sync"):
        p = pm.add_parser(name)
        p.add_argument("prefixes", nargs="+")
        p.add_argument("--prefix-type", default="BREEZE")

    mon = sub.add_parser("monitor").add_subparsers(dest="cmd", required=True)
    mon.add_parser("counters")
    mon.add_parser("logs")

    op = sub.add_parser("openr").add_subparsers(dest="cmd", required=True)
    op.add_parser("version")
    op.add_parser("config")

    return parser


_HANDLERS = {
    "kvstore": cmd_kvstore,
    "decision": cmd_decision,
    "fib": cmd_fib,
    "lm": cmd_lm,
    "prefixmgr": cmd_prefixmgr,
    "monitor": cmd_monitor,
    "openr": cmd_openr,
}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        with BlockingCtrlClient(args.host, args.port) as client:
            _HANDLERS[args.module](client, args)
        return 0
    except ConnectionRefusedError:
        print(
            f"cannot connect to openr-tpu at {args.host}:{args.port}",
            file=sys.stderr,
        )
        return 1


if __name__ == "__main__":
    sys.exit(main())
