"""Operator CLI (breeze equivalent, openr/py/openr/cli/)."""
