"""Decision module: LSDB subscription → debounced SPF → route deltas.

Equivalent of openr/decision/Decision.{h,cpp} module shell (the computation
itself lives in openr_tpu.solver).
"""

from openr_tpu.decision.decision import Decision, DecisionConfig

__all__ = ["Decision", "DecisionConfig"]
