"""Decision module: consumes KvStore publications, maintains per-area
LinkState + global PrefixState, debounces, solves, emits route deltas.

Behavioral port of openr/decision/Decision.{h,cpp} module shell:
  - processPublication (Decision.cpp:1631-1763): 'adj:<node>' values update
    the area's LinkState (with ordered-FIB holds when enabled);
    'prefix:...' values update PrefixState (per-node or per-prefix keys);
    expired keys delete the corresponding db.
  - pending-updates batch tracker (Decision.h:95-207): counts + the perf
    event trace of the oldest event in the batch.
  - debounced rebuild (AsyncDebounce, Decision.cpp:1406) between
    debounce_min and debounce_max.
  - cold-start timer (eor_time_s) delays the first computation so the LSDB
    can fill after restart (Decision.cpp:1353-1359).
  - RibPolicy applied to unicast routes before emission
    (Decision.cpp:1831-1865), with TTL expiry re-emission.
  - solver backend selected by config: 'cpu' oracle or 'tpu' batched
    (the BASELINE.json north-star plugin seam).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.messaging import QueueClosedError, RQueue, ReplicateQueue
from openr_tpu.monitor.spans import Span
from openr_tpu.solver import (
    DecisionRouteDb,
    DecisionRouteUpdate,
    DeltaRouteBuilder,
    SolverSupervisor,
    SpfSolver,
    SupervisorConfig,
    TpuSpfSolver,
    get_route_delta,
)
from openr_tpu.solver.rib_policy import RibPolicy
from openr_tpu.types import (
    ADJ_DB_MARKER,
    PREFIX_DB_MARKER,
    AdjacencyDatabase,
    PerfEvents,
    PrefixDatabase,
    Publication,
    parse_prefix_key,
)
from openr_tpu.utils import AsyncDebounce
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin
from openr_tpu.utils.ownership import owned_by
from openr_tpu.utils import serializer

import dataclasses
import functools


@functools.lru_cache(maxsize=65536)
def _loads_cached(data: bytes):
    """Shared LSDB value decode cache.

    KvStore re-floods the same serialized value many times (full syncs
    after restart; every node of an in-process emulation decoding the same
    bytes). Decoded objects MUST be treated as immutable by all consumers
    — Decision copies before its one mutation (area stamping)."""
    return serializer.loads(data)


def _adjacencies_to_me_changed(
    prior_db: Optional[AdjacencyDatabase],
    adj_db: AdjacencyDatabase,
    me: str,
) -> bool:
    """DeltaPath qualification for a neighbor's adjacency update.

    My route inputs beyond distances (nexthop addresses, my link up/down,
    my triangle weights) can only move when the neighbor's adjacencies TO
    ME changed: the LinkState ordered diff applies only the advertising
    node's own direction, so a far-side-only update leaves every link to
    me byte-identical. Compares exactly the fields that diff consumes; a
    node with no prior advertisement is structural and forces the full
    path through the comparison (None != [...])."""

    def to_me(db: Optional[AdjacencyDatabase]):
        if db is None:
            return None
        return sorted(
            (
                adj.if_name,
                adj.other_if_name,
                adj.metric,
                adj.adj_label,
                adj.is_overloaded,
                adj.nexthop_v4,
                adj.nexthop_v6,
            )
            for adj in db.adjacencies
            if adj.other_node_name == me
        )

    new = to_me(adj_db)
    if not new and not (prior_db is not None and to_me(prior_db)):
        return False  # no adjacency to me on either side of the update
    return to_me(prior_db) != new


def _load_adj_db(data: bytes, area: str) -> AdjacencyDatabase:
    adj_db = _loads_cached(data)
    assert isinstance(adj_db, AdjacencyDatabase)
    if adj_db.area != area:
        # copy-on-write: never stamp the shared cached object
        adj_db = dataclasses.replace(adj_db, area=area)
    return adj_db


@dataclass
class DecisionConfig:
    my_node_name: str
    areas: List[str] = field(default_factory=lambda: ["0"])
    solver_backend: str = "cpu"  # 'cpu' | 'tpu'
    # (batch, graph) device-mesh shape for the tpu backend; None = single
    # device. Resolved against jax.devices() by TpuSpfSolver on first solve.
    solver_mesh: Optional[tuple] = None
    enable_v4: bool = True
    compute_lfa_paths: bool = False
    enable_ordered_fib: bool = False
    bgp_dry_run: bool = False
    bgp_use_igp_metric: bool = False
    debounce_min: float = 0.01  # 10ms (docs/Runbook.md:425-435)
    debounce_max: float = 0.25  # 250ms
    eor_time_s: float = 0.0  # cold-start hold; 0 = no hold
    # solver fault domain (docs/Robustness.md): the tpu backend runs under
    # a SolverSupervisor — error-classified retries, a circuit breaker
    # falling back to the CPU oracle, probe-driven recovery, and an
    # every-Nth-solve warm-state audit (0 disables the audit)
    solver_supervised: bool = True
    solver_failure_threshold: int = 3
    solver_max_attempts: int = 2
    solver_deadline_s: float = 30.0
    solver_probe_interval_s: float = 5.0
    solver_probe_successes: int = 2
    solver_audit_interval: int = 0
    # partial-mesh degradation ladder: a device-loss streak re-resolves
    # the solver mesh over surviving chips before the breaker may open
    solver_mesh_degrade: bool = True
    # resident blocked-FW all-pairs matrix (docs/Apsp.md): areas up to
    # solver_apsp_max_nodes real nodes keep a device-resident APSP matrix
    # serving LFA qualification, KSP layer seeding and TE hard-scoring —
    # and keeping DeltaPath enabled under compute_lfa_paths; solver_apsp
    # off disables it wholesale (big areas fall back per-area regardless)
    solver_apsp: bool = True
    solver_apsp_max_nodes: int = 4096
    # flight recorder (solver/flight_recorder.py, docs/Monitoring.md):
    # per-area SolveTrace ring bound, the sampled phase-timing cadence
    # (every Nth solve takes block_until_ready barriers at phase seams;
    # 0 disables sampling), and an optional directory forensics dumps
    # are written to as JSON artifacts
    solver_trace_ring: int = 64
    solver_trace_sample_every: int = 16
    solver_forensics_dir: Optional[str] = None
    # device-memory observatory (monitor/memledger.py,
    # docs/Monitoring.md "Device-memory observatory"): capacity admission
    # keeps this fraction of device capacity free when gating layouts
    # (predict_fit headroom), and an explicit capacity override in bytes
    # stands in for backends that expose no memory_stats (0 = auto-detect;
    # without stats the static caps like solver_apsp_max_nodes remain the
    # only gate)
    solver_mem_headroom_frac: float = 0.10
    solver_mem_capacity_bytes: int = 0


# wall-clock PerfEvent descriptors mapped onto convergence-span stages:
# the origin's pre-publish chain rides the advertised AdjacencyDatabase
# (linkmonitor/link_monitor.py), the flood-hop trace rides the publication
# itself (kvstore/store.py) — remote nodes reconstruct the monotonic span
# from these, so every node's CONVERGENCE_TRACE covers spark→fib
_PRE_STAGE_EVENTS = {
    "NEIGHBOR_EVENT_RECVD": "spark.neighbor_event",
    "ADJ_DB_ADVERTISED": "linkmonitor.adj_advertised",
}
_FLOOD_ORIGINATED = "KVSTORE_FLOOD_ORIGINATED"
_FLOOD_RECEIVED = "KVSTORE_FLOOD_RECEIVED"


class _PendingUpdates:
    """Batch tracker (Decision.h:95-207), extended with the DeltaPath dirty
    set: the prefixes whose advertisements this batch touched, and whether
    anything in the batch disqualifies the partial route rebuild (label
    moves, adjacency changes incident to me, structural deletes)."""

    def __init__(self) -> None:
        self.count = 0
        self.perf_events: Optional[PerfEvents] = None
        self.needs_route_update = False
        self.span: Optional[Span] = None
        self.dirty_prefixes: Set = set()
        self.force_full = False

    def apply(
        self,
        perf_events: Optional[PerfEvents],
        publication: Optional[Publication] = None,
    ) -> None:
        if self.count == 0:
            # the batch's oldest event is the one convergence is measured
            # from: stamp it on the MONOTONIC clock (seeded from the local
            # KvStore publication stamp when one rode along) so
            # convergence.e2e_ms is immune to wall-clock jumps — the
            # PerfEvents trace below stays wall-clock for cross-node
            # reporting, the span owns all local latency math
            self.span = _build_span(perf_events, publication)
            self.span.mark("decision.recv")
        self.count += 1
        self.needs_route_update = True
        # keep the OLDEST event trace in the batch (Decision.h:174-191)
        if perf_events is not None and (
            self.perf_events is None
            or (
                perf_events.events
                and self.perf_events.events
                and perf_events.events[0].unix_ts
                < self.perf_events.events[0].unix_ts
            )
        ):
            self.perf_events = perf_events.copy()

    def reset(self) -> None:
        self.count = 0
        self.perf_events = None
        self.needs_route_update = False
        self.span = None
        self.dirty_prefixes = set()
        self.force_full = False


def _build_span(
    perf_events: Optional[PerfEvents],
    publication: Optional[Publication],
) -> Span:
    """Seed one convergence Span with every stage known to predate the
    local publish stamp.

    On the ORIGINATING node the pre-publish chain arrives as exact
    monotonic marks (Publication.span_stages). On REMOTE nodes the same
    chain — plus the flood hops in between — is reconstructed from the
    wall-clock PerfEvents: each event's monotonic time is `now_mono -
    (now_wall - event_wall)`, exact inside one emulator host and
    NTP-accurate across real hosts (which is the precision cross-node
    measurement has anyway). From kvstore.publish on, every mark is live.
    """
    pub_ts = publication.ts_monotonic if publication is not None else None
    stages: List = []
    span_stages = (
        publication.span_stages if publication is not None else None
    )
    wall: List = []
    if span_stages:
        stages.extend(span_stages)
    elif perf_events is not None:
        for ev in perf_events.events:
            stage = _PRE_STAGE_EVENTS.get(ev.event_descr)
            if stage is not None:
                wall.append((stage, ev.unix_ts))
    flood = publication.perf_events if publication is not None else None
    if flood is not None:
        hop = 0
        for ev in flood.events:
            if ev.event_descr == _FLOOD_ORIGINATED:
                wall.append(("kvstore.flood.origin", ev.unix_ts))
            elif ev.event_descr == _FLOOD_RECEIVED:
                hop += 1
                wall.append((f"kvstore.flood.hop{hop}", ev.unix_ts))
    if wall:
        now_mono = time.monotonic()
        now_wall_ms = time.time() * 1e3
        stages.extend(
            (stage, now_mono - max(0.0, now_wall_ms - ts) / 1e3)
            for stage, ts in wall
        )
    stages.sort(key=lambda s: s[1])
    if pub_ts is not None:
        # the publish stamp bounds every pre-publish stage
        stages = [(stage, min(ts, pub_ts)) for stage, ts in stages]
    t0 = stages[0][1] if stages else pub_ts
    span = Span("convergence", t0=t0)
    for stage, ts in stages:
        span.mark(stage, ts=ts)
    if pub_ts is not None:
        span.mark("kvstore.publish", ts=pub_ts)
    return span


@owned_by("decision-loop")
class Decision(CountersMixin, HistogramsMixin):
    def __init__(
        self,
        config: DecisionConfig,
        kvstore_updates: RQueue,
        route_updates_queue: ReplicateQueue,
        static_routes_updates: Optional[RQueue] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        watchdog=None,
        log_sample_fn=None,
    ) -> None:
        self.config = config
        self.kvstore_updates = kvstore_updates
        self.route_updates_queue = route_updates_queue
        self.static_routes_updates = static_routes_updates
        self._loop = loop
        self._log_sample_fn = log_sample_fn
        # lazy TE engine (openr_tpu/te): built on the first runTeOptimize
        self._te_service = None

        solver_kwargs = dict(
            enable_v4=config.enable_v4,
            compute_lfa_paths=config.compute_lfa_paths,
            enable_ordered_fib=config.enable_ordered_fib,
            bgp_dry_run=config.bgp_dry_run,
            bgp_use_igp_metric=config.bgp_use_igp_metric,
        )
        # device-memory observatory knobs apply to the process-wide ledger
        # before any backend registers resident state
        from openr_tpu.monitor.memledger import get_ledger

        ledger = get_ledger()
        ledger.set_headroom_frac(config.solver_mem_headroom_frac)
        ledger.set_capacity_override(
            config.solver_mem_capacity_bytes
            if config.solver_mem_capacity_bytes > 0
            else None
        )
        if config.solver_backend == "tpu":
            primary = TpuSpfSolver(
                config.my_node_name,
                mesh=config.solver_mesh,
                apsp_max_nodes=(
                    config.solver_apsp_max_nodes if config.solver_apsp else 0
                ),
                # the APSP shadow audit shares the warm-state audit cadence
                apsp_audit_interval=config.solver_audit_interval,
                **solver_kwargs,
            )
            if config.solver_supervised:
                # the solve path's fault domain: device faults degrade to
                # the CPU oracle behind a circuit breaker instead of
                # unwinding into this module's event loop
                self.solver = SolverSupervisor(
                    primary,
                    SpfSolver(config.my_node_name, **solver_kwargs),
                    SupervisorConfig(
                        failure_threshold=config.solver_failure_threshold,
                        max_attempts=config.solver_max_attempts,
                        solve_deadline_s=config.solver_deadline_s,
                        probe_interval_s=config.solver_probe_interval_s,
                        probe_successes_to_close=(
                            config.solver_probe_successes
                        ),
                        audit_interval=config.solver_audit_interval,
                        mesh_degrade=config.solver_mesh_degrade,
                        trace_ring_size=config.solver_trace_ring,
                        trace_sample_every=(
                            config.solver_trace_sample_every
                        ),
                        forensics_dir=config.solver_forensics_dir,
                    ),
                    watchdog=watchdog,
                    log_sample_fn=log_sample_fn,
                )
            else:
                self.solver = primary
        else:
            self.solver = SpfSolver(config.my_node_name, **solver_kwargs)
        self.area_link_states: Dict[str, LinkState] = {
            area: LinkState(area) for area in config.areas
        }
        self.prefix_state = PrefixState()
        # per-prefix-key aggregation (Decision.cpp:1584-1629), keyed by
        # (node, area): per-prefix entries override full-db entries
        self._per_prefix_entries: Dict[tuple, Dict] = {}
        self._full_db_entries: Dict[tuple, Dict] = {}
        self.route_db = DecisionRouteDb()
        self.rib_policy: Optional[RibPolicy] = None
        # DeltaPath: builds DecisionRouteUpdates directly from the device
        # delta's changed destinations when the event qualifies, falling
        # back to the classic full build + get_route_delta diff
        self._delta_builder = DeltaRouteBuilder(self.solver)
        self._pending = _PendingUpdates()
        self._rebuild_debounce = AsyncDebounce(
            config.debounce_min,
            config.debounce_max,
            self.rebuild_routes,
            loop=loop,
        )
        self._cold_start_until: Optional[float] = None
        self._cold_start_timer: Optional[asyncio.TimerHandle] = None
        self._retry_timer: Optional[asyncio.TimerHandle] = None
        self._rib_policy_timer: Optional[asyncio.TimerHandle] = None
        self._task: Optional[asyncio.Task] = None
        self.counters: Dict[str, int] = {}
        self.histograms: Dict = {}
        if isinstance(self.solver, SolverSupervisor):
            # breaker trips, probes and audits happen in the BACKGROUND,
            # between rebuilds — the supervisor records straight into this
            # module's monitor-registered dicts so getCounters/ctrl always
            # read live fault-domain state, not the last rebuild's copy
            self.solver.counters = self.counters
            self.solver.histograms = self.histograms
            self.counters["decision.spf.fallback_active"] = 0
        self.have_computed_routes = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    def start(self) -> None:
        # warm-boot hygiene: any device-resident warm state surviving into
        # this start (an in-process emulator restart hands the same
        # process — and its compile caches — a fresh daemon) is dropped
        # exactly like a resharding event drops it: the first solve after
        # a whole-node restart must be a cold start, never a warm
        # continuation of pre-restart buffers (docs/Robustness.md)
        invalidate = getattr(self.solver, "invalidate_warm_state", None)
        if invalidate is not None:
            invalidate()
        if self.config.eor_time_s > 0:
            self._cold_start_until = (
                self.loop().time() + self.config.eor_time_s
            )
            self._cold_start_timer = self.loop().call_later(
                self.config.eor_time_s, self._end_cold_start
            )
        if isinstance(self.solver, SolverSupervisor):
            self.solver.start(self.loop())  # background health-probe loop
        self._task = self.loop().create_task(self._run())

    def stop(self) -> None:
        if isinstance(self.solver, SolverSupervisor):
            self.solver.stop()
        # device-memory observatory: daemon stop releases every ledger-
        # registered structure (teardown returns the ledger to baseline)
        solver_close = getattr(self.solver, "close", None)
        if solver_close is not None:
            solver_close()
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self._rebuild_debounce.cancel()
        if self._cold_start_timer is not None:
            self._cold_start_timer.cancel()
            self._cold_start_timer = None
        if self._rib_policy_timer is not None:
            self._rib_policy_timer.cancel()
        if self._retry_timer is not None:
            self._retry_timer.cancel()
            self._retry_timer = None

    def _retry_rebuild(self) -> None:
        self._retry_timer = None
        self.rebuild_routes()

    def _end_cold_start(self) -> None:
        self._cold_start_until = None
        self._pending.needs_route_update = True
        self._pending.force_full = True
        self.rebuild_routes()

    async def _run(self) -> None:
        tasks = [self._consume_kvstore()]
        if self.static_routes_updates is not None:
            tasks.append(self._consume_static())
        await asyncio.gather(*tasks, return_exceptions=True)

    async def _consume_kvstore(self) -> None:
        while True:
            try:
                pub = await self.kvstore_updates.get()
            except (QueueClosedError, asyncio.CancelledError):
                return
            self.process_publication(pub)

    async def _consume_static(self) -> None:
        try:
            while True:
                update = await self.static_routes_updates.get()
                mpls_to_update, mpls_to_delete = update
                self.solver.push_static_routes_delta(
                    mpls_to_update, mpls_to_delete
                )
                static = self.solver.process_static_route_updates()
                if static is not None and not static.empty():
                    self.route_updates_queue.push(static)
        except (QueueClosedError, asyncio.CancelledError):
            pass

    # ------------------------------------------------------------------
    # publication processing
    # ------------------------------------------------------------------

    # minimum adj keys in one publication for the bulk cold-start ingest;
    # small batches gain nothing over the incremental diff path
    _BULK_ADJ_THRESHOLD = 8

    def process_publication(self, publication: Publication) -> None:
        area = publication.area
        link_state = self.area_link_states.get(area)
        if link_state is None:
            # unknown area: create on the fly (config-less area discovery)
            link_state = LinkState(area)
            self.area_link_states[area] = link_state

        changed = False
        bulk_keys = self._bulk_adj_keys(publication, link_state)
        if bulk_keys:
            changed |= self._bulk_ingest_adj(
                publication, bulk_keys, area, link_state
            )
        for key, value in publication.key_vals.items():
            if value.value is None or key in bulk_keys:
                continue  # ttl refresh only / already bulk-ingested
            try:
                changed |= self._process_key(
                    key, value, area, link_state, publication
                )
            except Exception:
                # a malformed value must not poison the rest of the batch
                # (Decision.cpp:1726-1729 catches per-key)
                import logging

                logging.getLogger(__name__).exception(
                    "failed to process key %s", key
                )
                self._bump("decision.errors")

        for key in publication.expired_keys:
            if key.startswith(ADJ_DB_MARKER):
                node = key[len(ADJ_DB_MARKER):]
                if link_state.delete_adjacency_database(node).topology_changed:
                    changed = True
                    self._pending.force_full = True  # structural delete
                    self._pending.apply(None, publication)
            elif key.startswith(PREFIX_DB_MARKER):
                node, _, _ = parse_prefix_key(key)
                delete_db = PrefixDatabase(
                    this_node_name=node, delete_prefix=True
                )
                node_db = self._update_node_prefix_database(
                    key, delete_db, area
                )
                if node_db is None:
                    continue
                node_db.area = area
                dirty = self.prefix_state.update_prefix_database(node_db)
                if dirty:
                    changed = True
                    self._pending.dirty_prefixes |= dirty
                    self._pending.apply(None, publication)

        if changed:
            self._schedule_rebuild()

    def _bulk_adj_keys(
        self, publication: Publication, link_state: LinkState
    ) -> Set[str]:
        """Keys eligible for the cold-start bulk adjacency ingest: the area
        LinkState is empty (a KvStore full sync after restart) and the
        publication carries a batch of adj keys. Ordered-FIB holds are
        irrelevant here — with an empty graph every hop-distance lookup
        yields zero holds, which is what the bulk path applies."""
        if link_state.num_nodes() or link_state.get_adjacency_databases():
            return set()
        keys = {
            key
            for key, value in publication.key_vals.items()
            if key.startswith(ADJ_DB_MARKER) and value.value is not None
        }
        return keys if len(keys) >= self._BULK_ADJ_THRESHOLD else set()

    def _bulk_ingest_adj(
        self,
        publication: Publication,
        keys: Set[str],
        area: str,
        link_state: LinkState,
    ) -> bool:
        """Deserialize + ingest a full-sync batch of adj dbs in one pass
        (LinkState.bulk_update_adjacency_databases). Per-key malformed
        values are dropped with the same error accounting as the
        incremental path."""
        adj_dbs: List[AdjacencyDatabase] = []
        for key in sorted(keys):  # deterministic ingest order
            try:
                adj_dbs.append(
                    _load_adj_db(publication.key_vals[key].value, area)
                )
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "failed to process key %s", key
                )
                self._bump("decision.errors")
        change = link_state.bulk_update_adjacency_databases(adj_dbs)
        self._bump("decision.adj_db_update", len(adj_dbs))
        self._bump("decision.bulk_adj_ingests")
        self._pending.force_full = True  # cold-start ingest
        if not (
            change.topology_changed
            or change.link_attributes_changed
            or change.node_label_changed
        ):
            return False
        for db in adj_dbs:
            self._pending.apply(db.perf_events, publication)
        return True

    def _process_key(
        self,
        key: str,
        value,
        area: str,
        link_state: LinkState,
        publication: Optional[Publication] = None,
    ) -> bool:
        """Apply one LSDB key; returns True if state changed."""
        changed = False
        if key.startswith(ADJ_DB_MARKER):
            adj_db = _load_adj_db(value.value, area)
            # snapshot the previous advertisement before the LinkState
            # diff replaces it: the DeltaPath qualification below compares
            # the adjacencies-to-me across the update
            prior_db = link_state.get_adjacency_databases().get(
                adj_db.this_node_name
            )
            hold_up = hold_down = 0
            if self.config.enable_ordered_fib:
                # hold TTLs from hop distance (Decision.cpp:1669-1679)
                maybe_hops = link_state.get_hops_from_a_to_b(
                    self.config.my_node_name, adj_db.this_node_name
                )
                if maybe_hops is not None:
                    hold_up = maybe_hops
                    hold_down = (
                        link_state.get_max_hops_to_node(adj_db.this_node_name)
                        - hold_up
                    )
            change = link_state.update_adjacency_database(
                adj_db, hold_up, hold_down
            )
            self._bump("decision.adj_db_update")
            if (
                change.topology_changed
                or change.link_attributes_changed
                or change.node_label_changed
            ):
                changed = True
                # DeltaPath qualification: a label move re-arbitrates the
                # whole node-label table, my own advertisement changes my
                # links wholesale, and a neighbor whose adjacency TO ME
                # changed moves route inputs (nexthop addresses, link
                # up/down, my triangle weights) no distance column
                # reflects. A neighbor update where the adjacency to me is
                # byte-identical — only FAR-side links changed — leaves
                # the link to me untouched and stays on the delta path
                # (the narrowed ROADMAP refusal; the ordered diff only
                # applies the advertising node's own direction).
                me = self.config.my_node_name
                if (
                    change.node_label_changed
                    or adj_db.this_node_name == me
                    or _adjacencies_to_me_changed(prior_db, adj_db, me)
                ):
                    self._pending.force_full = True
                self._pending.apply(adj_db.perf_events, publication)
        elif key.startswith(PREFIX_DB_MARKER):
            # cached decode: prefix dbs are never mutated by this module
            # (aggregation builds fresh node_db objects)
            prefix_db = _loads_cached(value.value)
            assert isinstance(prefix_db, PrefixDatabase)
            node_db = self._update_node_prefix_database(key, prefix_db, area)
            if node_db is None:
                return False
            node_db.area = area
            self._bump("decision.prefix_db_update")
            dirty = self.prefix_state.update_prefix_database(node_db)
            if dirty:
                changed = True
                self._pending.dirty_prefixes |= dirty
                self._pending.apply(prefix_db.perf_events, publication)
        return changed

    def _update_node_prefix_database(
        self, key: str, prefix_db: PrefixDatabase, pub_area: str
    ) -> Optional[PrefixDatabase]:
        """Merge a per-prefix or full-db key into the node's aggregated
        PrefixDatabase (Decision.cpp:1584-1629). Per-prefix entries override
        full-db entries; aggregation is per (node, area) so one node's
        advertisements in different areas never bleed into each other."""
        node = prefix_db.this_node_name
        _, key_area, key_prefix = parse_prefix_key(key)
        agg_key = (node, key_area if key_area is not None else pub_area)
        per_prefix = self._per_prefix_entries.setdefault(agg_key, {})
        full_db = self._full_db_entries.setdefault(agg_key, {})
        if key_prefix is not None:
            # per-prefix key
            if prefix_db.delete_prefix:
                per_prefix.pop(key_prefix, None)
            else:
                assert len(prefix_db.prefix_entries) == 1, key
                entry = prefix_db.prefix_entries[0]
                # ignore self-redistributed route reflection
                # (Decision.cpp:1598-1604)
                if (
                    node == self.config.my_node_name
                    and entry.area_stack
                    and entry.area_stack[0] in self.area_link_states
                ):
                    return None
                per_prefix[key_prefix] = entry
        else:
            full_db.clear()
            for entry in prefix_db.prefix_entries:
                full_db[entry.prefix] = entry

        node_db = PrefixDatabase(
            this_node_name=node, perf_events=prefix_db.perf_events
        )
        node_db.prefix_entries.extend(per_prefix.values())
        node_db.prefix_entries.extend(
            entry
            for prefix, entry in full_db.items()
            if prefix not in per_prefix
        )
        return node_db

    def _schedule_rebuild(self) -> None:
        if self._cold_start_until is not None:
            return  # waiting for LSDB fill after restart
        self._rebuild_debounce()

    # ------------------------------------------------------------------
    # route computation + emission
    # ------------------------------------------------------------------

    def rebuild_routes(self) -> None:
        """Debounced batch solve + delta emission (Decision.cpp:1771-1814).

        DeltaPath: when every LSDB event in the batch rode the device
        delta-extraction path, the DecisionRouteUpdate is built directly
        from the changed destinations (DeltaRouteBuilder) — no full table
        rebuild, no full-db diff — and streamed into Fib's incremental
        programming path like any other update."""
        if self._cold_start_until is not None:
            return
        if not self._pending.needs_route_update:
            return
        perf_events = self._pending.perf_events
        span = self._pending.span
        dirty_prefixes = self._pending.dirty_prefixes
        force_full = self._pending.force_full or not self.have_computed_routes
        self._bump("decision.batched_updates", self._pending.count)
        self._pending.reset()
        self._bump("decision.route_build_runs")
        if span is not None:
            # oldest-event recv -> debounce fire, on the monotonic clock
            self._observe("decision.debounce_ms", span.mark("decision.debounce"))

        t0 = time.perf_counter()
        try:
            new_db, delta, used_delta = self._delta_builder.build(
                self.config.my_node_name,
                self.area_link_states,
                self.prefix_state,
                self.route_db,
                dirty_prefixes=dirty_prefixes,
                force_full=force_full,
                policy_fn=self._rib_policy_entry_fn(),
            )
        except Exception:
            # rebuild_routes runs from a loop timer callback: an uncaught
            # exception here vanishes into the loop's exception handler and
            # the daemon silently stops converging. Log + count + schedule a
            # retry at the debounce MAX (a direct timer: re-arming the
            # debouncer would fire at debounce_min again — its backoff
            # resets on every fire — and a persistent failure would then
            # burn the loop with ~100 failed full rebuilds per second).
            import logging

            logging.getLogger(__name__).exception("route build failed")
            self._bump("decision.route_build_errors")
            self._pending.needs_route_update = True
            # the dirty snapshot was consumed: the retry must not trust it
            self._pending.force_full = True
            if self._retry_timer is not None:
                self._retry_timer.cancel()
            self._retry_timer = self.loop().call_later(
                self.config.debounce_max, self._retry_rebuild
            )
            return
        build_ms = (time.perf_counter() - t0) * 1e3
        self._observe("decision.route_build_ms", build_ms)
        if used_delta:
            self._bump("decision.route_build_delta_runs")
            self._observe("decision.route_build_delta_ms", build_ms)
        if self._delta_builder.last_error is not None:
            self._bump("decision.route_build_delta_errors")
        if span is not None:
            span.mark("decision.route_build")
        # surface the solver's SPF convergence counters (warm vs cold solve
        # split, relaxation + invalidation rounds of the last solve) and
        # profiling histograms (solve latency, warm/cold split) through this
        # module's registered dicts so getCounters/getHistograms see them;
        # histogram objects are shared by reference — the solver keeps
        # recording into them, the monitor merges copies on export
        for key, value in self.solver.counters.items():
            if key.startswith(("decision.spf.", "decision.mem.")):
                self.counters[key] = value
        for key, hist in self.solver._ensure_histograms().items():
            if key.startswith("decision.spf."):
                self._ensure_histograms()[key] = hist
        if new_db is None:
            return
        if used_delta:
            corrected = self._verify_delta_build(new_db)
            if corrected is not None:
                # shadow audit caught a divergence: serve the corrected
                # full rebuild (the partial update is superseded)
                delta = get_route_delta(corrected, self.route_db)
                new_db = corrected
        self.route_db = new_db
        self.have_computed_routes = True
        if not delta.empty():
            delta.perf_events = perf_events
            delta.span = span
            self.route_updates_queue.push(delta)
            self._bump("decision.route_updates_published")

    def _rib_policy_entry_fn(self):
        """Per-entry RibPolicy hook for the route builder (applied to every
        computed entry before diffing, on both the full and delta paths)."""
        if self.rib_policy is None or not self.rib_policy.is_active():
            return None

        def apply(entry) -> None:
            if self.rib_policy is not None and self.rib_policy.apply_action(
                entry
            ):
                self._bump("decision.rib_policy_applied")

        return apply

    def _verify_delta_build(self, new_db) -> Optional[DecisionRouteDb]:
        """Run the supervisor's route-delta shadow audit when available.
        Skipped while a RibPolicy is active: the audit's comparator is a
        raw full rebuild, which would flag every policy-transformed entry
        as divergence."""
        verify = getattr(self.solver, "verify_route_delta", None)
        if verify is None or self._rib_policy_entry_fn() is not None:
            return None
        return verify(
            new_db,
            self.config.my_node_name,
            self.area_link_states,
            self.prefix_state,
        )

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def set_rib_policy(self, policy: RibPolicy) -> None:
        """OpenrCtrl setRibPolicy (Decision.cpp:1517-1550): apply now and
        schedule re-application at expiry. A policy change transforms
        entries everywhere, so the rebuild is forced down the full path."""
        self.rib_policy = policy
        if self._rib_policy_timer is not None:
            self._rib_policy_timer.cancel()
        self._rib_policy_timer = self.loop().call_later(
            max(0.0, policy.get_ttl_duration()), self._on_rib_policy_expiry
        )
        self._pending.needs_route_update = True
        self._pending.force_full = True
        self.rebuild_routes()

    def get_rib_policy(self) -> Optional[RibPolicy]:
        return self.rib_policy

    def _on_rib_policy_expiry(self) -> None:
        # re-emit routes without the expired policy (full path: the expiry
        # un-transforms entries everywhere)
        self._pending.needs_route_update = True
        self._pending.force_full = True
        self.rebuild_routes()

    # ------------------------------------------------------------------
    # read APIs (OpenrCtrl surface)
    # ------------------------------------------------------------------

    def get_decision_route_db(
        self, node: Optional[str] = None
    ) -> Optional[DecisionRouteDb]:
        """Computed routes from this node's (or any node's) perspective
        (Decision.cpp:1437-1448)."""
        if node is None or node == self.config.my_node_name:
            return self.route_db
        solver = SpfSolver(
            node,
            enable_v4=self.config.enable_v4,
            compute_lfa_paths=self.config.compute_lfa_paths,
            enable_ordered_fib=self.config.enable_ordered_fib,
            bgp_dry_run=self.config.bgp_dry_run,
            bgp_use_igp_metric=self.config.bgp_use_igp_metric,
        )
        return solver.build_route_db(
            node, self.area_link_states, self.prefix_state
        )

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def run_te_optimize(self, params: Optional[Dict] = None) -> Dict:
        """What-if differentiable-TE optimization over the live LSDB
        (ctrl `runTeOptimize` / `breeze decision te-optimize`,
        docs/TrafficEngineering.md). Read-only against routing state: the
        report proposes weight changes, nothing is programmed. Runs
        supervised when the solver is a SolverSupervisor — a device fault
        degrades the optimization to the CPU backend and feeds the same
        breaker as SPF solves."""
        if self._te_service is None:
            from openr_tpu.te import TeService

            self._te_service = TeService(
                self.config.my_node_name,
                self.area_link_states,
                solver=self.solver,
                log_sample_fn=self._log_sample_fn,
            )
            # TE counters/histograms record straight into this module's
            # monitor-registered dicts (same pattern as the supervisor)
            self._te_service.counters = self.counters
            self._te_service.histograms = self.histograms
        return self._te_service.optimize(params)

    def get_solver_health(self) -> Dict:
        """Solver fault-domain state (ctrl getSolverHealth / `breeze
        decision solver-health`): the degraded flag, breaker state and
        probe/audit stats when supervised; a static healthy record when
        the backend runs bare (cpu oracle or supervision disabled)."""
        if isinstance(self.solver, SolverSupervisor):
            return self.solver.health()
        return {
            "degraded": False,
            "breaker_state": "unsupervised",
            "fallback_active": 0,
            "backend": self.config.solver_backend,
            "solve_ms_last": getattr(self.solver, "solve_ms_last", None),
            "delta_extract_ms_last": getattr(
                self.solver, "delta_extract_ms_last", None
            ),
            "apsp_close_ms_last": getattr(
                self.solver, "apsp_close_ms_last", None
            ),
        }

    def get_device_memory(self, area: Optional[str] = None) -> Dict:
        """Device-memory observatory surface (ctrl `getDeviceMemory` /
        `breeze decision memory`): the resident-state ledger snapshot —
        per-structure live bytes, exact-accounting totals, watermark
        reconciliation, the capacity verdict and the last admission
        refusal (docs/Monitoring.md "Device-memory observatory"). The
        ledger is process-global, so this answers even when the backend
        runs bare; `area` narrows the entry listing only."""
        from openr_tpu.monitor.memledger import get_ledger

        snap = get_ledger().snapshot(area=area)
        snap["supervised"] = isinstance(self.solver, SolverSupervisor)
        return snap

    def get_solve_traces(
        self, area: Optional[str] = None, last_n: Optional[int] = None
    ) -> Dict:
        """Flight-recorder surface (ctrl `getSolveTraces` / `breeze
        decision solve-traces`): the per-area SolveTrace rings with
        eviction accounting plus the forensics-dump index
        (docs/Monitoring.md "Flight recorder & profiling"). Recording
        rides the SolverSupervisor; an unsupervised backend reports
        enabled=False with empty surfaces."""
        recorder = getattr(self.solver, "recorder", None)
        if not isinstance(self.solver, SolverSupervisor) or recorder is None:
            return {
                "enabled": False,
                "traces": [],
                "stats": {},
                "forensics": [],
            }
        return {
            "enabled": True,
            "traces": recorder.snapshot(area=area, last_n=last_n),
            "stats": recorder.stats(),
            "forensics": recorder.dump_summaries(),
        }

    def get_adjacency_databases(self) -> Dict[str, AdjacencyDatabase]:
        out: Dict[str, AdjacencyDatabase] = {}
        for link_state in self.area_link_states.values():
            out.update(link_state.get_adjacency_databases())
        return out

    def get_prefix_databases(self) -> Dict[tuple, PrefixDatabase]:
        return self.prefix_state.get_prefix_databases()

    def decrement_ordered_fib_holds(self) -> None:
        """Tick ordered-FIB holds on all areas (Decision.cpp hold timer)."""
        changed = False
        for link_state in self.area_link_states.values():
            if link_state.decrement_holds().topology_changed:
                changed = True
        if changed:
            self._pending.needs_route_update = True
            self._pending.force_full = True  # hold expiry flips visibility
            self._pending.count += 1
            self._schedule_rebuild()

