"""Decision per-event benchmark: grid + fabric, CPU oracle vs TPU solver.

Port of the reference harness semantics
(openr/decision/tests/DecisionBenchmark.cpp:640-823): build a grid or
3-tier Clos fabric where every node announces one unique prefix, then
measure the steady-state cost of one topology event — a link metric flap
arriving as a fresh AdjacencyDatabase — through the full route-build
pipeline (LinkState ingest -> SPF -> per-prefix ECMP selection -> RouteDb).

The reference measures `adj_receive` and `spf` counters per event on its
CPU SpfSolver; here the same event loop runs twice, once on the CPU oracle
(per-source memoized Dijkstra) and once on the TPU batched solver
(incremental array patch + one batched device solve), and reports both.

Env: DECISION_GRID_SIDES, DECISION_FABRIC_PODS, DECISION_EVENTS,
DECISION_KSP2_SIDES, DECISION_KSP2_PREFIXES.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List

from benchmarks.common import emit, note

from openr_tpu.lsdb import LinkState, PrefixState
from openr_tpu.solver import SpfSolver, TpuSpfSolver
from openr_tpu.topology import build_adj_dbs, fabric_edges, grid_edges
from openr_tpu.types import (
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
)


def _unique_prefix(i: int) -> str:
    return f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}/32"


def _prefix_state(nodes: List[str], cap: int = 0, **entry_kw) -> PrefixState:
    ps = PrefixState()
    use = nodes[:cap] if cap else nodes
    for i, node in enumerate(use):
        ps.update_prefix_database(
            PrefixDatabase(
                node,
                [PrefixEntry(IpPrefix(_unique_prefix(i)), **entry_kw)],
                area="0",
            )
        )
    return ps


def _build_ls(edges) -> LinkState:
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    return ls


def _flap_event_bench(
    name: str,
    edges,
    me: str,
    flap_edge,
    events: int,
    prefix_cap: int = 0,
    **entry_kw,
) -> None:
    """Measure per-event route rebuild time, CPU vs TPU, on a topology where
    `flap_edge` (a, b) alternates metric 1 <-> 5 each event."""
    a, b, _ = flap_edge
    variants = []
    for metric in (1, 5):
        ev = [
            (x, y, metric if {x, y} == {a, b} else w) for x, y, w in edges
        ]
        variants.append(build_adj_dbs(ev)[a])

    nodes = sorted({n for x, y, _ in edges for n in (x, y)})
    results: Dict[str, float] = {}
    for label, solver_cls in (("cpu", SpfSolver), ("tpu", TpuSpfSolver)):
        ls = _build_ls(edges)
        ps = _prefix_state(nodes, cap=prefix_cap, **entry_kw)
        solver = solver_cls(me)
        db_warm = solver.build_route_db(me, {"0": ls}, ps)  # cold build
        assert db_warm is not None and db_warm.unicast_entries
        # warm one flap cycle (jit compile for both metric variants)
        for v in variants:
            ls.update_adjacency_database(v)
            solver.build_route_db(me, {"0": ls}, ps)
        t0 = time.time()
        for i in range(events):
            ls.update_adjacency_database(variants[i % 2])
            solver.build_route_db(me, {"0": ls}, ps)
        per_event = (time.time() - t0) / events
        results[label] = per_event
        note(f"{name} {label}: {per_event*1e3:.2f} ms/event")

    emit(
        {
            "metric": f"decision_event_ms[{name}]",
            "value": round(results["tpu"] * 1e3, 3),
            "unit": "ms/event (flap -> RouteDb)",
            "vs_baseline": round(results["cpu"] / results["tpu"], 2),
        }
    )


def main(argv: List[str] = ()) -> None:
    grid_sides = [
        int(x)
        for x in os.environ.get("DECISION_GRID_SIDES", "10,32").split(",")
        if x
    ]
    fabric_pods = [
        int(x)
        for x in os.environ.get("DECISION_FABRIC_PODS", "6").split(",")
        if x
    ]
    ksp2_sides = [
        int(x)
        for x in os.environ.get("DECISION_KSP2_SIDES", "8").split(",")
        if x
    ]
    events = int(os.environ.get("DECISION_EVENTS", "10"))
    ksp2_prefixes = int(os.environ.get("DECISION_KSP2_PREFIXES", "16"))

    for side in grid_sides:
        edges = grid_edges(side)
        mid = side // 2
        flap = (f"g{mid}_{mid}", f"g{mid}_{mid+1}", 1)
        _flap_event_bench(
            f"grid{side*side}", edges, "g0_0", flap, events
        )

    for pods in fabric_pods:
        edges = fabric_edges(pods)
        n = len({x for a, b, _ in edges for x in (a, b)})
        flap = ("fsw0_0", "rsw0_0", 1)
        _flap_event_bench(
            f"fabric{n}", edges, "rsw0_0", flap, events
        )

    for side in ksp2_sides:
        edges = grid_edges(side)
        mid = side // 2
        flap = (f"g{mid}_{mid}", f"g{mid}_{mid+1}", 1)
        # KSP2 variant: capped prefix count (each KSP2 prefix costs a
        # penalized re-solve batch + host path trace)
        _flap_event_bench(
            f"grid{side*side}_ksp2",
            edges,
            "g0_0",
            flap,
            events,
            prefix_cap=ksp2_prefixes,
            forwarding_type=PrefixForwardingType.SR_MPLS,
            forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
        )


if __name__ == "__main__":
    main()
