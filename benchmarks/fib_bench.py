"""Fib programming benchmark.

Equivalent of the reference's `fib_benchmark` binary
(CMakeLists.txt:782-833): measures route-delta programming throughput
through the Fib module against the mock agent — the pure module-path cost
(delta bookkeeping, nexthop dedup, perf logging) that sits between
Decision's RouteDb delta and the platform agent.

Env knobs: FIB_ROUTES (default 10000), FIB_BATCH (default 500).
Emits one JSON line per measurement (benchmarks/common.emit contract).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import List

from benchmarks.common import emit, note


def bench_fib_programming(n_routes: int, batch: int) -> None:
    from openr_tpu.fib import Fib, FibConfig
    from openr_tpu.messaging import RWQueue
    from openr_tpu.platform import MockFibHandler
    from openr_tpu.solver import DecisionRouteUpdate
    from openr_tpu.solver.routes import RibUnicastEntry
    from openr_tpu.types import IpPrefix, NextHop

    async def body():
        handler = MockFibHandler()
        fib = Fib(
            FibConfig(my_node_name="bench"),
            handler,
            RWQueue(),
            RWQueue(),
        )

        def entry(i: int) -> RibUnicastEntry:
            return RibUnicastEntry(
                prefix=IpPrefix(f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}/32"),
                nexthops={
                    NextHop(address="fe80::1", iface="po1", metric=10),
                    NextHop(address="fe80::2", iface="po2", metric=10),
                },
            )

        # need >= 2 batches: one warm, rest timed
        b = batch if n_routes > batch else max(1, n_routes // 4)
        deltas: List[DecisionRouteUpdate] = []
        for start in range(0, n_routes, b):
            deltas.append(
                DecisionRouteUpdate(
                    unicast_routes_to_update=[
                        entry(i)
                        for i in range(start, min(start + b, n_routes))
                    ]
                )
            )
        # warm one batch, then complete the initial full sync so the timed
        # deltas take the incremental agent-programming path instead of the
        # pre-sync early return (fib/fib.py:374-378)
        await fib.process_route_updates(deltas[0])
        synced = await fib.sync_route_db()
        assert synced
        fib.has_synced_fib = True  # _run_sync sets this in the daemon path
        fib._sync_scheduled = False
        if fib._sync_handle is not None:  # cancel the warm-up's pending sync
            fib._sync_handle.cancel()
            fib._sync_handle = None
        calls_before = handler.counters.get("add_unicast_routes", 0)
        t0 = time.time()
        for delta in deltas[1:]:
            await fib.process_route_updates(delta)
        elapsed = time.time() - t0
        # the agent must actually have been programmed per delta
        programmed = handler.counters.get("add_unicast_routes", 0) - calls_before
        assert programmed == len(deltas) - 1, (programmed, len(deltas) - 1)
        return (n_routes - len(deltas[0].unicast_routes_to_update)) / elapsed, b

    rate, batch = asyncio.run(body())
    note(f"fib: programmed at {rate:,.0f} routes/s (batch {batch})")
    emit(
        {
            "metric": "fib_program_routes_per_sec",
            "value": round(rate, 1),
            "unit": f"routes/s (batches of {batch}, programmed through the mock agent)",
            "vs_baseline": 0.0,  # no reference binary run to compare against
        }
    )


def main(argv: List[str] = ()) -> None:
    n_routes = int(os.environ.get("FIB_ROUTES", "10000"))
    batch = int(os.environ.get("FIB_BATCH", "500"))
    bench_fib_programming(n_routes, batch)


if __name__ == "__main__":
    main()
