"""Shared benchmark helpers: raw edge-list compilation and device timing.

The timing methodology matches bench.py: R independent solves are chained
inside one jitted lax.scan (a data dependency folds each result into a
carry so no solve can be elided), and throughput is the marginal time
between a short and a long chain — this cancels the fixed dispatch/sync
latency of the device link, which is irrelevant to steady-state event
processing where results stay device-resident.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict, List, Sequence, Tuple

import numpy as np

from openr_tpu.ops.graph import INF, _next_bucket

Edge = Tuple[str, str, int]


def compile_edges(
    edges: Sequence[Edge],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, Dict[str, int]]:
    """Edge list -> padded (src, dst, w, overloaded, node_index) arrays.

    numpy-vectorized equivalent of ops.graph.compile_graph for synthetic
    benchmark topologies where building a full LinkState (python object
    graph) would dominate setup time at 100k+ nodes.
    """
    names = sorted({n for a, b, _ in edges for n in (a, b)})
    node_index = {name: i for i, name in enumerate(names)}
    n = len(names)
    e = 2 * len(edges)

    a = np.fromiter((node_index[x] for x, _, _ in edges), np.int32)
    b = np.fromiter((node_index[y] for _, y, _ in edges), np.int32)
    m = np.fromiter((w for _, _, w in edges), np.int32)

    srcs = np.concatenate([a, b])
    dsts = np.concatenate([b, a])
    ws = np.concatenate([m, m])

    n_pad = _next_bucket(max(n, 1))
    e_pad = _next_bucket(max(e, 1))
    src = np.zeros(e_pad, dtype=np.int32)
    dst = np.zeros(e_pad, dtype=np.int32)
    w = np.full(e_pad, INF, dtype=np.int32)
    order = np.argsort(dsts, kind="stable")
    src[:e] = srcs[order]
    dst[:e] = dsts[order]
    w[:e] = ws[order]
    dst[e:] = dst[e - 1]
    overloaded = np.zeros(n_pad, dtype=bool)
    return src, dst, w, overloaded, node_index


def time_marginal(run, reps_small: int, reps_big: int, rounds: int = 3) -> float:
    """Best marginal seconds/rep between a short and a long chained run.

    `run(reps)` must block until the device is done.
    """
    run(reps_small)  # compile/warm
    run(reps_big)
    best = float("inf")
    t_big = None
    for _ in range(rounds):
        t0 = time.time()
        run(reps_small)
        t_small = time.time() - t0
        t0 = time.time()
        run(reps_big)
        t_big = time.time() - t0
        marginal = (t_big - t_small) / (reps_big - reps_small)
        if marginal > 0:  # noise guard
            best = min(best, marginal)
    if not np.isfinite(best):
        best = t_big / reps_big
    return best


def emit(result: dict) -> None:
    """One JSON result line to stdout."""
    print(json.dumps(result), flush=True)


def note(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)
