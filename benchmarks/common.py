"""Shared benchmark helpers: raw edge-list compilation and device timing.

The timing methodology matches bench.py: R independent solves are chained
inside one jitted lax.scan (a data dependency folds each result into a
carry so no solve can be elided), and throughput is the marginal time
between a short and a long chain — this cancels the fixed dispatch/sync
latency of the device link, which is irrelevant to steady-state event
processing where results stay device-resident.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict, List, Sequence, Tuple

import numpy as np

from openr_tpu.ops.graph import INF  # noqa: F401  (re-exported for benches)
from openr_tpu.ops.graph import compile_edges as graph_compile_edges

Edge = Tuple[str, str, int]


def compile_edges(
    edges: Sequence[Edge],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, Dict[str, int]]:
    """Edge list -> padded (src, dst, w, overloaded, node_index) arrays.

    Thin wrapper over ops.graph.compile_edges (the numpy-vectorized fast
    path) for the edge-list-form benchmark consumers; node ids follow its
    in-degree renumbering, which consumers must reach through node_index.
    """
    graph = graph_compile_edges(edges)
    return graph.src, graph.dst, graph.w, graph.overloaded, graph.node_index


def time_marginal(run, reps_small: int, reps_big: int, rounds: int = 3) -> float:
    """Median marginal seconds/rep between a short and a long chained run.

    `run(reps)` must block until the device is done. The median of the
    positive per-round marginals is reported — taking the minimum would
    systematically favor rounds where link-sync jitter happened to inflate
    the short chain and deflate the long one.

    When EVERY round's marginal is non-positive (sync jitter swamped the
    chain-length delta), falls back to the best (minimum) whole-chain time
    observed across all rounds divided by reps_big — the least
    jitter-inflated sample available — and notes the degraded methodology
    on stderr (the fixed dispatch latency is then NOT cancelled, so the
    number overstates per-event cost).
    """
    run(reps_small)  # compile/warm
    run(reps_big)
    marginals = []
    best_t_big = None
    for _ in range(rounds):
        t0 = time.time()
        run(reps_small)
        t_small = time.time() - t0
        t0 = time.time()
        run(reps_big)
        t_big = time.time() - t0
        if best_t_big is None or t_big < best_t_big:
            best_t_big = t_big
        marginal = (t_big - t_small) / (reps_big - reps_small)
        if marginal > 0:  # noise guard: jitter can invert tiny pairs
            marginals.append(marginal)
    if not marginals:
        note(
            f"time_marginal: all {rounds} round marginals non-positive; "
            f"degraded fallback = best whole-chain {best_t_big:.4f}s / "
            f"{reps_big} reps (dispatch latency not cancelled)"
        )
        return best_t_big / reps_big
    return float(np.median(marginals))


def emit(result: dict) -> None:
    """One JSON result line to stdout."""
    print(json.dumps(result), flush=True)


def note(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)
