"""PersistentStore benchmark — the reference's `config_store_benchmark`
(CMakeLists.txt:782-833): store/load/flush throughput of the write-behind
disk kv used for drain state, link-metric overrides, and allocated
prefixes.

Env knobs: CS_KEYS (default 1000), CS_VALUE_BYTES (default 1024).
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import List

from benchmarks.common import emit, note


def bench_config_store(n_keys: int, value_bytes: int) -> None:
    """Writes run inside an asyncio loop — the daemon's mode, where flushes
    are write-behind debounced (PersistentStore docstring); without a loop
    every store() snapshots immediately (the tool mode), which measures
    fsync throughput rather than the store."""
    import asyncio

    from openr_tpu.configstore import PersistentStore

    payload = bytes(value_bytes)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "store.bin")

        async def write_phase() -> float:
            store = PersistentStore(path)
            store.store("warm", payload)
            t0 = time.time()
            for i in range(n_keys):
                store.store(f"key-{i:06d}", payload)
            store.flush()  # one explicit snapshot closes the batch
            rate = n_keys / (time.time() - t0)
            store.stop()
            return rate

        write_rate = asyncio.run(write_phase())

        # cold load path: fresh store reads the snapshot back
        t0 = time.time()
        store2 = PersistentStore(path)
        loaded = sum(
            1
            for i in range(n_keys)
            if store2.load(f"key-{i:06d}") == payload
        )
        load_rate = n_keys / (time.time() - t0)
        assert loaded == n_keys, loaded
        store2.stop()

    note(
        f"config-store: {write_rate:,.0f} writes/s (flushed), "
        f"{load_rate:,.0f} loads/s after reopen"
    )
    emit(
        {
            "metric": "config_store_writes_per_sec",
            "value": round(write_rate, 1),
            "unit": f"writes/s ({value_bytes}B values, snapshot flushed)",
            "vs_baseline": 0.0,  # no reference binary run to compare against
        }
    )
    emit(
        {
            "metric": "config_store_loads_per_sec",
            "value": round(load_rate, 1),
            "unit": f"loads/s ({value_bytes}B values, after reopen)",
            "vs_baseline": 0.0,  # no reference binary run to compare against
        }
    )


def main(argv: List[str] = ()) -> None:
    bench_config_store(
        int(os.environ.get("CS_KEYS", "1000")),
        int(os.environ.get("CS_VALUE_BYTES", "1024")),
    )


if __name__ == "__main__":
    main()
