"""Scale benchmarks: BASELINE.md measurement configs 2-5.

  clos_flap   (config 2) — 3-tier Clos fabric, incremental SPF on a single
              link-flap event: LinkState ingest -> changelog array patch ->
              one batched device re-solve (vs CPU oracle event: ingest ->
              memo invalidation -> Dijkstra re-runs).
  wan_multi   (config 3) — synthetic WAN graph, batched multi-source SPF
              throughput on device (vs host Dijkstra samples).
  wan_ksp     (config 4) — ECMP first-hop mask + KSP penalized re-solves
              fused on device: base row + K masked-weight rows in one call,
              first-hop triangle mask computed on device.
  multi_metric(config 5) — M metric variants (e.g. SR-TE vs IGP weight
              sets) x sources solved as one sharded batch over the mesh.

Defaults are sized for the BASELINE configs (10k Clos, 100k WAN, 50k KSP);
env vars scale them down for smoke runs: SCALE_CLOS_PODS, SCALE_WAN_N,
SCALE_KSP_N, SCALE_SOURCES, SCALE_METRICS.
"""

from __future__ import annotations

import heapq
import os
import time
from functools import partial
from typing import List

import numpy as np

from benchmarks.common import compile_edges, emit, note, time_marginal

from openr_tpu.ops.graph import INF


# ---------------------------------------------------------------------------
# config 2: Clos fabric, incremental single-link-flap event
# ---------------------------------------------------------------------------


def bench_clos_flap(pods: int, events: int = 8) -> None:
    from openr_tpu.lsdb import LinkState
    from openr_tpu.solver import TpuSpfSolver
    from openr_tpu.topology import build_adj_dbs, fabric_edges

    edges = fabric_edges(pods)
    t0 = time.time()
    dbs = build_adj_dbs(edges)
    t1 = time.time()
    ls = LinkState("0")
    # production cold-start path: one bulk ingest (full-sync publication)
    ls.bulk_update_adjacency_databases(list(dbs.values()))
    n = len(dbs)
    note(
        f"clos: {n} nodes, {len(edges)} links, built in {time.time()-t0:.1f}s"
        f" (fixtures {t1-t0:.1f}s, cold-start LSDB ingest {time.time()-t1:.1f}s)"
    )

    me = "rsw0_0"
    solver = TpuSpfSolver(me)
    solve = solver._area_solve(ls, me)
    assert solve is not None

    # flap fsw0_1<->rsw0_1 metric between 1 and 5 via adj-db updates
    variants = []
    for metric in (5, 1):
        ev = [
            (a, b, metric if {a, b} == {"fsw0_1", "rsw0_1"} else w)
            for a, b, w in edges
        ]
        variants.append(build_adj_dbs(ev)["fsw0_1"])
    # warm both variants (jit both paths)
    for v in variants:
        ls.update_adjacency_database(v)
        solver._area_solve(ls, me)

    t0 = time.time()
    for i in range(events):
        ls.update_adjacency_database(variants[i % 2])
        solver._area_solve(ls, me)  # incremental refresh + device solve
    wall_event = (time.time() - t0) / events

    # Steady-state marginal event cost: chain flap events device-side (the
    # two weight variants stacked per bucket, indexed by step parity) so the
    # fixed host-device link sync latency — ~70ms+ through the axon tunnel,
    # sub-ms co-located — cancels out, mirroring the bench.py methodology.
    import jax
    import jax.numpy as jnp
    from functools import partial as _partial

    from openr_tpu.ops.graph import refresh_graph
    from openr_tpu.ops.spf import _sell_solver_raw

    area = solver._solves[(ls.area, me)][1]
    g = area.graph
    sell = g.sell
    assert sell is not None
    wg_variants = []
    for v in variants:
        ls.update_adjacency_database(v)
        g = area.graph = refresh_graph(area.graph, ls)
        wg_variants.append(g.sell.wg)
    wg_stacks = tuple(
        jnp.asarray(np.stack([wgs[i] for wgs in wg_variants]))
        for i in range(len(sell.wg))
    )
    nbrs = tuple(jnp.asarray(a) for a in sell.nbr)
    ov = jnp.asarray(g.overloaded)
    from openr_tpu.ops.graph import _next_bucket

    rows_np = np.array([g.node_index[s] for s in area.sources], np.int32)
    s_pad = _next_bucket(len(rows_np), minimum=8)  # match _AreaSolve._solve
    rows = jnp.asarray(
        np.concatenate(
            [rows_np, np.full(s_pad - len(rows_np), rows_np[0], np.int32)]
        )
    )
    solve = _sell_solver_raw(sell.shape_key())

    @_partial(jax.jit, static_argnames=("reps",))
    def chained(reps):
        def body(carry, i):
            wgs_i = tuple(a[i % 2] for a in wg_stacks)
            d = solve(rows, nbrs, wgs_i, ov)
            return carry ^ d[0, -1], None

        acc, _ = jax.lax.scan(
            body, jnp.int32(0), jnp.arange(reps, dtype=jnp.int32)
        )
        return acc

    # long chain: the delta must dwarf the tunnel's ~100ms sync jitter
    device_marginal = time_marginal(
        lambda r: int(chained(r)), 2, 2 + 16 * events
    )

    # Host-side share of an event: adj-db ingest + changelog array patch +
    # the delta upload dispatch (async — no device sync in this loop). The
    # honest steady-state event cost is host + device marginal.
    def _host_events(count, t_start):
        nonlocal g, w_host
        for i in range(count):
            ls.update_adjacency_database(variants[(i + t_start) % 2])
            g = area.graph = refresh_graph(area.graph, ls)
            # mirror the solver's provenance fast path: diff only the
            # changelog-touched positions when available
            if g.changed_edges is not None:
                cand = g.changed_edges
                changed = cand[w_host[cand] != g.w[cand]]
            else:
                changed = np.nonzero(w_host[: g.e] != g.w[: g.e])[0]
            if len(changed):
                stacks = list(wg_stacks)
                for k in np.unique(sell.edge_bucket[changed]):
                    sel = changed[sell.edge_bucket[changed] == k]
                    stacks[k] = (
                        stacks[k]
                        .at[0, sell.edge_row[sel], sell.edge_slot[sel]]
                        .set(jnp.asarray(g.w[sel]))
                    )
                w_host[changed] = g.w[changed]

    w_host = g.w.copy()
    _host_events(2, 0)  # warm the scatter executables outside the timing
    t0 = time.time()
    _host_events(events, 0)
    host_event = (time.time() - t0) / events
    per_event = host_event + device_marginal

    # CPU oracle event: same ingest + fresh Dijkstra from me
    t0 = time.time()
    for i in range(events):
        ls.update_adjacency_database(variants[i % 2])
        ls.get_spf_result(me)
    cpu_event = (time.time() - t0) / events

    note(
        f"clos{n} flap event: tpu {per_event*1e3:.2f}ms steady-state "
        f"(host {host_event*1e3:.2f} + device {device_marginal*1e3:.2f}; "
        f"wall {wall_event*1e3:.2f}ms incl. link sync) "
        f"cpu {cpu_event*1e3:.2f}ms"
    )
    emit(
        {
            "metric": f"clos{n}_flap_event_ms",
            "value": round(per_event * 1e3, 3),
            "unit": "ms/event (ingest + delta patch + device re-solve, "
            "steady state)",
            "vs_baseline": round(cpu_event / per_event, 2),
        }
    )


# ---------------------------------------------------------------------------
# config 3: WAN batched multi-source throughput
# ---------------------------------------------------------------------------


def _host_dijkstra(src_i, dst_i, w_i, n, source) -> np.ndarray:
    """Reference-architecture baseline: binary-heap Dijkstra on the host."""
    adj: List[List] = [[] for _ in range(n)]
    for s, d, w in zip(src_i, dst_i, w_i):
        if w < INF:
            adj[s].append((d, w))
    dist = np.full(n, INF, dtype=np.int64)
    dist[source] = 0
    heap = [(0, source)]
    while heap:
        dm, u = heapq.heappop(heap)
        if dm != dist[u]:
            continue
        for v, w in adj[u]:
            nd = dm + w
            if nd < dist[v]:
                dist[v] = nd
                heapq.heappush(heap, (nd, v))
    return dist


def bench_wan_multi(n: int, n_sources: int, cpu_samples: int = 4) -> None:
    import jax
    import jax.numpy as jnp

    from openr_tpu.ops.graph import compile_edges as graph_compile_edges
    from openr_tpu.ops.spf import _sell_solver_raw, sell_fixpoint
    from openr_tpu.topology import wan_edges

    t0 = time.time()
    graph = graph_compile_edges(wan_edges(n, degree=4, seed=3))
    note(
        f"wan: n={graph.n} e={graph.e} built in {time.time()-t0:.1f}s "
        f"(padded {graph.n_pad}/{graph.e_pad})"
    )
    sell = graph.sell
    assert sell is not None

    rng = np.random.default_rng(7)
    sources = jnp.asarray(
        rng.choice(n, size=n_sources, replace=False).astype(np.int32)
    )
    solve = _sell_solver_raw(sell.shape_key())
    nbrs = tuple(jnp.asarray(a) for a in sell.nbr)
    wgs = tuple(jnp.asarray(a) for a in sell.wg)
    ov_d = jnp.asarray(graph.overloaded)

    @partial(jax.jit, static_argnames=("reps",))
    def chained(reps):
        def body(carry, k):
            # perturbed weights = distinct LSDB events (INF slots stay INF)
            wgs_k = tuple(
                jnp.where(a < INF, (a + k) % 100 + 1, a) for a in wgs
            )
            d = solve(sources, nbrs, wgs_k, ov_d)
            return carry ^ d[0, -1], None

        acc, _ = jax.lax.scan(
            body, jnp.int32(0), jnp.zeros(reps, dtype=jnp.int32)
        )
        return acc

    marginal = time_marginal(lambda r: int(chained(r)), 1, 4)
    rate = n_sources / marginal
    note(
        f"wan{n}: {n_sources}-source batch in {marginal*1e3:.1f}ms "
        f"-> {rate:,.0f} SPF/s"
    )

    # correctness spot-check + native C++ baseline (falls back to the host
    # python Dijkstra when the toolchain is missing); solve only the sampled
    # sources — the full [S, n_pad] matrix is ~0.5GB host-side at 100k nodes
    sample = np.asarray(sources)[: max(cpu_samples, 3)]
    d = np.asarray(sell_fixpoint(sell, sample, sell.wg, graph.overloaded))
    from openr_tpu.solver.native_spf import native_spf_available

    if native_spf_available():
        from openr_tpu.solver.native_spf import NativeSpfSolver

        solver = NativeSpfSolver(graph)
        for i in range(min(cpu_samples, 3)):
            ref = solver.run(int(sources[i]))
            np.testing.assert_array_equal(d[i, : graph.n], ref)
        native_sources = np.linspace(
            0, graph.n - 1, max(cpu_samples, 8), dtype=np.int32
        )
        solver.run_many(native_sources[:2])
        t0 = time.time()
        solver.run_many(native_sources)
        cpu_rate = len(native_sources) / (time.time() - t0)
        solver.close()
        note(f"wan{n}: native C++ Dijkstra {cpu_rate:.1f} SPF/s")
    else:
        t0 = time.time()
        for i in range(cpu_samples):
            ref = _host_dijkstra(
                graph.src, graph.dst, graph.w, graph.n_pad, int(sources[i])
            )
            np.testing.assert_array_equal(
                np.minimum(d[i, : graph.n], INF),
                np.minimum(ref[: graph.n], INF),
            )
        cpu_rate = cpu_samples / (time.time() - t0)
        note(f"wan{n}: host python Dijkstra {cpu_rate:.1f} SPF/s")
    emit(
        {
            "metric": f"wan{n}_spf_per_sec",
            "value": round(rate, 1),
            "unit": f"SPF/s ({n_sources}-source batches)",
            "vs_baseline": round(rate / cpu_rate, 1),
        }
    )


# ---------------------------------------------------------------------------
# config 4: ECMP first-hop mask + KSP penalized re-solves fused on device
# ---------------------------------------------------------------------------


def bench_wan_ksp(n: int, k_dests: int) -> None:
    import jax
    import jax.numpy as jnp

    from openr_tpu.ops.graph import compile_edges as graph_compile_edges
    from openr_tpu.ops.spf import _sell_solver_vw
    from openr_tpu.topology import wan_edges

    graph = graph_compile_edges(wan_edges(n, degree=4, seed=5))
    sell = graph.sell
    assert sell is not None
    src, dst, w = graph.src, graph.dst, graph.w
    e_pad = graph.e_pad
    note(f"ksp wan: n={n} e_pad={e_pad}")

    me = graph.node_index["w0"]
    rng = np.random.default_rng(11)
    # my up-edges; their far ends are the neighbor rows for the first-hop mask
    mine = np.nonzero((src == me) & (w < INF))[0]
    neighbors = dst[mine]
    deg = len(neighbors)

    # batch = [me] + neighbors (base weights) + K penalized me rows, each
    # masking a few edges (the links of a previously traced path set) to
    # INF via the device-side per-bucket masks
    s = 1 + deg + k_dests
    sources = np.concatenate(
        [
            np.array([me], dtype=np.int32),
            neighbors.astype(np.int32),
            np.full(k_dests, me, dtype=np.int32),
        ]
    )
    per_bucket = [[] for _ in range(len(sell.nbr))]
    for row in range(1 + deg, s):
        for p in rng.choice(graph.e, size=8, replace=False):
            per_bucket[sell.edge_bucket[p]].append(
                (sell.edge_row[p], sell.edge_slot[p], row)
            )
    masks = tuple(
        jnp.asarray(
            np.asarray(entries, dtype=np.int32)
            if entries
            else np.full((1, 3), 1 << 30, dtype=np.int32)
        )
        for entries in per_bucket
    )

    my_w = jnp.asarray(w[mine])
    sources_d = jnp.asarray(sources)
    nbrs = tuple(jnp.asarray(a) for a in sell.nbr)
    wgs = tuple(jnp.asarray(a) for a in sell.wg)
    ov_d = jnp.asarray(graph.overloaded)
    solve_vw = _sell_solver_vw(sell.shape_key(), None)

    @partial(jax.jit, static_argnames=("reps",))
    def chained(reps):
        def body(carry, k):
            wgs_k = tuple(
                jnp.where(a < INF, (a + k) % 100 + 1, a) for a in wgs
            )
            d = solve_vw(sources_d, nbrs, wgs_k, masks, ov_d)
            # ECMP first-hop mask fused: edge (me -> v) is a first hop for
            # dest t iff w(me,v) + D[v, t] == D[me, t]
            fh = (my_w[:, None] + d[1 : 1 + deg, :] == d[0][None, :]).sum()
            return carry ^ d[0, -1] ^ fh.astype(jnp.int32), None

        acc, _ = jax.lax.scan(
            body, jnp.int32(0), jnp.zeros(reps, dtype=jnp.int32)
        )
        return acc

    marginal = time_marginal(lambda r: int(chained(r)), 1, 4)

    # measured baseline: the same s solves executed one row at a time with
    # each row's own penalty mask (the reference's sequential
    # per-destination re-run structure). Masks are stacked per batch row
    # and sliced by the loop index so no iteration is loop-invariant (XLA
    # must not be able to hoist the solve).
    one_src = sources_d[:1]
    per_row_bucket = [
        np.full((s, 8, 3), 1 << 30, dtype=np.int32) for _ in sell.nbr
    ]
    for k, entries in enumerate(per_bucket):
        counts = {}
        for r, sl, row in entries:
            j = counts.get(row, 0)
            per_row_bucket[k][row, j] = (r, sl, 0)  # col 0: single-row solve
            counts[row] = j + 1
    masks_rows = tuple(jnp.asarray(a) for a in per_row_bucket)

    @partial(jax.jit, static_argnames=("reps",))
    def chained_seq(reps):
        def body(carry, k):
            wgs_k = tuple(
                jnp.where(a < INF, (a + k) % 100 + 1, a) for a in wgs
            )

            def one(i, acc):
                masks_i = tuple(
                    jax.lax.dynamic_index_in_dim(m, i, axis=0, keepdims=False)
                    for m in masks_rows
                )
                d = solve_vw(one_src, nbrs, wgs_k, masks_i, ov_d)
                return acc ^ d[0, -1]

            acc = jax.lax.fori_loop(0, s, one, carry)
            return acc, None

        acc, _ = jax.lax.scan(
            body, jnp.int32(0), jnp.zeros(reps, dtype=jnp.int32)
        )
        return acc

    seq_marginal = time_marginal(lambda r: int(chained_seq(r)), 1, 2)
    note(
        f"ksp wan{n}: base + {k_dests} penalized solves + first-hop mask "
        f"fused {marginal*1e3:.1f}ms vs sequential {seq_marginal*1e3:.1f}ms"
    )
    emit(
        {
            "metric": f"wan{n}_ksp_fused_ms",
            "value": round(marginal * 1e3, 2),
            "unit": f"ms/event ({k_dests} penalized re-solves fused)",
            "vs_baseline": round(seq_marginal / marginal, 2),
        }
    )


# ---------------------------------------------------------------------------
# config 5: multi-metric/multi-topology solve sharded over the mesh
# ---------------------------------------------------------------------------


def bench_multi_metric(n: int, n_metrics: int, n_sources: int) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from openr_tpu.ops.spf import _bf_fixpoint_vw
    from openr_tpu.parallel import make_mesh
    from openr_tpu.topology import wan_edges

    edges = wan_edges(n, degree=4, seed=9)
    src, dst, w, overloaded, node_index = compile_edges(edges)

    devices = jax.devices()
    mesh = make_mesh(devices, shape=(len(devices), 1))
    note(f"multi-metric: mesh {dict(mesh.shape)} on {devices[0].platform}")

    rng = np.random.default_rng(13)
    s = n_metrics * n_sources
    # round the batch up to the mesh axis
    batch = mesh.shape["batch"]
    s_pad = ((s + batch - 1) // batch) * batch
    sources = np.tile(
        rng.choice(n, size=n_sources, replace=False).astype(np.int32),
        n_metrics,
    )
    sources = np.concatenate(
        [sources, np.zeros(s_pad - s, dtype=np.int32)]
    )
    # metric variants: scaled/perturbed copies of the base weights (distinct
    # routing topologies, e.g. IGP vs latency-optimized SR-TE planes)
    w_rows = np.empty((s_pad, len(w)), dtype=np.int32)
    finite = w < INF
    for mi in range(n_metrics):
        variant = w.copy()
        variant[finite] = w[finite] * (mi + 1) + mi
        w_rows[mi * n_sources : (mi + 1) * n_sources] = variant
    w_rows[s:] = w

    row_sharded = NamedSharding(mesh, P("batch"))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(
        _bf_fixpoint_vw,
        # (sources, src_e, dst_e, w_rows, overloaded)
        in_shardings=(row_sharded, repl, repl, row_sharded, repl),
        out_shardings=NamedSharding(mesh, P("batch", None)),
    )
    args = (
        jax.device_put(jnp.asarray(sources), row_sharded),
        jax.device_put(jnp.asarray(src), repl),
        jax.device_put(jnp.asarray(dst), repl),
        jax.device_put(jnp.asarray(w_rows), row_sharded),
        jax.device_put(jnp.asarray(overloaded), repl),
    )

    sources_d, src_d, dst_d, w_rows_d, ov_d = args

    @partial(jax.jit, static_argnames=("reps",))
    def chained_fused(reps):
        def body(carry, k):
            # rep-dependent weights: no iteration is loop-invariant
            wk = jnp.where(
                w_rows_d < INF, (w_rows_d + k) % 100 + 1, w_rows_d
            )
            d = _bf_fixpoint_vw(sources_d, src_d, dst_d, wk, ov_d)
            return carry ^ d[0, -1], None

        acc, _ = jax.lax.scan(
            body, jnp.int32(0), jnp.arange(reps, dtype=jnp.int32)
        )
        return acc

    fn(*args).block_until_ready()  # keep the sharded executable validated
    # long chains: per-event time is ms-scale, so the delta must dwarf the
    # tunneled link's sync jitter
    marginal = time_marginal(lambda r: int(chained_fused(r)), 2, 50)
    rate = s / marginal

    # measured baseline: the reference structure — one metric plane (one
    # routing topology) solved at a time — chained device-side on a single
    # device so the comparison isolates plane-fusion, not link syncs. On a
    # one-chip mesh vs_baseline therefore reads as the fusion win; on a
    # real multi-chip mesh it additionally carries the sharding win.
    plane_w = jnp.asarray(
        np.stack(
            [w_rows[mi * n_sources][None, :] for mi in range(n_metrics)]
        )
    )  # [M, 1, E] — per-plane shared weights
    plane_sources = jax.device_put(
        jnp.asarray(sources[:n_sources]), devices[0]
    )
    src1, dst1, ov1 = (
        jax.device_put(jnp.asarray(a), devices[0])
        for a in (src, dst, overloaded)
    )

    @partial(jax.jit, static_argnames=("reps",))
    def chained_planes(reps):
        def rep_body(carry, k):
            def plane(mi, acc):
                wm = jax.lax.dynamic_index_in_dim(
                    plane_w, mi, axis=0, keepdims=False
                )
                wk = jnp.where(wm < INF, (wm + k) % 100 + 1, wm)
                d = _bf_fixpoint_vw(plane_sources, src1, dst1, wk, ov1)
                return acc ^ d[0, -1]

            return jax.lax.fori_loop(0, n_metrics, plane, carry), None

        acc, _ = jax.lax.scan(
            rep_body, jnp.int32(0), jnp.arange(reps, dtype=jnp.int32)
        )
        return acc

    seq_marginal = time_marginal(
        lambda r: int(chained_planes(r)), 2, 50
    )
    note(
        f"multi-metric wan{n}: {n_metrics} metrics x {n_sources} sources "
        f"fused {marginal*1e3:.1f}ms vs plane-at-a-time "
        f"{seq_marginal*1e3:.1f}ms -> {rate:,.0f} solves/s"
    )
    emit(
        {
            "metric": f"wan{n}_multimetric_solves_per_sec",
            "value": round(rate, 1),
            "unit": f"SPF/s ({n_metrics} metric planes fused+sharded)",
            "vs_baseline": round(seq_marginal / marginal, 2),
        }
    )


def main(argv: List[str] = ()) -> None:
    clos_pods = int(os.environ.get("SCALE_CLOS_PODS", "170"))
    wan_n = int(os.environ.get("SCALE_WAN_N", "100000"))
    ksp_n = int(os.environ.get("SCALE_KSP_N", "50000"))
    n_sources = int(os.environ.get("SCALE_SOURCES", "128"))
    n_metrics = int(os.environ.get("SCALE_METRICS", "4"))

    bench_clos_flap(clos_pods)
    bench_wan_multi(wan_n, n_sources)
    bench_wan_ksp(ksp_n, k_dests=15)
    bench_multi_metric(min(wan_n, 8192), n_metrics, max(8, n_sources // 4))


if __name__ == "__main__":
    main()
