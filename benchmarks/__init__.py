"""Benchmark suite mirroring the reference's harnesses on TPU.

Ports of the reference benchmark binaries (CMakeLists.txt:782-865):
  decision_bench  — DecisionBenchmark.cpp grid/fabric per-event harness
  kvstore_bench   — KvStoreBenchmark.cpp mergeKeyValues/dumpAll harness
  scale_bench     — BASELINE.md configs 2-5 (10k Clos incremental flap,
                    100k WAN batched multi-source, 50k ECMP+KSP fused,
                    multi-metric sharded over the device mesh)

Each module is a script printing one JSON line per measured config to
stdout (details to stderr), and exposes main(argv) for tests.
"""
