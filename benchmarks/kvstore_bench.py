"""KvStore benchmark: CRDT merge + full dump throughput.

Port of the reference harness (openr/kvstore/tests/KvStoreBenchmark.cpp:
289-300): mergeKeyValues over {store size} x {update size} grids, and
dumpAll over store sizes. Values carry ~100-byte payloads like the
reference's generated entries.

Env: KVSTORE_MERGE_SIZES ("store:update,..."), KVSTORE_DUMP_SIZES.
"""

from __future__ import annotations

import os
import time
from typing import List

from benchmarks.common import emit, note

from openr_tpu.kvstore.store import merge_key_values
from openr_tpu.types import Value


def _make_store(n: int, originator: str = "node") -> dict:
    return {
        f"prefix:node{i}": Value(
            version=1,
            originator_id=f"{originator}{i}",
            value=(b"v" * 100) + str(i).encode(),
        )
        for i in range(n)
    }


def bench_merge(store_size: int, update_size: int, rounds: int = 5) -> None:
    base = _make_store(store_size)

    def fresh_store(native: bool):
        if not native:
            return dict(base)
        from openr_tpu.kvstore.native import NativeKvTable

        table = NativeKvTable()
        for key, value in base.items():
            table[key] = value
        return table

    backends = ["python"]
    try:
        from openr_tpu.kvstore.native import native_kv_available

        if native_kv_available():
            backends.append("native")
    except Exception:
        pass

    rates = {}
    for backend in backends:
        best = float("inf")
        for r in range(rounds):
            store = fresh_store(backend == "native")
            update = {
                f"prefix:node{i}": Value(
                    version=2 + r,
                    originator_id=f"node{i}",
                    value=(b"u" * 100) + str(i).encode(),
                )
                for i in range(update_size)
            }
            t0 = time.time()
            accepted = merge_key_values(store, update)
            dt = time.time() - t0
            assert len(accepted) == update_size
            best = min(best, dt)
        rates[backend] = update_size / best
        note(
            f"merge[{backend}] store={store_size} update={update_size}: "
            f"{best*1e3:.2f}ms ({rates[backend]:,.0f} keys/s)"
        )
    # metric pinned to the python engine so the series stays comparable
    # across hosts; vs_baseline carries the native/python ratio when the
    # toolchain is present
    emit(
        {
            "metric": f"kvstore_merge_keys_per_sec[{store_size}x{update_size}]",
            "value": round(rates["python"], 1),
            "unit": "keys/s",
            "vs_baseline": round(
                rates.get("native", rates["python"]) / rates["python"], 2
            ),
        }
    )


def bench_dump(store_size: int, rounds: int = 5) -> None:
    from openr_tpu.kvstore import InProcessTransport, KvStore

    kv = KvStore("bench", ["0"], InProcessTransport())
    kv.db("0").store.update(_make_store(store_size))
    best = float("inf")
    for _ in range(rounds):
        t0 = time.time()
        pub = kv.dump_all(area="0")
        dt = time.time() - t0
        assert len(pub.key_vals) == store_size
        best = min(best, dt)
    rate = store_size / best
    note(f"dumpAll n={store_size}: {best*1e3:.2f}ms ({rate:,.0f} keys/s)")
    emit(
        {
            "metric": f"kvstore_dump_keys_per_sec[{store_size}]",
            "value": round(rate, 1),
            "unit": "keys/s",
            "vs_baseline": 0.0,
        }
    )


def main(argv: List[str] = ()) -> None:
    merge_sizes = [
        tuple(int(v) for v in pair.split(":"))
        for pair in os.environ.get(
            "KVSTORE_MERGE_SIZES", "100:10,1000:100,10000:1000"
        ).split(",")
        if pair
    ]
    dump_sizes = [
        int(x)
        for x in os.environ.get("KVSTORE_DUMP_SIZES", "100,1000").split(",")
        if x
    ]
    for store_size, update_size in merge_sizes:
        bench_merge(store_size, update_size)
    for n in dump_sizes:
        bench_dump(n)


if __name__ == "__main__":
    main()
