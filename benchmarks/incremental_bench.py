"""Incremental SPF benchmark: warm-start vs cold solve on link-flap events.

BASELINE.md config 2 — "10k-node 3-tier Clos/fat-tree, incremental SPF on
single link-flap event" — is the convergence-latency half of the north-star
metric. This bench chains single-link-flap events (a far-pod rsw<->fsw link
going down, then back up, via fresh AdjacencyDatabases) through two
_AreaSolve instances over the same LinkState:

  - warm: the default device-resident path — the previous distance matrix
    warm-starts the fixpoint (increase events run the on-device
    invalidation pass first), so relaxation rounds scale with the event's
    affected radius instead of the graph diameter.
  - cold: warm_start=False — the same fused patch+solve dispatch, but
    re-relaxing from D0 = INF every event (the pre-warm-start behavior).

Reported: warm events/sec, p99 per-event latency, and the mean relaxation
round counts of both paths. The round-count win is asserted, so the bench
doubles as a regression gate even on CPU CI where wall-clock is noisy.

Env: INC_PODS, INC_PLANES, INC_SSW, INC_FSW, INC_RSW, INC_EVENTS;
BENCH_SMOKE=1 selects tiny defaults.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import List, Tuple

import numpy as np

from benchmarks.common import emit, note

from openr_tpu.lsdb import LinkState
from openr_tpu.topology import build_adj_dbs, fabric_edges


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _set_link_overload(dbs, ls, node: str, other: str, down: bool) -> bool:
    """Publish `node`'s AdjacencyDatabase with the adjacency toward `other`
    marked (un)overloaded — the weight-only link-flap event shape (the link
    stays in the arrays; its weight patches to INF and back)."""
    db = dbs[node]
    db = dataclasses.replace(
        db,
        adjacencies=[
            dataclasses.replace(adj, is_overloaded=down)
            if adj.other_node_name == other
            else adj
            for adj in db.adjacencies
        ],
    )
    dbs[node] = db
    return ls.update_adjacency_database(db).topology_changed


def main(argv=None) -> None:
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    pods = _env_int("INC_PODS", 2 if smoke else 170)
    planes = _env_int("INC_PLANES", 2 if smoke else 4)
    ssw = _env_int("INC_SSW", 2 if smoke else 9)
    fsw = _env_int("INC_FSW", 2 if smoke else 8)
    rsw = _env_int("INC_RSW", 4 if smoke else 48)
    events = _env_int("INC_EVENTS", 6 if smoke else 50)
    warmup = 2

    from openr_tpu.solver.tpu import _AreaSolve

    edges = fabric_edges(
        pods, planes=planes, ssw_per_plane=ssw, fsw_per_pod=fsw,
        rsw_per_pod=rsw,
    )
    dbs = build_adj_dbs(edges)
    ls = LinkState("0")
    t0 = time.time()
    ls.bulk_update_adjacency_databases(list(dbs.values()))
    me = "rsw0_0"
    warm = _AreaSolve(ls, me)
    cold = _AreaSolve(ls, me, warm_start=False)
    assert warm.graph.sell is not None, "Clos must qualify for sliced-ELL"
    note(
        f"clos: n={warm.graph.n} e={warm.graph.e} "
        f"(padded {warm.graph.n_pad}/{warm.graph.e_pad}) "
        f"built + first solves in {time.time()-t0:.1f}s; "
        f"cold rounds={cold.rounds_last}"
    )

    # rotate flaps over far-pod rsw uplinks; rsw index starts at 1 so the
    # flapped link is never incident to me even in a single-pod topology
    # (a link at me changes the source batch and legitimately forces a
    # cold solve — not the steady-state event this bench measures)
    flap_pod = pods - 1
    links: List[Tuple[str, str]] = [
        (f"fsw{flap_pod}_{f}", f"rsw{flap_pod}_{r}")
        for f in range(fsw)
        for r in range(1, rsw)
    ]
    assert links, "need rsw_per_pod >= 2"

    warm_lat: List[float] = []
    cold_lat: List[float] = []
    warm_rounds: List[int] = []
    cold_rounds: List[int] = []
    for i in range(warmup + events):
        node, other = links[(i // 2) % len(links)]
        changed = _set_link_overload(dbs, ls, node, other, down=(i % 2 == 0))
        assert changed, (node, other, i)
        t0 = time.perf_counter()
        warm.refresh()  # blocks: rounds sync per event
        t_warm = time.perf_counter() - t0
        t0 = time.perf_counter()
        cold.refresh()
        t_cold = time.perf_counter() - t0
        if i < warmup:
            continue  # jit compile + cache warm
        warm_lat.append(t_warm)
        cold_lat.append(t_cold)
        warm_rounds.append(warm.rounds_last)
        cold_rounds.append(cold.rounds_last)

    assert warm.incremental_solves >= events, (
        warm.incremental_solves,
        warm.full_solves,
    )
    np.testing.assert_array_equal(warm.d, cold.d)  # bit-identical output

    rounds_warm = float(np.mean(warm_rounds))
    rounds_cold = float(np.mean(cold_rounds))
    # the headline claim, hardware-independent: warm-start converges in
    # fewer relaxation rounds than recompute-from-INF on the same events
    assert rounds_warm < rounds_cold, (warm_rounds, cold_rounds)

    mean_warm = float(np.mean(warm_lat))
    mean_cold = float(np.mean(cold_lat))
    p99_ms = float(np.percentile(warm_lat, 99) * 1e3)
    note(
        f"warm: {1.0/mean_warm:,.1f} events/s "
        f"(mean {mean_warm*1e3:.2f}ms, p99 {p99_ms:.2f}ms, "
        f"rounds {rounds_warm:.1f}) | cold: {1.0/mean_cold:,.1f} events/s "
        f"(mean {mean_cold*1e3:.2f}ms, rounds {rounds_cold:.1f})"
    )
    emit(
        {
            "metric": f"clos{warm.graph.n}_incremental_events_per_sec",
            "value": round(1.0 / mean_warm, 1),
            "unit": (
                f"link-flap events/s ({warm.graph.n}-node Clos, "
                "warm-start incremental solve)"
            ),
            "vs_baseline": round(mean_cold / mean_warm, 2),
            "baseline": "cold-solve",
            "p99_ms": round(p99_ms, 3),
            "rounds_warm_mean": round(rounds_warm, 2),
            "rounds_cold_mean": round(rounds_cold, 2),
        }
    )


if __name__ == "__main__":
    main()
