"""Benchmark: batched TPU SPF throughput vs the CPU SpfSolver oracle.

Mirrors the reference's DecisionBenchmark grid harness
(openr/decision/tests/DecisionBenchmark.cpp:806-823) on the BASELINE.md
config-1 topology (1k-node grid): measures SPF recomputes/sec — single-source
shortest-path computations per second — with ECMP first-hop DAG extraction
fused into the device step (BASELINE config 4).

Methodology: R independent solves (distinct per-event edge weights, as if R
LSDB events arrived) are chained inside one jit-compiled lax.scan, so one
dispatch covers R solves; throughput is the marginal time between a short and
a long chain, which cancels the fixed dispatch/sync latency of the device
link (the axon tunnel costs ~70ms per sync, irrelevant to steady-state event
processing where results stay device-resident). Baseline is the CPU oracle's
per-source Dijkstra on this host.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
plus detail lines on stderr.
"""

import json
import os
import sys
import time
from functools import partial

import numpy as np


def main() -> None:
    grid_side = int(os.environ.get("BENCH_GRID_SIDE", "32"))  # 32x32 = 1024
    reps_small = int(os.environ.get("BENCH_REPS_SMALL", "8"))
    reps_big = int(os.environ.get("BENCH_REPS_BIG", "64"))
    cpu_samples = int(os.environ.get("BENCH_CPU_SAMPLES", "8"))

    import jax
    import jax.numpy as jnp

    from openr_tpu.lsdb import LinkState
    from openr_tpu.ops import INF, compile_graph
    from openr_tpu.ops.spf import _bf_fixpoint_ell, _ecmp_dag
    from openr_tpu.topology import build_adj_dbs, grid_edges

    print(
        f"bench: {grid_side}x{grid_side} grid on {jax.devices()[0]}",
        file=sys.stderr,
    )

    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(grid_side)).values():
        ls.update_adjacency_database(db)
    graph = compile_graph(ls)
    assert graph.nbr is not None  # grid qualifies for the ELL pull kernel
    n_sources = graph.n
    print(
        f"graph: n={graph.n} e={graph.e} (padded {graph.n_pad}/{graph.e_pad})",
        file=sys.stderr,
    )

    sources = jnp.arange(graph.n_pad, dtype=jnp.int32)
    src = jnp.asarray(graph.src)
    dst = jnp.asarray(graph.dst)
    ov = jnp.asarray(graph.overloaded)
    nbr = jnp.asarray(graph.nbr)

    @partial(jax.jit, static_argnames=("reps",))
    def chained(w_variants, wg_variants, reps):
        def body(carry, wpair):
            w, wg = wpair
            d = _bf_fixpoint_ell(sources, nbr, wg, ov)
            dag = _ecmp_dag(d, src, dst, w, ov)
            # fold a data dependency so no solve can be elided
            return carry ^ d[0, -1] ^ dag[0, -1].astype(jnp.int32), None

        acc, _ = jax.lax.scan(
            body, jnp.int32(0), (w_variants[:reps], wg_variants[:reps])
        )
        return acc

    # distinct weight sets = distinct LSDB events, in both layouts
    w_np = [
        np.where(graph.w < INF, (graph.w + k) % 7 + 1, graph.w).astype(
            np.int32
        )
        for k in range(reps_big)
    ]
    wg_np = []
    for w_k in w_np:
        wg_k = graph.wg.copy()
        wg_k[graph.ell_row, graph.ell_slot] = w_k[: graph.e]
        wg_np.append(wg_k)
    w_variants = jnp.asarray(np.stack(w_np))
    wg_variants = jnp.asarray(np.stack(wg_np))

    t0 = time.time()
    int(chained(w_variants, wg_variants, reps_small))
    int(chained(w_variants, wg_variants, reps_big))
    print(f"compile+first runs: {time.time()-t0:.1f}s", file=sys.stderr)

    best_marginal = float("inf")
    for _ in range(3):
        t0 = time.time()
        int(chained(w_variants, wg_variants, reps_small))
        t_small = time.time() - t0
        t0 = time.time()
        int(chained(w_variants, wg_variants, reps_big))
        t_big = time.time() - t0
        marginal = (t_big - t_small) / (reps_big - reps_small)
        if marginal > 0:  # noise guard: tiny shapes can invert the pair
            best_marginal = min(best_marginal, marginal)
        print(
            f"chain {reps_small}: {t_small*1e3:.0f}ms  chain {reps_big}: "
            f"{t_big*1e3:.0f}ms  marginal {marginal*1e3:.2f}ms/solve",
            file=sys.stderr,
        )
    if not np.isfinite(best_marginal):
        # all pairs inverted by noise: fall back to the amortized long chain
        best_marginal = t_big / reps_big
    tpu_rate = n_sources / best_marginal
    print(
        f"tpu: {n_sources}-source solve + ECMP DAG in "
        f"{best_marginal*1e3:.2f}ms -> {tpu_rate:,.0f} SPF/s",
        file=sys.stderr,
    )

    # sanity: corner-to-corner distance with the unmodified weights
    d = _bf_fixpoint_ell(sources, nbr, jnp.asarray(graph.wg), ov)
    got = int(
        np.asarray(
            d[graph.node_index["g0_0"], graph.node_index[f"g{grid_side-1}_{grid_side-1}"]]
        )
    )
    assert got == 2 * (grid_side - 1), got

    # --- CPU oracle: per-source Dijkstra (the reference architecture) ---
    # The baseline of record is the native C++ Dijkstra (native/spf) — the
    # honest stand-in for the reference's C++ SpfSolver hot loop
    # (openr/decision/LinkState.cpp:806-880); the Python oracle rate is
    # reported on stderr for context only.
    sample_nodes = graph.names[:: max(1, len(graph.names) // cpu_samples)][
        :cpu_samples
    ]
    t0 = time.time()
    for node in sample_nodes:
        ls.run_spf(node)
    cpu_elapsed = time.time() - t0
    py_rate = len(sample_nodes) / cpu_elapsed
    print(
        f"python oracle: {len(sample_nodes)} Dijkstra runs in "
        f"{cpu_elapsed*1e3:.1f}ms -> {py_rate:,.0f} SPF/s",
        file=sys.stderr,
    )

    cpu_rate = py_rate
    baseline_kind = "python-oracle"
    from openr_tpu.solver.native_spf import (
        NativeSpfSolver,
        native_spf_available,
    )

    if native_spf_available():
        baseline_kind = "native-c++"
        solver = NativeSpfSolver(graph)
        native_sources = np.arange(graph.n, dtype=np.int32)
        solver.run_many(native_sources[:8])  # warm caches
        t0 = time.time()
        solver.run_many(native_sources)
        native_elapsed = time.time() - t0
        cpu_rate = len(native_sources) / native_elapsed
        print(
            f"native C++ oracle: {len(native_sources)} Dijkstra runs in "
            f"{native_elapsed*1e3:.1f}ms -> {cpu_rate:,.0f} SPF/s "
            "(baseline of record)",
            file=sys.stderr,
        )
        solver.close()

    print(
        json.dumps(
            {
                "metric": "spf_recomputes_per_sec",
                "value": round(tpu_rate, 1),
                "unit": f"SPF/s ({graph.n}-node grid, ECMP DAG fused)",
                "vs_baseline": round(tpu_rate / cpu_rate, 1),
                "baseline": baseline_kind,
            }
        )
    )


if __name__ == "__main__":
    main()
