"""Benchmark: batched TPU SPF throughput vs the native C++ SpfSolver oracle.

Headline config is BASELINE.md config 3 — batched multi-source SPF on a
100k-node synthetic WAN LSDB — the primary metric named in BASELINE.json
("SPF recomputes/sec on 100k-node LSDB"). The TPU side runs the sliced-ELL
pull relaxation (openr_tpu/ops/spf.py:_bf_fixpoint via _sell_solver); the
baseline of record is the native C++ Dijkstra (native/spf), the honest
stand-in for the reference's SpfSolver hot loop
(openr/decision/LinkState.cpp:806-880).

Methodology: R independent LSDB events are chained inside one jitted
lax.scan — each event patches the edge weights and solves an S-source
batch; a data dependency folds each result into a carry so no solve can be
elided. Throughput is the marginal time between a short and a long chain,
which cancels the fixed dispatch/sync latency of the device link (the axon
tunnel costs ~70ms per sync, irrelevant to steady-state event processing
where results stay device-resident).

Set BENCH_TOPO=grid for the 1k-node grid config (BASELINE.md config 1, with
ECMP first-hop DAG extraction fused — config 4 semantics).

Prints one JSON line per metric (SPF/s headline, convergence p95, TE
optimize latency, destination-tiled scale solve, exporter overhead):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "baseline": ...}
plus detail lines on stderr.
"""

import json
import os
import sys
import time
from functools import partial

import numpy as np


from benchmarks.common import note as _note
from benchmarks.common import time_marginal as _marginal_time


def _mem_columns(
    layout,
    n_nodes,
    structures,
    *,
    n_sources=1,
    graph=None,
    tiling=None,
    mesh_shape=None,
) -> dict:
    """Device-memory columns for one bench line (docs/Monitoring.md
    "Device-memory observatory"): the ledger's peak resident bytes for
    the line's structures next to the predict_fit forward model — the
    same padding/bucketing arithmetic the capacity-admission gate uses —
    so every BENCH round records how tight the prediction tracks what
    was actually pinned. Degraded-aware by construction: cpu-fallback
    rounds run the identical accounting on their reduced workload."""
    from openr_tpu.monitor.memledger import get_ledger

    ledger = get_ledger()
    verdict = ledger.predict_fit(
        n_nodes,
        layout,
        n_sources=n_sources,
        graph=graph,
        tiling=tiling,
        mesh_shape=mesh_shape,
    )
    peaks = ledger.structure_peak_bytes()
    peak = sum(peaks.get(s, 0) for s in structures)
    return {
        "mem_peak_bytes": int(peak),
        "mem_predicted_bytes": int(verdict["predicted_bytes"]),
        "mem_predicted_vs_live_bytes": int(
            verdict["predicted_bytes"] - peak
        ),
    }


def _native_rate(graph, samples: int) -> float:
    """SPF/s of the native C++ Dijkstra on `samples` sources."""
    from openr_tpu.solver.native_spf import NativeSpfSolver

    solver = NativeSpfSolver(graph)
    sources = np.linspace(0, graph.n - 1, samples, dtype=np.int32)
    solver.run_many(sources[: max(2, samples // 4)])  # warm caches
    t0 = time.time()
    solver.run_many(sources)
    elapsed = time.time() - t0
    rate = samples / elapsed
    _note(
        f"native C++ oracle: {samples} Dijkstra runs in "
        f"{elapsed*1e3:.1f}ms -> {rate:,.0f} SPF/s (baseline of record)"
    )
    solver.close()
    return rate


def _spf_phase_split(solve, sources, nbrs, wg_event, ov) -> dict:
    """One representative event measured with explicit barriers at the
    h2d / relax / d2h seams — the bench-side mirror of the flight
    recorder's sampled PhaseClock (docs/Monitoring.md "Flight recorder &
    profiling"), so the first hardware round lands with per-phase
    attribution on the SPF lines, not just one wall-clock number.
    Degraded-aware by construction: the same code path serves
    cpu-fallback rounds."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    wgs_dev = tuple(jnp.asarray(a) for a in wg_event)
    for a in wgs_dev:
        a.block_until_ready()
    t1 = time.perf_counter()
    d = solve(sources, nbrs, wgs_dev, ov)
    d.block_until_ready()
    t2 = time.perf_counter()
    np.asarray(d[0])  # one distance row host-side (the O(changes) shape)
    t3 = time.perf_counter()
    return {
        "h2d_ms": round((t1 - t0) * 1e3, 3),
        "relax_ms": round((t2 - t1) * 1e3, 3),
        "d2h_ms": round((t3 - t2) * 1e3, 3),
    }


def bench_wan() -> dict:
    import jax
    import jax.numpy as jnp

    from openr_tpu.ops.graph import INF, compile_edges
    from openr_tpu.ops.spf import _sell_solver_raw
    from openr_tpu.solver.native_spf import native_spf_available
    from openr_tpu.topology import wan_edges

    n = int(os.environ.get("BENCH_WAN_N", "100000"))
    # 128 sources = one 128-lane int32 tile in the minor dim — measured the
    # sweet spot on v5e (2500 SPF/s vs ~1650 at 1024 sources)
    n_sources = int(os.environ.get("BENCH_WAN_SOURCES", "128"))
    # chains long enough that the measured delta dwarfs the tunneled
    # link's sync jitter (~100ms): 8 extra events x ~50ms each
    reps_small = int(os.environ.get("BENCH_REPS_SMALL", "2"))
    reps_big = int(os.environ.get("BENCH_REPS_BIG", "10"))
    events = max(reps_big, reps_small)

    t0 = time.time()
    graph = compile_edges(wan_edges(n, degree=4, seed=3))
    _note(
        f"wan: n={graph.n} e={graph.e} (padded {graph.n_pad}/{graph.e_pad}) "
        f"built in {time.time()-t0:.1f}s on {jax.devices()[0]}"
    )
    sell = graph.sell
    assert sell is not None, "WAN degree profile must qualify for sliced-ELL"

    solve = _sell_solver_raw(sell.shape_key())

    rng = np.random.default_rng(7)
    sources = jnp.asarray(
        rng.choice(graph.n, size=n_sources, replace=False).astype(np.int32)
    )
    nbrs = tuple(jnp.asarray(a) for a in sell.nbr)
    ov = jnp.asarray(graph.overloaded)

    # distinct weight sets = distinct LSDB events, patched into the sliced
    # layout host-side exactly like refresh_graph's flap path
    wg_stacks = []
    for k in range(events):
        w_k = np.where(
            graph.w[: graph.e] < INF,
            (graph.w[: graph.e] + k) % 100 + 1,
            graph.w[: graph.e],
        ).astype(np.int32)
        wg_stacks.append(sell.patched_wg(w_k))
    wg_variants = tuple(
        jnp.asarray(np.stack([ws[i] for ws in wg_stacks]))
        for i in range(len(sell.wg))
    )

    # ledger registration of one event's device working set (the sell
    # planes + one weight set + the [S, n_pad] distance block the scan
    # materializes) — the line's mem columns read these back
    from openr_tpu.monitor.memledger import get_ledger

    ledger = get_ledger()
    ledger.register(
        "bench/wan", "sell", layout="sell",
        arrays=(*nbrs, *wg_stacks[0], ov),
    )
    ledger.register(
        "bench/wan", "dist", layout="sell",
        nbytes=n_sources * graph.n_pad * 4,
    )

    @partial(jax.jit, static_argnames=("reps",))
    def chained(wgv, reps):
        def body(carry, wgs_event):
            d = solve(sources, nbrs, wgs_event, ov)
            return carry ^ d[0, -1], None

        acc, _ = jax.lax.scan(
            body,
            jnp.int32(0),
            tuple(a[:reps] for a in wgv),
        )
        return acc

    t0 = time.time()
    int(chained(wg_variants, reps_small))
    int(chained(wg_variants, reps_big))
    _note(f"compile+first runs: {time.time()-t0:.1f}s")

    marginal = _marginal_time(
        lambda r: int(chained(wg_variants, r)), reps_small, reps_big
    )
    tpu_rate = n_sources / marginal
    _note(
        f"tpu: {n_sources}-source batch per event in {marginal*1e3:.1f}ms "
        f"-> {tpu_rate:,.0f} SPF/s"
    )

    # sanity: distances agree with the native oracle on unmodified weights
    # (solve just the sampled sources — pulling the full [S, n_pad] matrix
    # host-side would cost ~0.5GB over the device link for 3 rows)
    from openr_tpu.ops.spf import sell_fixpoint

    sample = np.asarray(sources)[[0, n_sources // 2, n_sources - 1]]
    d = np.asarray(sell_fixpoint(sell, sample, sell.wg, graph.overloaded))
    if native_spf_available():
        from openr_tpu.solver.native_spf import NativeSpfSolver

        solver = NativeSpfSolver(graph)
        for i, s in enumerate(sample):
            ref = solver.run(int(s))
            np.testing.assert_array_equal(d[i, : graph.n], ref)
        solver.close()
        _note("sanity: device distances match native oracle")
        cpu_rate = _native_rate(
            graph, int(os.environ.get("BENCH_CPU_SAMPLES", "32"))
        )
        baseline = "native-c++"
    else:  # toolchain missing: no honest baseline to report
        cpu_rate = None
        baseline = "unavailable"

    mem = _mem_columns(
        "sell", graph.n, ("sell", "dist"),
        n_sources=n_sources, graph=graph,
    )
    ledger.release_area("bench/wan")
    return {
        "metric": f"wan{graph.n}_spf_recomputes_per_sec",
        "value": round(tpu_rate, 1),
        "unit": f"SPF/s ({graph.n}-node WAN LSDB, {n_sources}-source batches)",
        "vs_baseline": round(tpu_rate / cpu_rate, 1) if cpu_rate else 0.0,
        "baseline": baseline,
        "phases": _spf_phase_split(
            solve, sources, nbrs, wg_stacks[0], ov
        ),
        **mem,
    }


def bench_grid() -> dict:
    import jax
    import jax.numpy as jnp

    from openr_tpu.lsdb import LinkState
    from openr_tpu.ops import INF, compile_graph
    from openr_tpu.ops.spf import _ecmp_dag, _sell_solver_raw
    from openr_tpu.solver.native_spf import native_spf_available
    from openr_tpu.topology import build_adj_dbs, grid_edges

    grid_side = int(os.environ.get("BENCH_GRID_SIDE", "32"))  # 32x32 = 1024
    reps_small = int(os.environ.get("BENCH_REPS_SMALL", "8"))
    reps_big = int(os.environ.get("BENCH_REPS_BIG", "64"))

    ls = LinkState("0")
    for db in build_adj_dbs(grid_edges(grid_side)).values():
        ls.update_adjacency_database(db)
    graph = compile_graph(ls)
    sell = graph.sell
    assert sell is not None
    _note(
        f"grid: n={graph.n} e={graph.e} (padded {graph.n_pad}/{graph.e_pad})"
        f" on {jax.devices()[0]}"
    )

    solve = _sell_solver_raw(sell.shape_key())
    sources = jnp.arange(graph.n_pad, dtype=jnp.int32)
    nbrs = tuple(jnp.asarray(a) for a in sell.nbr)
    ov = jnp.asarray(graph.overloaded)
    src_e = jnp.asarray(graph.src)
    dst_e = jnp.asarray(graph.dst)

    reps = reps_big
    w_rows = []
    wg_stacks = []
    for k in range(reps):
        w_k = np.where(
            graph.w < INF, (graph.w + k) % 7 + 1, graph.w
        ).astype(np.int32)
        w_rows.append(w_k)
        wg_stacks.append(sell.patched_wg(w_k[: graph.e]))
    w_variants = jnp.asarray(np.stack(w_rows))
    wg_variants = tuple(
        jnp.asarray(np.stack([ws[i] for ws in wg_stacks]))
        for i in range(len(sell.wg))
    )

    # one event's device working set on the ledger (mem columns below)
    from openr_tpu.monitor.memledger import get_ledger

    ledger = get_ledger()
    ledger.register(
        "bench/grid", "sell", layout="sell",
        arrays=(*nbrs, *wg_stacks[0], ov),
    )
    ledger.register(
        "bench/grid", "dist", layout="sell",
        nbytes=graph.n_pad * graph.n_pad * 4,
    )

    @partial(jax.jit, static_argnames=("reps",))
    def chained(wv, wgv, reps):
        def body(carry, event):
            w_e, wgs_event = event
            d = solve(sources, nbrs, wgs_event, ov)
            dag = _ecmp_dag(d, src_e, dst_e, w_e, ov)
            # fold a data dependency so no solve can be elided
            return carry ^ d[0, -1] ^ dag[0, -1].astype(jnp.int32), None

        acc, _ = jax.lax.scan(
            body,
            jnp.int32(0),
            (wv[:reps], tuple(a[:reps] for a in wgv)),
        )
        return acc

    t0 = time.time()
    int(chained(w_variants, wg_variants, reps_small))
    int(chained(w_variants, wg_variants, reps_big))
    _note(f"compile+first runs: {time.time()-t0:.1f}s")

    marginal = _marginal_time(
        lambda r: int(chained(w_variants, wg_variants, r)),
        reps_small,
        reps_big,
    )
    tpu_rate = graph.n / marginal
    _note(
        f"tpu: {graph.n}-source solve + ECMP DAG in {marginal*1e3:.2f}ms "
        f"-> {tpu_rate:,.0f} SPF/s"
    )

    # sanity: corner-to-corner distance with the unmodified weights
    from openr_tpu.ops.spf import sell_fixpoint

    d = sell_fixpoint(sell, np.arange(graph.n_pad), sell.wg, graph.overloaded)
    got = int(
        np.asarray(
            d[
                graph.node_index["g0_0"],
                graph.node_index[f"g{grid_side-1}_{grid_side-1}"],
            ]
        )
    )
    assert got == 2 * (grid_side - 1), got

    if native_spf_available():
        cpu_rate = _native_rate(graph, graph.n)
        baseline = "native-c++"
    else:
        t0 = time.time()
        sample = graph.names[:: max(1, graph.n // 8)][:8]
        for node in sample:
            ls.run_spf(node)
        cpu_rate = len(sample) / (time.time() - t0)
        baseline = "python-oracle"

    mem = _mem_columns(
        "sell", graph.n, ("sell", "dist"),
        n_sources=graph.n_pad, graph=graph,
    )
    ledger.release_area("bench/grid")
    return {
        "metric": "spf_recomputes_per_sec",
        "value": round(tpu_rate, 1),
        "unit": f"SPF/s ({graph.n}-node grid, ECMP DAG fused)",
        "vs_baseline": round(tpu_rate / cpu_rate, 1),
        "baseline": baseline,
        "phases": _spf_phase_split(
            solve, sources, nbrs, wg_stacks[0], ov
        ),
        **mem,
    }


def _apply_env_defaults(pairs) -> None:
    for key, val in pairs:
        os.environ.setdefault(key, val)


def _apply_smoke_env() -> None:
    """BENCH_SMOKE=1: tiny topology + short chains so the full bench path
    (compile, chained events, sanity checks, JSON emission) runs in CI —
    bench bitrot fails tier-1 instead of silently zeroing BENCH rounds."""
    _apply_env_defaults(
        (
            ("BENCH_WAN_N", "192"),
            ("BENCH_WAN_SOURCES", "8"),
            ("BENCH_GRID_SIDE", "6"),
            ("BENCH_REPS_SMALL", "1"),
            ("BENCH_REPS_BIG", "2"),
            ("BENCH_CPU_SAMPLES", "4"),
            ("BENCH_TE_STEPS", "6"),
            ("BENCH_TE_SCENARIOS", "2"),
            ("BENCH_TE_REPEATS", "1"),
            ("BENCH_SCALE_N", "384"),
            ("BENCH_SCALE_SOURCES", "8"),
            ("BENCH_SCALE_FLAPS", "2"),
            ("BENCH_EXPORTER_RECORDS", "200"),
            ("BENCH_STREAM_SUBS", "8"),
            ("BENCH_STREAM_SWEEP", "4"),
            ("BENCH_APSP_N", "96"),
            ("BENCH_APSP_SWEEP", "48,96"),
            ("BENCH_APSP_REPEATS", "1"),
        )
    )


def _apply_reduced_env() -> None:
    """Reduced workload for degraded (CPU-fallback) runs: the line is an
    availability signal, not a perf sample, so it must finish fast."""
    _apply_env_defaults(
        (
            ("BENCH_WAN_N", "2000"),
            ("BENCH_WAN_SOURCES", "16"),
            ("BENCH_GRID_SIDE", "16"),
            ("BENCH_REPS_SMALL", "2"),
            ("BENCH_REPS_BIG", "4"),
            ("BENCH_CPU_SAMPLES", "8"),
            ("BENCH_CONV_NODES", "4"),
            ("BENCH_CONV_FLAPS", "1"),
            ("BENCH_TE_STEPS", "12"),
            ("BENCH_TE_SCENARIOS", "2"),
            ("BENCH_TE_REPEATS", "1"),
            ("BENCH_SCALE_N", "20000"),
            ("BENCH_SCALE_SOURCES", "8"),
            ("BENCH_SCALE_FLAPS", "2"),
            ("BENCH_EXPORTER_RECORDS", "500"),
            ("BENCH_STREAM_SUBS", "16"),
            ("BENCH_STREAM_SWEEP", "8"),
            ("BENCH_APSP_N", "256"),
            ("BENCH_APSP_SWEEP", "64,128,256"),
            ("BENCH_APSP_REPEATS", "1"),
        )
    )


def _probe_backend() -> str:
    """'native' when the configured JAX backend initializes, else force
    JAX_PLATFORMS=cpu (with a reduced workload) and report 'cpu-fallback'.

    Probed in a subprocess: jax caches a failed backend discovery
    in-process, so an in-process probe could not be retried on CPU."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "native"  # already explicitly CPU: nothing to probe
    import subprocess

    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True,
            timeout=180,
        )
        ok = probe.returncode == 0
        err = probe.stderr.decode(errors="replace").strip().splitlines()
    except Exception as exc:  # timeout/spawn failure: treat as unavailable
        ok = False
        err = [repr(exc)]
    if ok:
        return "native"
    _note("backend probe failed: " + (err[-1] if err else "unknown error"))
    _note("falling back to JAX_PLATFORMS=cpu with a reduced workload")
    os.environ["JAX_PLATFORMS"] = "cpu"
    # jax is already imported transitively (openr_tpu.ops); the env var is
    # only read at import time, so update the live config too — safe while
    # no backend has been initialized in this process (the probe ran in a
    # subprocess precisely to keep it that way)
    import jax

    jax.config.update("jax_platforms", "cpu")
    _apply_reduced_env()
    return "cpu-fallback"


# the convergence flap batch's summary, kept so the exporter-overhead
# line measures on the SAME run instead of spinning a second emulator
_CONV_SUMMARY = {}


def _bench_convergence() -> dict:
    """Second metric line: p95 hello-to-programmed-route from an emulator
    line-topology flap run (VirtualNetwork.convergence_report), so the
    incremental/DeltaPath work shows up in the trajectory as
    convergence.e2e_ms, not just raw SPF/s."""
    from openr_tpu.testing.decision_harness import run_bench_convergence

    nodes = int(os.environ.get("BENCH_CONV_NODES", "5"))
    flaps = int(os.environ.get("BENCH_CONV_FLAPS", "2"))
    backend = os.environ.get("BENCH_CONV_BACKEND", "tpu")
    summary = run_bench_convergence(nodes=nodes, flaps=flaps, backend=backend)
    _CONV_SUMMARY.update(summary)
    _note(
        f"convergence: {summary['spans_total']} spans over "
        f"{summary['flaps']} flap cycles on a {summary['nodes']}-node line "
        f"-> p50 {summary['e2e_p50_ms']:.1f}ms / p95 "
        f"{summary['e2e_p95_ms']:.1f}ms"
    )
    return {
        "metric": "convergence_e2e_p95_ms",
        "value": round(summary["e2e_p95_ms"], 2),
        "unit": (
            f"ms p95 hello-to-programmed-route ({summary['nodes']}-node "
            f"line emulator, {summary['flaps']} flap cycles, "
            f"{backend} backend)"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "spans": summary["spans_total"],
        "e2e_p50_ms": round(summary["e2e_p50_ms"], 2),
        "e2e_max_ms": round(summary["e2e_max_ms"], 2),
    }


def _bench_te() -> dict:
    """Third metric line: wall-clock of one what-if differentiable-TE
    optimization (openr_tpu/te) on the congested 2-pod Clos fixture with
    its skewed synthetic demand matrix — the TE workload enters the bench
    trajectory from day one as te_optimize_ms. Degraded-aware like the
    other lines: a cpu-fallback round runs the identical optimization with
    a reduced step budget and is marked `"degraded": true` by main()."""
    from openr_tpu.lsdb import LinkState
    from openr_tpu.te import TeService, congested_clos_fixture
    from openr_tpu.topology import build_adj_dbs

    steps = int(os.environ.get("BENCH_TE_STEPS", "48"))
    scenarios = int(os.environ.get("BENCH_TE_SCENARIOS", "4"))
    repeats = int(os.environ.get("BENCH_TE_REPEATS", "3"))

    edges, spec = congested_clos_fixture()
    ls = LinkState("0")
    for db in build_adj_dbs(edges).values():
        ls.update_adjacency_database(db)
    svc = TeService("l0_0", {"0": ls})
    params = {"demands": spec, "steps": steps, "scenarios": scenarios}
    report = svc.optimize(params)  # compile + first run, excluded
    times = []
    for _ in range(max(repeats, 1)):
        report = svc.optimize(params)
        times.append(report["solve_ms"])
    best = min(times)
    _note(
        f"te-optimize: {report['nodes']}-node Clos, {report['scenarios']} "
        f"scenario(s), {report['steps']} steps in {best:.1f}ms (best of "
        f"{len(times)}; first+compile excluded) — max util "
        f"{report['initial_max_util']:.2f} -> "
        f"{report['optimized_max_util']:.2f}"
    )
    # TE registers its [B, n, n] scenario batch on the ledger for each
    # run's duration (te/service.py seam); the structure peak is what one
    # optimization actually pinned
    from openr_tpu.ops.graph import compile_graph

    mem = _mem_columns(
        "te", report["nodes"], ("te",),
        n_sources=report["scenarios"], graph=compile_graph(ls),
    )
    return {
        "metric": "te_optimize_ms",
        "value": round(best, 2),
        "unit": (
            f"ms per what-if TE optimization ({report['nodes']}-node Clos, "
            f"{report['scenarios']} scenario(s), {report['steps']} Adam "
            f"steps, compile excluded)"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "initial_max_util": report["initial_max_util"],
        "optimized_max_util": report["optimized_max_util"],
        "improved": report["improved"],
        **mem,
    }


def _bench_scale() -> dict:
    """Fourth metric line: the destination-tiled 2-D layout at scale — a
    synthetic WAN cold solve plus a warm link-flap batch with D tiled
    P('batch', 'graph') over every available device, per-device tile bytes
    reported next to the [S, n_pad] replica bytes the old row-sharded
    layout would have pinned per chip. Defaults to the 1M-node config
    (the ROADMAP "heavy traffic from millions of users" topology class);
    BENCH_SMOKE / cpu-fallback rounds shrink it so the line is always an
    availability signal, never a hang."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from openr_tpu.ops.graph import INF, compile_edges
    from openr_tpu.ops.spf import _tile_solver, _tile_solver_warm
    from openr_tpu.parallel import make_mesh, tile_graph
    from openr_tpu.topology import wan_edges

    n = int(os.environ.get("BENCH_SCALE_N", "1000000"))
    n_sources = int(os.environ.get("BENCH_SCALE_SOURCES", "16"))
    flaps = int(os.environ.get("BENCH_SCALE_FLAPS", "4"))

    devices = jax.devices()
    total = 1
    while total * 2 <= len(devices):
        total *= 2
    b_ax = 2 if total >= 4 else 1
    g_ax = total // b_ax
    mesh = make_mesh(devices[:total], shape=(b_ax, g_ax))

    t0 = time.time()
    graph = compile_edges(wan_edges(n, degree=4, seed=5))
    if graph.n_pad % g_ax:
        # tiny-n smoke configs can under-run the graph axis; shrink it
        while g_ax > 1 and graph.n_pad % g_ax:
            g_ax //= 2
        mesh = make_mesh(devices[: b_ax * g_ax], shape=(b_ax, g_ax))
    tiling = tile_graph(graph, g_ax)
    _note(
        f"scale: n={graph.n} e={graph.e} (n_pad {graph.n_pad}) built in "
        f"{time.time()-t0:.1f}s; mesh {dict(mesh.shape)}, tile "
        f"{graph.n_pad // g_ax} cols x {tiling.e_tile} edges/partition"
    )

    gs = NamedSharding(mesh, P("graph", None))
    repl = NamedSharding(mesh, P())
    rng = np.random.default_rng(11)
    s_pad = n_sources + (-n_sources) % b_ax
    rows = rng.choice(graph.n, size=s_pad, replace=False).astype(np.int32)
    args = (
        jax.device_put(
            jnp.asarray(rows), NamedSharding(mesh, P("batch"))
        ),
        jax.device_put(jnp.asarray(tiling.src_l), gs),
        jax.device_put(jnp.asarray(tiling.hseg), gs),
        jax.device_put(jnp.asarray(tiling.w), gs),
        jax.device_put(jnp.asarray(tiling.hcols), gs),
        jax.device_put(jnp.asarray(graph.overloaded), repl),
    )
    key = tiling.shape_key() + (graph.n_pad,)
    solve = _tile_solver(key, mesh)
    # the resident tile working set on the ledger (mem columns below):
    # edge tiles + halo frontier + the tiled D (logical global bytes)
    from openr_tpu.monitor.memledger import get_ledger

    ledger = get_ledger()
    ledger.register(
        "bench/scale", "tile", layout="tile2d",
        arrays=(args[1], args[2], args[3], args[5]),
    )
    ledger.register(
        "bench/scale", "halo", layout="tile2d", arrays=(args[4],)
    )
    ledger.register(
        "bench/scale", "dist", layout="tile2d",
        nbytes=s_pad * graph.n_pad * 4,
    )
    d, rounds = solve(*args)  # compile + first run, excluded
    t0 = time.time()
    d, rounds = solve(*args)
    cold_rounds = int(rounds)  # scalar read forces completion
    cold_ms = (time.time() - t0) * 1e3

    # warm link-flap batch: metric wiggles on random up edges, each event
    # one warm dispatch against the resident tile state
    warm = _tile_solver_warm(key, mesh)
    ov = args[5]
    up = np.nonzero(graph.w[: graph.e] < INF)[0]
    w2_old = args[3]
    warm_ms = []
    warm_rounds = []
    for i in range(max(flaps, 1)):
        w_new = graph.w.copy()
        pos = up[rng.integers(len(up))]
        w_new[pos] = (w_new[pos] + 1 + i) % 100 + 1
        w2_new = jax.device_put(jnp.asarray(tiling.tile_weights(w_new)), gs)
        t0 = time.time()
        d, r, ir, _, num = warm(
            args[0], args[1], args[2], w2_new, w2_old, args[4], ov, ov, d
        )
        warm_rounds.append(int(r) + int(ir))  # forces completion
        warm_ms.append((time.time() - t0) * 1e3)
        w2_old = w2_new
    warm_best = min(warm_ms)

    # phase-split attribution of one more warm flap, with explicit
    # barriers at the h2d / relax / d2h seams (the tiled layout's halo
    # traffic rides inside relax — the rounds split it, like the flight
    # recorder's sampled traces; docs/Monitoring.md)
    w_new = graph.w.copy()
    pos = up[rng.integers(len(up))]
    w_new[pos] = (w_new[pos] + 7) % 100 + 1
    t0 = time.perf_counter()
    w2_new = jax.device_put(jnp.asarray(tiling.tile_weights(w_new)), gs)
    w2_new.block_until_ready()
    t1 = time.perf_counter()
    d, r, ir, _, num = warm(
        args[0], args[1], args[2], w2_new, w2_old, args[4], ov, ov, d
    )
    d.block_until_ready()
    t2 = time.perf_counter()
    np.asarray(d[0])  # one distance row host-side
    t3 = time.perf_counter()
    phases = {
        "h2d_ms": round((t1 - t0) * 1e3, 3),
        "relax_ms": round((t2 - t1) * 1e3, 3),
        "d2h_ms": round((t3 - t2) * 1e3, 3),
    }

    tile_bytes = (s_pad // b_ax) * (graph.n_pad // g_ax) * 4
    replica_bytes = s_pad * graph.n_pad * 4
    _note(
        f"scale: cold solve {cold_ms:.0f}ms ({cold_rounds} rounds), warm "
        f"flap best {warm_best:.0f}ms over {len(warm_ms)} event(s); "
        f"per-device D tile {tile_bytes / 1e6:.1f}MB vs full replica "
        f"{replica_bytes / 1e6:.1f}MB ({replica_bytes / max(tile_bytes, 1):.0f}x)"
    )
    mem = _mem_columns(
        "tile2d", graph.n, ("tile", "halo", "dist"),
        n_sources=s_pad, graph=graph, tiling=tiling,
        mesh_shape=(b_ax, g_ax),
    )
    ledger.release_area("bench/scale")
    return {
        "metric": f"scale{graph.n}_tiled_cold_solve_ms",
        "value": round(cold_ms, 2),
        "unit": (
            f"ms cold {s_pad}-source solve ({graph.n}-node WAN, D tiled "
            f"P('batch','graph') over mesh {dict(mesh.shape)})"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "warm_flap_ms": round(warm_best, 2),
        "tile_bytes_per_device": tile_bytes,
        "replica_bytes_per_device": replica_bytes,
        "mesh": [mesh.shape["batch"], mesh.shape["graph"]],
        "phases": phases,
        **mem,
    }


def _bench_exporter() -> dict:
    """Fifth metric line: continuous-telemetry overhead on the standard
    flap batch — best full-registry Prometheus exposition render (each
    render parsed back, so the sample only counts if the text round-trips)
    plus the per-record windowed-rollup fold cost, both measured on the
    converged emulator run behind the convergence line (one emulator spin
    serves both; with BENCH_CONVERGENCE=0 a reduced flap batch is run
    here instead). Degraded-aware like the other lines: cpu-fallback
    rounds reuse their reduced flap batch and are marked by main()."""
    summary = dict(_CONV_SUMMARY)
    if "scrape_render_ms" not in summary:
        from openr_tpu.testing.decision_harness import run_bench_convergence

        summary = run_bench_convergence(
            nodes=int(os.environ.get("BENCH_CONV_NODES", "5")),
            flaps=1,
            backend=os.environ.get("BENCH_CONV_BACKEND", "tpu"),
        )
    _note(
        f"exporter: {summary['metrics_series']}-family registry rendered "
        f"in {summary['scrape_render_ms']:.3f}ms, rollup fold "
        f"{summary['rollup_record_us']:.2f}us/span "
        f"({summary['nodes']}-node flap batch)"
    )
    return {
        "metric": "exporter_scrape_render_ms",
        "value": summary["scrape_render_ms"],
        "unit": (
            f"ms best full-registry Prometheus exposition render "
            f"({summary['metrics_series']} metric families, "
            f"{summary['nodes']}-node line emulator flap batch, "
            f"parse-validated)"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "rollup_record_us": summary["rollup_record_us"],
        "metrics_series": summary["metrics_series"],
    }


def _bench_stream() -> dict:
    """Sixth metric line: streaming control-plane fan-out throughput —
    the standard convergence flap batch re-run with BENCH_STREAM_SUBS
    concurrent `subscribeKvStore` subscriptions riding every node's real
    ctrl socket (docs/Streaming.md). The metric is sustained
    delta-delivery rate summed across subscribers (deliveries/s); the
    line also carries the run's convergence e2e p95 next to the
    zero-subscriber baseline's (the convergence line measured earlier on
    the same config), asserting fan-out does not move the convergence
    path outside noise. Degraded-aware like every line: cpu-fallback
    rounds run the reduced batch and are marked by main()."""
    from openr_tpu.testing.decision_harness import run_bench_convergence

    nodes = int(os.environ.get("BENCH_CONV_NODES", "5"))
    flaps = int(os.environ.get("BENCH_CONV_FLAPS", "2"))
    backend = os.environ.get("BENCH_CONV_BACKEND", "tpu")
    subscribers = int(os.environ.get("BENCH_STREAM_SUBS", "64"))
    summary = run_bench_convergence(
        nodes=nodes,
        flaps=flaps,
        backend=backend,
        measure_exporter=False,
        subscribers=subscribers,
    )
    baseline_p95 = _CONV_SUMMARY.get("e2e_p95_ms", 0.0)
    p95 = summary["e2e_p95_ms"]
    if baseline_p95 > 0:
        # "held flat": generous noise envelope — an emulator flap batch
        # on shared CI jitters; a real fan-out regression (subscribers
        # serialized into the convergence path) blows through 5x+250ms
        assert p95 <= baseline_p95 * 5.0 + 250.0, (
            f"convergence p95 {p95:.1f}ms with {subscribers} subscribers "
            f"vs {baseline_p95:.1f}ms baseline: fan-out is not isolated"
        )
    # subscriber sweep: the same flap batch at other fan-out widths, so
    # one BENCH round records how delivery rate and encode share scale
    # with subscriber count (BENCH_STREAM_SWEEP, comma-separated counts;
    # smoke/reduced envs pin tiny defaults — degraded rounds inherit the
    # reduced sweep like every other knob)
    sweep_counts = [
        int(x)
        for x in os.environ.get("BENCH_STREAM_SWEEP", "16,256").split(",")
        if x.strip() and int(x) != subscribers
    ]
    sweep = []
    for count in sweep_counts:
        point = run_bench_convergence(
            nodes=nodes,
            flaps=flaps,
            backend=backend,
            measure_exporter=False,
            subscribers=count,
        )
        sweep.append(
            {
                "subscribers": count,
                "events_s": round(point["stream_events_per_s"], 1),
                "encode_share": point["stream_encode_share"],
                "class_hit_rate": point["stream_class_hit_rate"],
            }
        )
    _note(
        f"stream: {subscribers} subscriber(s) x {summary['nodes']}-node "
        f"flap batch -> {summary['stream_deltas']} deliveries "
        f"({summary['stream_events_per_s']:,.0f}/s), "
        f"{summary['stream_resyncs']} resync(s); encode share "
        f"{summary['stream_encode_share'] * 100:.1f}% (class hit rate "
        f"{summary['stream_class_hit_rate'] * 100:.0f}%); e2e p95 "
        f"{p95:.1f}ms vs {baseline_p95:.1f}ms without subscribers"
    )
    return {
        "metric": "stream_fanout_events_s",
        "value": round(summary["stream_events_per_s"], 1),
        "unit": (
            f"delta deliveries/s across {subscribers} concurrent "
            f"subscribeKvStore subscriber(s) ({summary['nodes']}-node "
            f"line emulator, {summary['flaps']} flap cycles)"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "subscribers": subscribers,
        "deliveries": summary["stream_deltas"],
        "resyncs": summary["stream_resyncs"],
        # the shared-encode meters (docs/Streaming.md): fraction of the
        # batch wall clock spent on REAL body serializations, and how
        # often subscribers reused a filter-class's shared bytes
        "encode_share": summary["stream_encode_share"],
        "encode_classes": summary["stream_encode_classes"],
        "class_hit_rate": summary["stream_class_hit_rate"],
        "sweep": sweep,
        "e2e_p95_ms": round(p95, 2),
        "baseline_e2e_p95_ms": round(baseline_p95, 2),
    }


def _bench_apsp() -> dict:
    """Seventh metric line: the blocked min-plus Floyd–Warshall APSP close
    (openr_tpu/apsp, docs/Apsp.md) on a synthetic WAN — cold close wall
    time (compile excluded), the warm re-close of a single-link weight
    event (rounds + ms, the O(dirty-blocks) path), and the
    FW-vs-batched-Dijkstra crossover sweep: at each node count the dense
    blocked close races the batched min-plus column solve for ALL sources
    (what serving the same all-pairs demand through the one-source batch
    machinery would cost), bracketing where the solver should hand off.
    Degraded-aware like every line: cpu-fallback rounds shrink the sizes
    and are marked by main()."""
    from openr_tpu.apsp import ApspState, np_floyd_warshall, build_weight_matrix
    from openr_tpu.ops.graph import compile_edges
    from openr_tpu.ops.spf import batched_spf
    from openr_tpu.topology import wan_edges

    n = int(os.environ.get("BENCH_APSP_N", "2048"))
    sweep = [
        int(x)
        for x in os.environ.get("BENCH_APSP_SWEEP", "256,512,1024").split(",")
        if x.strip()
    ]
    repeats = int(os.environ.get("BENCH_APSP_REPEATS", "3"))

    def graph_for(nodes):
        return compile_edges(wan_edges(nodes, degree=4, seed=7))

    graph = graph_for(n)
    apsp = ApspState(max_nodes=n)
    apsp.ensure(graph)  # compile + first close, excluded
    cold_times = []
    for _ in range(max(repeats, 1)):
        apsp.invalidate("bench_cold")
        apsp.ensure(graph)
        cold_times.append(apsp.close_ms_last)
    cold_ms = min(cold_times)

    # warm re-close of a single-link weight event: patch one real edge
    # (the first warm event compiles the seed + re-close executables and
    # is dropped, same compile-excluded convention as the cold loop)
    w_mut = graph.w.copy()
    pos = graph.e // 2
    warm_times = []
    rounds = 0
    for i in range(max(repeats, 1) + 1):
        w_mut = w_mut.copy()
        w_mut[pos] = int(w_mut[pos]) % 13 + 1 + i
        graph.w = w_mut
        graph.version += 1
        apsp.ensure(graph)
        if i:
            warm_times.append(apsp.close_ms_last)
        rounds = apsp.reclose_rounds_last or 0
    warm_ms = min(warm_times)

    # mem columns measured while ONLY the main state's FW triple is
    # resident (the sweep below stacks smaller states; ApspState
    # registers its matrices with the ledger itself)
    mem = _mem_columns("apsp", graph.n, ("apsp",), graph=graph)

    crossover = []
    handoff = None
    for nodes in sweep:
        g = compile_edges(wan_edges(nodes, degree=4, seed=7))
        sub = ApspState(max_nodes=nodes)
        sub.ensure(g)  # compile excluded
        sub.invalidate("bench_cold")
        t0 = time.perf_counter()
        sub.ensure(g)
        fw_ms = (time.perf_counter() - t0) * 1e3
        sources = np.arange(g.n_pad, dtype=np.int32)
        np.asarray(batched_spf(g, sources))  # compile excluded
        t0 = time.perf_counter()
        np.asarray(batched_spf(g, sources))
        dj_ms = (time.perf_counter() - t0) * 1e3
        crossover.append(
            {
                "nodes": nodes,
                "fw_close_ms": round(fw_ms, 3),
                "batched_dijkstra_ms": round(dj_ms, 3),
            }
        )
        if handoff is None and fw_ms < dj_ms:
            handoff = nodes
        sub.close()  # return the sweep state's ledger bytes
    # parity spot-check: the bench must not report a number for a wrong
    # matrix (cheap at the smallest sweep size)
    g_chk = compile_edges(wan_edges(sweep[0], degree=4, seed=7))
    chk = ApspState(max_nodes=sweep[0])
    chk.ensure(g_chk)
    ref = np_floyd_warshall(build_weight_matrix(g_chk), g_chk.overloaded)
    assert np.array_equal(chk.d, ref), "APSP bench parity check failed"
    chk.close()
    apsp.close()

    _note(
        f"apsp: {n}-node WAN blocked-FW close {cold_ms:.1f}ms cold / "
        f"{warm_ms:.1f}ms warm re-close ({rounds} round(s)); crossover "
        + ", ".join(
            f"{c['nodes']}n fw {c['fw_close_ms']:.0f}ms vs dj "
            f"{c['batched_dijkstra_ms']:.0f}ms"
            for c in crossover
        )
    )
    return {
        "metric": "fw_apsp_close_ms",
        "value": round(cold_ms, 3),
        "unit": (
            f"ms per cold blocked-FW all-pairs close ({n}-node WAN, "
            f"compile excluded, best of {len(cold_times)})"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "warm_reclose_ms": round(warm_ms, 3),
        "reclose_rounds": rounds,
        "crossover": crossover,
        "crossover_nodes": handoff,
        **mem,
    }


def _bench_fleet() -> dict:
    """Eighth metric line: continuous fleet-observation overhead — the
    standard convergence flap batch re-run with the fleet observer
    (openr_tpu/fleet) attached over every node's real ctrl socket,
    scraping + streaming + evaluating the SLO rules continuously. The
    metric is the mean watchdog tick cost (scrape sweep fold + rule
    evaluation over the store); the line carries the attached run's
    convergence e2e p95 next to the detached baseline's (the convergence
    line measured earlier on the same config) so a fleet watcher that
    perturbs the convergence path is caught, not just a slow one.
    Degraded-aware like every line: cpu-fallback rounds run the reduced
    batch and are marked by main()."""
    from openr_tpu.testing.decision_harness import run_bench_convergence

    nodes = int(os.environ.get("BENCH_CONV_NODES", "5"))
    flaps = int(os.environ.get("BENCH_CONV_FLAPS", "2"))
    backend = os.environ.get("BENCH_CONV_BACKEND", "tpu")
    summary = run_bench_convergence(
        nodes=nodes,
        flaps=flaps,
        backend=backend,
        measure_exporter=False,
        fleet_observer=True,
    )
    baseline_p95 = _CONV_SUMMARY.get("e2e_p95_ms", 0.0)
    p95 = summary["e2e_p95_ms"]
    if baseline_p95 > 0:
        # the same held-flat envelope as the fan-out line: an observer
        # that serializes into the convergence path blows through it
        assert p95 <= baseline_p95 * 5.0 + 250.0, (
            f"convergence p95 {p95:.1f}ms with the fleet observer "
            f"attached vs {baseline_p95:.1f}ms detached: the watcher is "
            f"not isolated"
        )
    _note(
        f"fleet: observer on the {summary['nodes']}-node flap batch -> "
        f"{summary['fleet_ticks']} watchdog tick(s) at "
        f"{summary['fleet_tick_ms']:.3f}ms/tick, "
        f"{summary['fleet_scrapes']} scrapes at "
        f"{summary['fleet_scrape_ms']:.3f}ms; e2e p95 {p95:.1f}ms "
        f"attached vs {baseline_p95:.1f}ms detached"
    )
    return {
        "metric": "fleet_watch_overhead_ms",
        "value": round(max(summary["fleet_tick_ms"], 1e-4), 4),
        "unit": (
            f"ms mean SLO-watchdog tick (fleet observer attached to the "
            f"{summary['nodes']}-node line emulator flap batch over real "
            f"ctrl sockets)"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "fleet_ticks": summary["fleet_ticks"],
        "fleet_scrapes": summary["fleet_scrapes"],
        "fleet_scrape_ms": summary["fleet_scrape_ms"],
        "attached_e2e_p95_ms": round(p95, 2),
        "baseline_e2e_p95_ms": round(baseline_p95, 2),
    }


def _bench_journal() -> dict:
    """Ninth metric line: state-journal recording overhead — the standard
    convergence flap batch re-run with every node journaling its KvStore
    publications and RIB deltas (openr_tpu/journal). The metric is the
    mean per-record cost from the sampled `journal.record_ms` guard; the
    line carries the journal-on run's convergence e2e p95 next to the
    journal-off baseline's (the convergence line measured earlier on the
    same config) under the same held-flat envelope as the fan-out and
    fleet lines, and every node's final state is replay-verified against
    the CPU oracle (docs/Journal.md). Degraded-aware like every line."""
    from openr_tpu.testing.decision_harness import run_bench_convergence

    nodes = int(os.environ.get("BENCH_CONV_NODES", "5"))
    flaps = int(os.environ.get("BENCH_CONV_FLAPS", "2"))
    backend = os.environ.get("BENCH_CONV_BACKEND", "tpu")
    summary = run_bench_convergence(
        nodes=nodes,
        flaps=flaps,
        backend=backend,
        measure_exporter=False,
        journal=True,
    )
    baseline_p95 = _CONV_SUMMARY.get("e2e_p95_ms", 0.0)
    p95 = summary["e2e_p95_ms"]
    if baseline_p95 > 0:
        # held-flat envelope vs the journal-off baseline: a recorder
        # that serializes into the convergence path blows through it
        assert p95 <= baseline_p95 * 5.0 + 250.0, (
            f"convergence p95 {p95:.1f}ms with the state journal "
            f"recording vs {baseline_p95:.1f}ms journal-off: the "
            f"recorder is not O(changes)"
        )
    verified = summary["journal_replay_verified"]
    assert verified == summary["journal_nodes"], (
        f"replay determinism broke under the flap batch: only {verified} "
        f"of {summary['journal_nodes']} nodes' replayed RIBs matched the "
        f"CPU oracle"
    )
    _note(
        f"journal: {summary['journal_records']} records over the "
        f"{summary['nodes']}-node flap batch at "
        f"{summary['journal_record_us']:.1f}us/record (sampled), "
        f"{verified}/{summary['journal_nodes']} nodes replay-verified; "
        f"e2e p95 {p95:.1f}ms journal-on vs {baseline_p95:.1f}ms off"
    )
    return {
        "metric": "journal_record_us",
        "value": round(max(summary["journal_record_us"], 1e-4), 4),
        "unit": (
            f"us mean journal record (sampled guard, every node of the "
            f"{summary['nodes']}-node line emulator flap batch recording "
            f"publications + RIB deltas)"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "journal_records": summary["journal_records"],
        "journal_evicted": summary["journal_evicted"],
        "journal_replay_verified": verified,
        "journal_nodes": summary["journal_nodes"],
        "attached_e2e_p95_ms": round(p95, 2),
        "baseline_e2e_p95_ms": round(baseline_p95, 2),
    }


def _bench_convergence_under_loss() -> dict:
    """Tenth metric line: convergence under hostile transport — the
    standard flap batch re-run behind a seeded chaos mesh dropping a
    fraction of every KvStore RPC (openr_tpu/testing/chaos.py). The
    dissemination plane has to eat the drops with retried full syncs and
    anti-entropy repair, so the p95 is allowed a much looser envelope
    than the attached lines — the assertion is that loss degrades
    convergence boundedly instead of wedging it (a wedged store never
    converges and the flap batch itself times out). The line carries the
    drop count as evidence that the mesh actually interfered."""
    from openr_tpu.testing.decision_harness import run_bench_convergence

    nodes = int(os.environ.get("BENCH_CONV_NODES", "5"))
    flaps = int(os.environ.get("BENCH_CONV_FLAPS", "2"))
    backend = os.environ.get("BENCH_CONV_BACKEND", "tpu")
    loss = float(os.environ.get("BENCH_LOSS_RATE", "0.15"))
    seed = int(os.environ.get("BENCH_LOSS_SEED", "1"))
    summary = run_bench_convergence(
        nodes=nodes,
        flaps=flaps,
        backend=backend,
        measure_exporter=False,
        chaos_loss=loss,
        chaos_seed=seed,
    )
    baseline_p95 = _CONV_SUMMARY.get("e2e_p95_ms", 0.0)
    p95 = summary["e2e_p95_ms"]
    if baseline_p95 > 0:
        # bounded-degradation envelope vs the lossless baseline: wide,
        # because every dropped flood costs a full-sync retry on a
        # jittered backoff — but a store that livelocks under loss
        # (re-flooding without repairing) blows through even this
        assert p95 <= baseline_p95 * 20.0 + 2000.0, (
            f"convergence p95 {p95:.1f}ms under {loss:.0%} KvStore RPC "
            f"loss vs {baseline_p95:.1f}ms clean: the dissemination "
            f"plane is not recovering boundedly from drops"
        )
    _note(
        f"loss: e2e p95 {p95:.1f}ms under {loss:.0%} seeded RPC loss "
        f"(seed {seed}, {summary['chaos_kv_dropped']} RPCs dropped) vs "
        f"{baseline_p95:.1f}ms clean"
    )
    return {
        "metric": "convergence_under_loss_p95_ms",
        "value": round(p95, 2),
        "unit": (
            f"ms p95 hello-to-programmed-route under {loss:.0%} seeded "
            f"KvStore RPC loss ({summary['nodes']}-node line emulator, "
            f"{summary['flaps']} flap batches, chaos seed {seed})"
        ),
        "vs_baseline": 0.0,
        "baseline": "none",
        "chaos_loss": loss,
        "chaos_seed": seed,
        "chaos_kv_dropped": summary["chaos_kv_dropped"],
        "spans": summary["spans_total"],
        "clean_e2e_p95_ms": round(baseline_p95, 2),
    }


def _reexec_degraded(fault_kind: str) -> int:
    """Re-run this bench in a fresh process pinned to JAX_PLATFORMS=cpu.

    The supervisor's breaker semantics, applied to the bench harness: a
    dead backend DEGRADES — the run re-executes on the CPU oracle platform
    and reports `"degraded": true` — it never exits nonzero. A fresh
    process is required because jax caches a failed backend discovery
    in-process (the same reason _probe_backend probes out-of-process)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_DEGRADED"] = fault_kind
    env.pop("BENCH_FAULT", None)  # the injected fault is TPU-side only
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)], env=env, timeout=3600
    )
    return proc.returncode


def main(argv=None) -> None:
    if os.environ.get("BENCH_SMOKE") == "1":
        _apply_smoke_env()
    degraded_reason = os.environ.get("BENCH_DEGRADED")
    if degraded_reason:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _apply_reduced_env()
    backend = _probe_backend()
    topo = os.environ.get("BENCH_TOPO", "wan")
    try:
        # deterministic fault seam (tests/test_benchmarks.py): a dead
        # backend that slips past the subprocess probe — the BENCH_r02-r05
        # failure mode, where jax.devices() raised mid-workload
        fault = os.environ.get("BENCH_FAULT")
        if fault:
            raise RuntimeError(
                f"injected bench fault: {fault} "
                "(UNAVAILABLE: TPU backend setup/compile error)"
            )
        results = [bench_grid() if topo == "grid" else bench_wan()]
        if os.environ.get("BENCH_CONVERGENCE", "1") == "1":
            results.append(_bench_convergence())
        if os.environ.get("BENCH_TE", "1") == "1":
            results.append(_bench_te())
        if os.environ.get("BENCH_SCALE", "1") == "1":
            results.append(_bench_scale())
        if os.environ.get("BENCH_EXPORTER", "1") == "1":
            results.append(_bench_exporter())
        if (
            os.environ.get("BENCH_STREAM", "1") == "1"
            and os.environ.get("BENCH_CONVERGENCE", "1") == "1"
        ):
            # defined against the convergence flap batch: without the
            # baseline run there is no held-flat comparison to make
            results.append(_bench_stream())
        if os.environ.get("BENCH_APSP", "1") == "1":
            results.append(_bench_apsp())
        if (
            os.environ.get("BENCH_FLEET", "1") == "1"
            and os.environ.get("BENCH_CONVERGENCE", "1") == "1"
        ):
            # defined against the convergence flap batch: the detached
            # baseline p95 is the held-flat comparison
            results.append(_bench_fleet())
        if (
            os.environ.get("BENCH_JOURNAL", "1") == "1"
            and os.environ.get("BENCH_CONVERGENCE", "1") == "1"
        ):
            # defined against the convergence flap batch: the journal-off
            # baseline p95 is the held-flat comparison
            results.append(_bench_journal())
        if (
            os.environ.get("BENCH_LOSS", "1") == "1"
            and os.environ.get("BENCH_CONVERGENCE", "1") == "1"
        ):
            # defined against the convergence flap batch: the lossless
            # baseline p95 anchors the bounded-degradation envelope
            results.append(_bench_convergence_under_loss())
    except Exception as exc:
        # route the failure through the solver fault domain's vocabulary:
        # classify, then degrade exactly like the supervisor's breaker
        # (serve from CPU), never raise on a TPU-less host
        from openr_tpu.solver.supervisor import classify_solver_error

        kind = classify_solver_error(exc)
        _note(f"bench workload failed ({kind}): {exc!r}")
        if degraded_reason or backend != "native":
            # already degraded (probe fallback or a re-exec child): a CPU
            # failure is genuine bitrot and must fail loudly
            raise
        _note("degrading: re-running on JAX_PLATFORMS=cpu in a fresh process")
        sys.exit(_reexec_degraded(kind))
    if backend != "native" or degraded_reason:
        # a fallback run measures a reduced workload on the wrong hardware:
        # mark every line so BENCH consumers treat them as availability
        # signals, never as perf regressions (tests/test_benchmarks.py
        # enforces the contract)
        for result in results:
            result["backend"] = "cpu-fallback"
            result["degraded"] = True
            if degraded_reason:
                result["fault_kind"] = degraded_reason
    from openr_tpu.utils.build_info import (
        ARTIFACT_SCHEMA_VERSION,
        build_fingerprint,
    )

    fingerprint = build_fingerprint()
    for result in results:
        # artifact provenance stamp: BENCH_r* consumers trace every line
        # to the exact code + field contract that produced it
        result["schema_version"] = ARTIFACT_SCHEMA_VERSION
        result["build"] = fingerprint
        print(json.dumps(result))


if __name__ == "__main__":
    main()
